"""SKY009: donation discipline on jitted dispatches.

`donate_argnums` hands a buffer to XLA: after the dispatch the
caller's array is INVALID (its memory backs the output). The engine
leans on this everywhere — every decode/prefill dispatch donates the
KV cache so XLA updates in place instead of copying gigabytes per
token — which makes two mistakes easy and catastrophic:

  1. USE AFTER DONATION: referencing the donated argument after the
     dispatch instead of rebinding the result in the same statement
     (`self.cache, out = fn(self.params, self.cache, ...)` is the
     contract; a later `self.cache` load on the old binding reads
     freed memory or a deleted-buffer error, but only on real TPUs —
     CPU tests never catch it because donation is a no-op there).
  2. UNPINNED DONATING DISPATCH: inside the engine (any class that
     defines `_pin_cache_out`), a new donating jit that omits the
     `**self._pin_cache_out(...)` splat (or an explicit
     `out_shardings=`) lets GSPMD reshard the donated pool, silently
     inserting a collective on the hot path (the exact drift the
     PR 15 compiled-HLO guard pinned down).

The checker tracks donating callables interprocedurally within the
module: decorated defs (`@functools.partial(jax.jit,
donate_argnums=...)`), `jax.jit(f, donate_argnums=...)` assignments,
factory methods that RETURN a donating function (directly, via a
cached `self._fns[key]` dict, or by calling another factory), and
instance attributes bound to factory results (including the
`a if cond else b` form). A dispatch through any of these is checked:
each donated positional argument must be rebound by the dispatch
statement itself, or never referenced afterwards in that function.
"""
from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from skypilot_tpu.analysis import callgraph, core

Positions = FrozenSet[int]


def _jit_call_info(call: ast.Call) -> Optional[Tuple[Positions, bool]]:
    """(donated positions, has out-sharding pin) if `call` creates a
    jitted function with donate_argnums, else None.

    Handles `jax.jit(...)` and `functools.partial(jax.jit, ...)`.
    `donate_argnums=(0,) if donate else ()` counts as donating (the
    True branch is the shipped configuration)."""
    name = core.dotted_name(call.func)
    if name is None:
        return None
    leaf = name.split('.')[-1]
    if leaf == 'partial':
        if not call.args:
            return None
        inner = core.dotted_name(call.args[0])
        if inner is None or inner.split('.')[-1] != 'jit':
            return None
    elif leaf != 'jit':
        return None
    donated: Set[int] = set()
    pinned = False
    for kw in call.keywords:
        if kw.arg == 'donate_argnums':
            donated |= _argnums(kw.value)
        elif kw.arg == 'out_shardings':
            pinned = True
        elif kw.arg is None:
            # **self._pin_cache_out(...) splat.
            if (isinstance(kw.value, ast.Call) and
                    isinstance(kw.value.func, ast.Attribute) and
                    kw.value.func.attr == '_pin_cache_out'):
                pinned = True
    if not donated:
        return None
    return frozenset(donated), pinned


def _argnums(node: ast.AST) -> Set[int]:
    if isinstance(node, ast.IfExp):
        return _argnums(node.body) | _argnums(node.orelse)
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and \
                    isinstance(elt.value, int):
                out.add(elt.value)
        return out
    return set()


def _ref(node: ast.AST) -> Optional[str]:
    """Stable dotted form of a rebindable reference (`self.cache`,
    `cache`); None for arbitrary expressions."""
    return core.dotted_name(node)


@core.register
class DonationChecker(core.Checker):
    rule = 'SKY009'
    name = 'donation-discipline'
    description = ('Arguments donated to a jitted dispatch must be '
                   'rebound, not referenced after; engine donating '
                   'jits must pin out-shardings.')
    version = 1

    @classmethod
    def applies_to(cls, path: str) -> bool:
        return not path.startswith('tests/')

    def check(self, tree: ast.Module) -> List[core.Finding]:
        graph = callgraph.build(tree, self.ctx.lines)
        self._graph = graph
        # Classes with a _pin_cache_out helper opt into the pin rule.
        self._pin_classes = {
            cls for cls, methods in graph.class_methods.items()
            if '_pin_cache_out' in methods}
        # qualname -> donated positions, for donating function DEFS.
        self._donating_defs: Dict[str, Positions] = {}
        # method qualname -> positions its return value donates.
        self._factories: Dict[str, Positions] = {}
        # (cls, attr) -> positions (instance attr bound to a factory
        # result, or a dict of donating fns: self._fns[k] = fn).
        self._attrs: Dict[Tuple[str, str], Positions] = {}
        self._collect_defs(graph)
        self._fixpoint(graph)
        for qual, info in graph.functions.items():
            self._check_function(graph, qual, info)
            self._check_pins(graph, info)
        return self.findings

    # -- collection -----------------------------------------------------------
    def _collect_defs(self, graph: callgraph.ModuleGraph) -> None:
        for qual, info in graph.functions.items():
            for dec in getattr(info.node, 'decorator_list', ()):
                if not isinstance(dec, ast.Call):
                    continue
                jit = _jit_call_info(dec)
                if jit is None:
                    continue
                positions, pinned = jit
                self._donating_defs[qual] = positions
                if info.cls in self._pin_classes and not pinned:
                    self.add(dec,
                             f'donating jit {info.name!r} omits the '
                             f'_pin_cache_out out-sharding pin; the '
                             f'donated pool layout can drift and '
                             f'GSPMD may insert a resharding '
                             f'collective on the dispatch')

    def _check_pins(self, graph: callgraph.ModuleGraph,
                    info: callgraph.FuncInfo) -> None:
        """The assignment-form counterpart of the decorator pin check:
        `self._fn = jax.jit(f, donate_argnums=...)` inside a pin-aware
        class needs the out-sharding pin too."""
        if info.cls not in self._pin_classes:
            return
        decs = {id(d) for d in getattr(info.node, 'decorator_list', ())}
        for node in graph.own_nodes(info.node):
            if not isinstance(node, ast.Call) or id(node) in decs:
                continue
            jit = _jit_call_info(node)
            if jit is None or jit[1]:
                continue
            self.add(node,
                     f'donating jit created in {info.qualname!r} '
                     f'omits the _pin_cache_out out-sharding pin; '
                     f'the donated pool layout can drift and GSPMD '
                     f'may insert a resharding collective on the '
                     f'dispatch')

    def _fixpoint(self, graph: callgraph.ModuleGraph) -> None:
        """Propagate donating-ness through factories, cached-fn dict
        attrs, and instance attributes until stable."""
        for _ in range(10):
            changed = False
            for qual, info in graph.functions.items():
                local = self._locals_of(graph, qual, info)
                # self._fns[key] = <donating local> / factory attr.
                for node in graph.own_nodes(info.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    positions = self._value_positions(
                        graph, info, local, node.value)
                    if positions is None:
                        continue
                    for target in node.targets:
                        key = self._attr_key(info, target)
                        if key is not None and \
                                self._attrs.get(key) != positions:
                            self._attrs[key] = positions
                            changed = True
                # return <donating thing> -> factory.
                for node in graph.own_nodes(info.node):
                    if not isinstance(node, ast.Return) or \
                            node.value is None:
                        continue
                    positions = self._value_positions(
                        graph, info, local, node.value)
                    if positions is not None and \
                            self._factories.get(qual) != positions:
                        self._factories[qual] = positions
                        changed = True
            if not changed:
                return

    def _attr_key(self, info: callgraph.FuncInfo,
                  target: ast.AST) -> Optional[Tuple[str, str]]:
        """(cls, attr) for `self.x = ...` / `self.x[k] = ...`."""
        if info.cls is None:
            return None
        if isinstance(target, ast.Subscript):
            target = target.value
        if (isinstance(target, ast.Attribute) and
                isinstance(target.value, ast.Name) and
                target.value.id == 'self'):
            return (info.cls, target.attr)
        return None

    def _value_positions(self, graph: callgraph.ModuleGraph,
                         info: callgraph.FuncInfo,
                         local: Dict[str, Positions],
                         value: ast.AST) -> Optional[Positions]:
        """Donated positions of the callable `value` evaluates to,
        or None if it is not a known donating callable."""
        if isinstance(value, ast.IfExp):
            a = self._value_positions(graph, info, local, value.body)
            b = self._value_positions(graph, info, local, value.orelse)
            if a is None and b is None:
                return None
            return (a or frozenset()) | (b or frozenset())
        if isinstance(value, ast.Name):
            if value.id in local:
                return local[value.id]
            qual = graph.resolve_callee(info, value)
            if qual is not None:
                return self._donating_defs.get(qual)
            return None
        if isinstance(value, ast.Call):
            jit = _jit_call_info(value)
            if jit is not None:
                return jit[0]
            qual = graph.resolve_callee(info, value.func)
            if qual is not None:
                return self._factories.get(qual)
            return None
        if isinstance(value, ast.Subscript):
            key = self._attr_key(info, value)
            if key is not None:
                return self._attrs.get(key)
            return None
        if isinstance(value, ast.Attribute):
            key = self._attr_key(info, value)
            if key is not None:
                return self._attrs.get(key)
        return None

    def _locals_of(self, graph: callgraph.ModuleGraph, qual: str,
                   info: callgraph.FuncInfo) -> Dict[str, Positions]:
        """Local names bound to donating callables in `qual`'s body:
        nested donating defs, `x = jax.jit(...)`, `fn =
        self._factory(...)`, `fn = self._fns[k]`."""
        local: Dict[str, Positions] = {}
        for child_qual, child in graph.functions.items():
            if child.parent == qual and \
                    child_qual in self._donating_defs:
                local[child.name] = self._donating_defs[child_qual]
        # Two passes so `a = jit(...); b = a` resolves.
        for _ in range(2):
            for node in graph.own_nodes(info.node):
                if not isinstance(node, ast.Assign):
                    continue
                positions = self._value_positions(
                    graph, info, local, node.value)
                if positions is None:
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        local[target.id] = positions
        return local

    # -- dispatch checking ----------------------------------------------------
    def _check_function(self, graph: callgraph.ModuleGraph, qual: str,
                        info: callgraph.FuncInfo) -> None:
        local = self._locals_of(graph, qual, info)
        for stmt in graph.own_nodes(info.node):
            if not isinstance(stmt, (ast.Assign, ast.AugAssign,
                                     ast.Expr)):
                continue
            for call in ast.walk(stmt):
                if not isinstance(call, ast.Call):
                    continue
                positions = self._dispatch_positions(
                    graph, info, local, call)
                if positions is None:
                    continue
                self._check_dispatch(graph, info, stmt, call,
                                     positions)

    def _dispatch_positions(self, graph: callgraph.ModuleGraph,
                            info: callgraph.FuncInfo,
                            local: Dict[str, Positions],
                            call: ast.Call) -> Optional[Positions]:
        func = call.func
        if isinstance(func, ast.Name):
            if func.id in local:
                return local[func.id]
            qual = graph.resolve_callee(info, func)
            if qual is not None:
                return self._donating_defs.get(qual)
            return None
        if isinstance(func, ast.Attribute):
            key = self._attr_key(info, func)
            if key is not None:
                return self._attrs.get(key)
        if isinstance(func, ast.Subscript):
            key = self._attr_key(info, func)
            if key is not None:
                return self._attrs.get(key)
        return None

    def _check_dispatch(self, graph: callgraph.ModuleGraph,
                        info: callgraph.FuncInfo, stmt: ast.stmt,
                        call: ast.Call,
                        positions: Positions) -> None:
        fn_name = core.dotted_name(call.func) or '<fn>'
        rebound: Set[str] = set()
        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                self._collect_refs(target, rebound)
        elif isinstance(stmt, ast.AugAssign):
            self._collect_refs(stmt.target, rebound)
        for pos in sorted(positions):
            if pos >= len(call.args):
                continue
            ref = _ref(call.args[pos])
            if ref is None or ref in rebound:
                continue
            use = self._first_later_use(graph, info, ref,
                                        stmt.end_lineno or stmt.lineno)
            if use is not None:
                self.add(use,
                         f'{ref} is referenced after being donated '
                         f'to {fn_name} (donate_argnums position '
                         f'{pos}, dispatched at line {call.lineno}); '
                         f'rebind the dispatch result in the same '
                         f'statement — the donated buffer is invalid '
                         f'after dispatch')

    @staticmethod
    def _collect_refs(target: ast.AST, out: Set[str]) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                DonationChecker._collect_refs(elt, out)
            return
        if isinstance(target, ast.Starred):
            target = target.value
        ref = _ref(target)
        if ref is not None:
            out.add(ref)

    def _first_later_use(self, graph: callgraph.ModuleGraph,
                         info: callgraph.FuncInfo, ref: str,
                         after_line: int) -> Optional[ast.AST]:
        best: Optional[ast.AST] = None
        for node in graph.own_nodes(info.node):
            if not isinstance(node, (ast.Name, ast.Attribute)):
                continue
            if not isinstance(getattr(node, 'ctx', None), ast.Load):
                continue
            if node.lineno <= after_line:
                continue
            if core.dotted_name(node) != ref:
                continue
            if best is None or (node.lineno, node.col_offset) < \
                    (best.lineno, best.col_offset):
                best = node
        return best
