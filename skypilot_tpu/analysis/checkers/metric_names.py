"""SKY004: metric-name hygiene, at the AST level.

Every Prometheus metric this codebase exports is declared once in
`observability/catalog.py` (SPECS). PR 2 enforced that with a
string-level CI checker; this rule promotes it to the AST so that
DYNAMICALLY BUILT names — f-strings, concatenation, variables passed
to `counter()`/`gauge()`/`histogram()`/`get_or_create()` — are caught
too, not just misspelled literals.

Import tracking keeps it precise: bare `counter(...)` is only policed
when the file imported it from the catalog, `m.Counter(...)` only when
`m` is the observability.metrics module, and `.get_or_create(...)`
only on receivers that look like a registry. `collections.Counter`
never trips it.

Catalog keys are read by PARSING catalog.py (no import): the linter
stays runnable on a tree that does not import.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Optional, Set

from skypilot_tpu.analysis import core

# The declaration points themselves build names from variables.
_EXEMPT_FILES = ('observability/catalog.py', 'observability/metrics.py')

_CATALOG_MOD = 'skypilot_tpu.observability.catalog'
_METRICS_MOD = 'skypilot_tpu.observability.metrics'
_FACTORIES = {'counter', 'gauge', 'histogram'}
_CLASSES = {'Counter', 'Gauge', 'Histogram'}

_catalog_cache: Optional[Set[str]] = None


def catalog_names(catalog_path: Optional[str] = None) -> Set[str]:
    """SPECS keys parsed from observability/catalog.py's AST."""
    global _catalog_cache
    if catalog_path is None and _catalog_cache is not None:
        return _catalog_cache
    path = catalog_path or os.path.join(core._PKG_DIR, 'observability',
                                        'catalog.py')
    names: Set[str] = set()
    try:
        with open(path, 'r', encoding='utf-8') as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return names
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.AnnAssign):
            target, value = node.target, node.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        else:
            continue
        if (isinstance(target, ast.Name) and target.id == 'SPECS' and
                isinstance(value, ast.Dict)):
            for key in value.keys:
                if isinstance(key, ast.Constant) and \
                        isinstance(key.value, str):
                    names.add(key.value)
    if catalog_path is None:
        _catalog_cache = names
    return names


@core.register
class MetricNameChecker(core.Checker):
    rule = 'SKY004'
    name = 'metric-name-hygiene'
    description = ('Metric names must be literals declared in '
                   'observability/catalog.py (no dynamic names).')

    def __init__(self, ctx: core.FileContext) -> None:
        super().__init__(ctx)
        # local alias -> ('factory'|'class'|'catalog'|'metrics')
        self._aliases: Dict[str, str] = {}

    @classmethod
    def applies_to(cls, path: str) -> bool:
        return not path.endswith(_EXEMPT_FILES)

    # -- import tracking ----------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            local = alias.asname or alias.name.split('.')[0]
            if alias.name == _CATALOG_MOD and alias.asname:
                self._aliases[local] = 'catalog'
            elif alias.name == _METRICS_MOD and alias.asname:
                self._aliases[local] = 'metrics'

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ''
        for alias in node.names:
            local = alias.asname or alias.name
            if mod == _CATALOG_MOD and alias.name in _FACTORIES:
                self._aliases[local] = 'factory'
            elif mod == _METRICS_MOD and alias.name in _CLASSES:
                self._aliases[local] = 'class'
            elif mod.endswith('observability') and \
                    alias.name == 'catalog':
                self._aliases[local] = 'catalog'
            elif mod.endswith('observability') and \
                    alias.name == 'metrics':
                self._aliases[local] = 'metrics'

    # -- the check ----------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        spec = self._name_arg_spec(node)
        if spec is not None:
            func_label, arg_idx = spec
            self._check_name_arg(node, func_label, arg_idx)
        self.generic_visit(node)

    def _name_arg_spec(self, node: ast.Call):
        """-> (label, name-arg index) when this call takes a metric
        name we should police, else None."""
        func = node.func
        if isinstance(func, ast.Name):
            kind = self._aliases.get(func.id)
            if kind == 'factory' and func.id in _FACTORIES:
                return func.id, 0
            if kind == 'class' and func.id in _CLASSES:
                return func.id, 0
            return None
        if isinstance(func, ast.Attribute):
            recv = core.dotted_name(func.value)
            if recv is not None:
                kind = self._aliases.get(recv.split('.')[0])
                if kind == 'catalog' and func.attr in _FACTORIES:
                    return f'{recv}.{func.attr}', 0
                if kind == 'metrics' and func.attr in _CLASSES:
                    return f'{recv}.{func.attr}', 0
            if func.attr == 'get_or_create' and recv is not None and \
                    'registr' in recv.lower():
                return f'{recv}.get_or_create', 1
        return None

    def _check_name_arg(self, node: ast.Call, func: str,
                        arg_idx: int) -> None:
        arg: Optional[ast.AST] = None
        if len(node.args) > arg_idx:
            arg = node.args[arg_idx]
        else:
            for kw in node.keywords:
                if kw.arg == 'name':
                    arg = kw.value
        if arg is None:
            return
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value not in catalog_names():
                self.add(node,
                         f'metric name {arg.value!r} is not declared '
                         f'in observability/catalog.py SPECS')
            return
        self.add(node,
                 f'{func}() called with a dynamically built metric '
                 f'name; declare a literal from '
                 f'observability/catalog.py instead')
