"""SKY002: jit-purity / retrace hazards inside jitted functions.

Python side effects inside `jax.jit`/`pjit`/`shard_map`-wrapped
functions either crash at trace time (`.item()`, `float()` on a
tracer), silently run once per TRACE instead of once per CALL
(`print`, global/attribute mutation), or force retraces that cap
throughput (the concurrency ceiling: one retrace stalls every queued
dispatch). The rule book:

  - `.item()` / `float(arg)` / `int(arg)` / `bool(arg)` / `np.*(arg)`
    on a traced argument: concretization — host sync or TracerError.
  - `print(...)`: runs at trace time only; use `jax.debug.print`.
  - `global` statements and writes to `self.*`/module attributes:
    side effects invisible to the tracer (stale after the first call).
  - `static_argnums`/`static_argnames` given a set/dict literal:
    static args must be hashable, and the spec is an int/str sequence.

A function counts as jitted when decorated with jit/pjit/shard_map
(directly or through functools.partial), or when the module wraps it
by name: `step = jax.jit(step_fn, ...)`.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from skypilot_tpu.analysis import core

_JIT_NAMES = {'jit', 'pjit', 'shard_map'}
_CONCRETIZERS = {'float', 'int', 'bool'}
_NUMPY_ROOTS = {'np', 'numpy', 'onp'}


def _is_jit_expr(node: ast.AST) -> bool:
    """`jax.jit` / `pjit` / `shard_map` / partial(jax.jit, ...) /
    jax.jit(...)-with-options, as a decorator or wrapper callee."""
    name = core.dotted_name(node)
    if name is not None:
        return name.split('.')[-1] in _JIT_NAMES
    if isinstance(node, ast.Call):
        callee = core.dotted_name(node.func)
        if callee is not None and callee.split('.')[-1] == 'partial':
            return bool(node.args) and _is_jit_expr(node.args[0])
        # jax.jit(static_argnums=...) used as a decorator factory.
        return _is_jit_expr(node.func)
    return False


class _BodyScan(ast.NodeVisitor):
    """Scans one jitted function body, nested closures included
    (inner defs trace together with the parent frame)."""

    def __init__(self, checker: 'JitPurityChecker',
                 fn: ast.AST, params: Set[str]) -> None:
        self.checker = checker
        self.fn = fn
        self.params = set(params)
        self.locals: Set[str] = set(params)
        self._depth = 0

    # Nested function defs: their bodies trace too (closures inside a
    # jitted step), so keep visiting — but track locals per frame is
    # overkill; tolerate the small chance of FP there.
    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._check_store(target, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_store(node.target, node)
        self.generic_visit(node)

    def _check_store(self, target: ast.AST, node: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.locals.add(target.id)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_store(elt, node)
            return
        if isinstance(target, ast.Attribute):
            root = target
            while isinstance(root, (ast.Attribute, ast.Subscript)):
                root = root.value
            if isinstance(root, ast.Name):
                if root.id == 'self' or root.id not in self.locals:
                    self.checker.add(
                        node,
                        f'attribute mutation '
                        f'{core.dotted_name(target) or root.id + ".*"}'
                        f' inside jitted function '
                        f'{getattr(self.fn, "name", "<lambda>")}: side '
                        f'effect runs at trace time only')

    def visit_Global(self, node: ast.Global) -> None:
        self.checker.add(
            node, f'global statement inside jitted function '
                  f'{getattr(self.fn, "name", "<lambda>")}: mutation '
                  f'is a trace-time side effect')

    def visit_Call(self, node: ast.Call) -> None:
        name = core.dotted_name(node.func)
        if name == 'print':
            self.checker.add(
                node, 'print() inside a jitted function runs at trace '
                      'time only; use jax.debug.print()')
        elif (isinstance(node.func, ast.Attribute) and
              node.func.attr == 'item'):
            self.checker.add(
                node, '.item() inside a jitted function concretizes a '
                      'traced value (TracerError / host sync)')
        elif (name in _CONCRETIZERS and len(node.args) == 1 and
              isinstance(node.args[0], ast.Name) and
              node.args[0].id in self.params):
            self.checker.add(
                node, f'{name}() on traced argument '
                      f'{node.args[0].id!r} concretizes it; hoist out '
                      f'of the jitted function or mark it static')
        elif name is not None and name.split('.')[0] in _NUMPY_ROOTS:
            if any(isinstance(a, ast.Name) and a.id in self.params
                   for a in node.args):
                self.checker.add(
                    node, f'{name}() on a traced argument runs on host '
                          f'at trace time; use jnp instead')
        self.generic_visit(node)


@core.register
class JitPurityChecker(core.Checker):
    rule = 'SKY002'
    name = 'jit-purity'
    description = ('Side effects / concretization / retrace hazards '
                   'inside jax.jit|pjit|shard_map functions.')

    def check(self, tree: ast.Module) -> List[core.Finding]:
        defs: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node
        scanned: Set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_jit_expr(d) for d in node.decorator_list):
                    self._scan(node, scanned)
            elif isinstance(node, ast.Call) and _is_jit_expr(node):
                # Covers decorator calls too: ast.walk reaches every
                # Call node, including those in decorator_list.
                self._check_static_argnums(node)
                # Wrapper form: step = jax.jit(step_fn, ...)
                if node.args:
                    target = node.args[0]
                    fn = None
                    if isinstance(target, ast.Name):
                        fn = defs.get(target.id)
                    elif isinstance(target, ast.Lambda):
                        fn = target
                    if fn is not None:
                        self._scan(fn, scanned)
        return self.findings

    def _scan(self, fn: ast.AST, scanned: Set[int]) -> None:
        if id(fn) in scanned:
            return
        scanned.add(id(fn))
        params: Set[str] = set()
        args = fn.args
        for a in (list(args.posonlyargs) + list(args.args) +
                  list(args.kwonlyargs)):
            params.add(a.arg)
        if args.vararg:
            params.add(args.vararg.arg)
        if args.kwarg:
            params.add(args.kwarg.arg)
        params.discard('self')
        scan = _BodyScan(self, fn, params)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            scan.visit(stmt)

    def _check_static_argnums(self, node: ast.AST) -> None:
        if not isinstance(node, ast.Call) or not _is_jit_expr(node):
            return
        for kw in node.keywords:
            if kw.arg in ('static_argnums', 'static_argnames'):
                if isinstance(kw.value, (ast.Set, ast.Dict)):
                    self.add(kw.value,
                             f'{kw.arg} takes an int/str sequence; a '
                             f'{type(kw.value).__name__.lower()} '
                             f'literal is unhashable/unordered')
