"""SKY003: lock discipline on classes that declare a lock.

A class that creates a `threading.Lock`/`RLock`/`Condition` (or
`asyncio.Lock`) has announced that its instance state is shared.
Every method that MUTATES state assigned in `__init__` must then hold
one of the class's locks — a method that writes `self.x` or calls
`self.queue.append(...)` without `with self._lock:` is a data race
waiting for load (the serving engine's batching plane and the agent's
exec table are exactly where these bite).

Conventions honored to keep noise down:
  - `__init__`/`__new__`/`__del__` and `_locked`-suffixed methods are
    exempt (construction happens-before sharing; `*_locked` documents
    "caller holds the lock").
  - a method that acquires ANY declared lock anywhere in its body is
    considered disciplined (granularity is method-level on purpose —
    the goal is catching methods nobody ever thought about locking).
  - only attributes assigned in `__init__` count as shared state.
  - attributes with a declared thread OWNER (`_STPU_OWNERS` /
    `# stpu: owner[...]` — see analysis/callgraph.py) are exempt:
    ownership is their synchronization story, and SKY008 verifies it
    against the call graph instead of asking for a lock.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from skypilot_tpu.analysis import callgraph, core

_LOCK_TYPES = {'Lock', 'RLock', 'Condition', 'Semaphore',
               'BoundedSemaphore'}
_MUTATORS = {'append', 'appendleft', 'extend', 'extendleft', 'insert',
             'pop', 'popleft', 'popitem', 'remove', 'discard', 'clear',
             'add', 'update', 'setdefault', 'sort', 'reverse'}
_EXEMPT_METHODS = {'__init__', '__new__', '__del__', '__post_init__'}


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for a bare `self.x` expression, else None."""
    if (isinstance(node, ast.Attribute) and
            isinstance(node.value, ast.Name) and
            node.value.id == 'self'):
        return node.attr
    return None


class _ClassScan:

    def __init__(self, checker: 'LockDisciplineChecker',
                 node: ast.ClassDef) -> None:
        self.checker = checker
        self.node = node
        self.locks: Set[str] = set()
        self.shared: Set[str] = set()

    def run(self) -> None:
        methods = [n for n in self.node.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        for m in methods:
            self._collect_attrs(m)
        if not self.locks:
            return
        self.shared -= self.locks
        # Owner-declared attrs answer to SKY008's call-graph check,
        # not lock discipline.
        self.shared -= set(callgraph.class_owned_attrs(
            self.node, self.checker.ctx.lines))
        for m in methods:
            if (m.name in _EXEMPT_METHODS or
                    m.name.endswith('_locked')):
                continue
            if self._acquires_lock(m):
                continue
            self._flag_mutations(m)

    def _collect_attrs(self, method: ast.AST) -> None:
        init = method.name == '__init__'
        for node in ast.walk(method):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    attr = _self_attr(target)
                    if attr is None:
                        continue
                    name = core.dotted_name(node.value.func) \
                        if isinstance(node.value, ast.Call) else None
                    if (name is not None and
                            name.split('.')[-1] in _LOCK_TYPES):
                        self.locks.add(attr)
                    elif init:
                        self.shared.add(attr)

    def _acquires_lock(self, method: ast.AST) -> bool:
        for node in ast.walk(method):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    expr = item.context_expr
                    # with self._lock:  |  with self._cv:
                    if _self_attr(expr) in self.locks:
                        return True
                    # with self._lock.something(...) (Condition waits)
                    if (isinstance(expr, ast.Call) and
                            isinstance(expr.func, ast.Attribute) and
                            _self_attr(expr.func.value) in self.locks):
                        return True
            if isinstance(node, ast.Call):
                # self._lock.acquire()
                if (isinstance(node.func, ast.Attribute) and
                        node.func.attr in ('acquire', 'wait',
                                           'notify', 'notify_all') and
                        _self_attr(node.func.value) in self.locks):
                    return True
        return False

    def _flag_mutations(self, method: ast.AST) -> None:
        lock_names = ', '.join(f'self.{l}' for l in sorted(self.locks))
        for node in ast.walk(method):
            target_attr = None
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    target_attr = target_attr or self._store_attr(target)
            elif isinstance(node, ast.AugAssign):
                target_attr = self._store_attr(node.target)
            elif isinstance(node, ast.Call):
                # self.queue.append(...) and friends
                if (isinstance(node.func, ast.Attribute) and
                        node.func.attr in _MUTATORS):
                    target_attr = _self_attr(node.func.value)
                    if target_attr not in self.shared:
                        target_attr = None
            if target_attr is not None:
                self.checker.add(
                    node,
                    f'{self.node.name}.{method.name} mutates shared '
                    f'attribute self.{target_attr} without holding '
                    f'{lock_names}')

    def _store_attr(self, target: ast.AST) -> Optional[str]:
        """Shared attr written by an assignment target (also catches
        `self.x[k] = v` subscript stores)."""
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                attr = self._store_attr(elt)
                if attr is not None:
                    return attr
            return None
        if isinstance(target, ast.Subscript):
            target = target.value
        attr = _self_attr(target)
        if attr is not None and attr in self.shared:
            return attr
        return None


@core.register
class LockDisciplineChecker(core.Checker):
    rule = 'SKY003'
    name = 'lock-discipline'
    description = ('Classes declaring a Lock must hold it in methods '
                   'that mutate shared instance state.')

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        _ClassScan(self, node).run()
        # Nested classes still get their own scan.
        self.generic_visit(node)
