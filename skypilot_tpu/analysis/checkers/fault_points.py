"""SKY010: fault-point drift — fire sites vs catalog vs docs.

The fault-injection registry (robustness/faults.py) is a CLOSED
catalog: `install_plan` rejects plans naming unknown points, and the
operator-facing point table in docs/internals.md §11 is the contract
chaos plans are written against. That closure only holds if the three
surfaces stay in sync, so this rule (the SKY004 catalog pattern
promoted to the robustness layer) checks:

  - every `faults.point(name, ...)` fire site names a KNOWN_POINTS
    entry — a typo'd point silently never fires, which is the worst
    failure mode a chaos harness can have;
  - every fire-site name appears in the internals §11 table — an
    undocumented point can't be targeted by anyone reading the docs;
  - when visiting faults.py itself: KNOWN_POINTS and the §11 table
    agree in BOTH directions (catalog entry missing from the docs,
    or a documented point the catalog no longer declares);
  - fire-site names must be string literals — a dynamic name defeats
    the closed-catalog property.

Coverage (every non-derived point has at least one live fire site)
is asserted by tests/unit_tests/test_static_analysis.py rather than
here, because it is a whole-repo property, not a per-file one.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Optional, Set

from skypilot_tpu.analysis import core

_FAULTS_REL = 'skypilot_tpu/robustness/faults.py'
_DOCS_REL = 'docs/internals.md'

# Derived points are plan-level sugar with, by design, no call site.
DERIVED_POINTS = {'jobs.preempt_storm'}

_ROW_RE = re.compile(r'^\|\s*`([A-Za-z0-9_.]+)`\s*\|')

_known_cache: Optional[Dict[str, int]] = None
_docs_cache: object = False           # False = not loaded yet


def known_points() -> Dict[str, int]:
    """KNOWN_POINTS keys -> declaration line, parsed from faults.py
    WITHOUT importing it (same trick as SKY004's catalog_names)."""
    global _known_cache
    if _known_cache is not None:
        return _known_cache
    out: Dict[str, int] = {}
    path = os.path.join(core.REPO_ROOT, _FAULTS_REL)
    try:
        with open(path, 'r', encoding='utf-8') as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        _known_cache = {}
        return _known_cache
    for node in ast.walk(tree):
        if (isinstance(node, (ast.Assign, ast.AnnAssign)) and
                isinstance(node.value, ast.Dict)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if not any(isinstance(t, ast.Name) and
                       t.id == 'KNOWN_POINTS' for t in targets):
                continue
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str):
                    out[k.value] = k.lineno
    _known_cache = out
    return out


def documented_points() -> Optional[Set[str]]:
    """Point names in the internals.md §11 table (None if the docs
    file is missing — doc checks are skipped, not spammed)."""
    global _docs_cache
    if _docs_cache is not False:
        return _docs_cache
    path = os.path.join(core.REPO_ROOT, _DOCS_REL)
    if not os.path.exists(path):
        _docs_cache = None
        return None
    out: Set[str] = set()
    in_section = False
    with open(path, 'r', encoding='utf-8') as f:
        for line in f:
            if line.startswith('## '):
                in_section = 'Fault injection' in line
                continue
            if not in_section:
                continue
            m = _ROW_RE.match(line)
            if m:
                out.add(m.group(1))
    _docs_cache = out
    return out


def reset_caches() -> None:
    """Test hook."""
    global _known_cache, _docs_cache
    _known_cache = None
    _docs_cache = False


@core.register
class FaultPointChecker(core.Checker):
    rule = 'SKY010'
    name = 'fault-point-drift'
    description = ('faults.point() fire sites, KNOWN_POINTS, and the '
                   'internals §11 table must agree.')
    version = 1

    @classmethod
    def applies_to(cls, path: str) -> bool:
        return not path.startswith('tests/')

    def __init__(self, ctx: core.FileContext) -> None:
        super().__init__(ctx)
        self._module_aliases: Set[str] = set()
        self._func_aliases: Set[str] = set()

    # -- import tracking (mirrors SKY004) ------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name.endswith('faults'):
                self._module_aliases.add(
                    alias.asname or alias.name.split('.')[0])
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ''
        for alias in node.names:
            if alias.name == 'faults' and mod.endswith('robustness'):
                self._module_aliases.add(alias.asname or 'faults')
            elif alias.name == 'point' and mod.endswith('faults'):
                self._func_aliases.add(alias.asname or 'point')
        self.generic_visit(node)

    # -- fire sites -----------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        if self._is_point_call(node) and node.args:
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant) and
                    isinstance(arg.value, str)):
                self.add(node,
                         'faults.point() name must be a string '
                         'literal — a dynamic name defeats the '
                         'closed catalog (install_plan validation '
                         'and the internals §11 table)')
            else:
                self._check_name(node, arg.value)
        self.generic_visit(node)

    def _is_point_call(self, node: ast.Call) -> bool:
        func = node.func
        if isinstance(func, ast.Name):
            return func.id in self._func_aliases
        if isinstance(func, ast.Attribute) and func.attr == 'point':
            base = core.dotted_name(func.value)
            return base in self._module_aliases
        return False

    def _check_name(self, node: ast.AST, name: str) -> None:
        known = known_points()
        if known and name not in known:
            self.add(node,
                     f'fault point {name!r} is not declared in '
                     f'KNOWN_POINTS ({_FAULTS_REL}) — this fire site '
                     f'can never be targeted by a plan')
            return
        docs = documented_points()
        if docs is not None and name not in docs:
            self.add(node,
                     f'fault point {name!r} is missing from the '
                     f'{_DOCS_REL} §11 point table — document the '
                     f'site and what a firing rule perturbs')

    # -- the declaration file itself -----------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_declaration(node, node.targets)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        # `KNOWN_POINTS: Dict[str, str] = {...}` is the real form.
        self._check_declaration(node, [node.target])
        self.generic_visit(node)

    def _check_declaration(self, node, targets) -> None:
        if (self.ctx.path == _FAULTS_REL and
                any(isinstance(t, ast.Name) and t.id == 'KNOWN_POINTS'
                    for t in targets) and
                isinstance(node.value, ast.Dict)):
            docs = documented_points()
            declared: Set[str] = set()
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str):
                    declared.add(k.value)
                    if docs is not None and k.value not in docs:
                        self.add(k,
                                 f'KNOWN_POINTS entry {k.value!r} is '
                                 f'missing from the {_DOCS_REL} §11 '
                                 f'point table')
            if docs is not None:
                for name in sorted(docs - declared):
                    self.add(node,
                             f'{_DOCS_REL} §11 documents fault point '
                             f'{name!r} that KNOWN_POINTS no longer '
                             f'declares — delete the stale row or '
                             f'restore the point')
