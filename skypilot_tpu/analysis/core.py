"""The `stpu check` lint framework: AST visitors, zero dependencies.

This is project-specific static analysis — the rules encode contracts
unit tests catch late or never (blocking calls stalling the asyncio
event loop, retrace hazards in jitted step functions, unlocked shared
state on the serving/scheduling hot paths, metric names drifting from
the catalog, control-plane exceptions vanishing without a log line).

Pieces:

  Finding            one (rule, path, line, col, message) diagnostic
  Checker            ast.NodeVisitor base; subclasses register with
                     @register and carry `rule` (SKYxxx) + description
  run_file/run_paths per-file runner: parse once, run every selected
                     checker, drop `# stpu: ignore[SKYxxx]` lines
  Baseline           committed grandfather list (analysis/baseline.json)
                     keyed (path, rule, line), each entry justified
  render_text/json   reporters for the CLI and the CI gate

Suppression: append `# stpu: ignore[SKY001]` (or a bare
`# stpu: ignore` for every rule) to the flagged line.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple, Type

# Repo root = the directory holding the `skypilot_tpu` package; paths
# in findings and the baseline are stored relative to it so runs from
# any cwd agree.
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(_PKG_DIR)
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                'baseline.json')

_SUPPRESS_RE = re.compile(
    r'#\s*stpu:\s*ignore(?:\[\s*([A-Za-z0-9_,\s]+?)\s*\])?')

_SKIP_DIRS = {'__pycache__', 'dashboard_static', 'node_modules',
              '.git', '.eggs'}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def key(self) -> Tuple[str, str, int]:
        return (self.path, self.rule, self.line)

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f'{self.path}:{self.line}:{self.col}: {self.rule} ' \
               f'{self.message}'


class FileContext:
    """Everything a checker may need about the file under analysis."""

    def __init__(self, path: str, source: str) -> None:
        self.abs_path = os.path.abspath(path)
        self.path = display_path(path)
        self.source = source
        self.lines = source.splitlines()


class Checker(ast.NodeVisitor):
    """Base class: subclass, set `rule`/`name`/`description`, override
    visit_* methods, call `self.add(node, message)` per diagnostic."""

    rule: str = 'SKY000'
    name: str = 'base'
    description: str = ''

    def __init__(self, ctx: FileContext) -> None:
        self.ctx = ctx
        self.findings: List[Finding] = []

    @classmethod
    def applies_to(cls, path: str) -> bool:
        """Override to scope a rule to a subtree (posix relpath in)."""
        del path
        return True

    def add(self, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            self.rule, self.ctx.path, getattr(node, 'lineno', 1),
            getattr(node, 'col_offset', 0), message))

    def check(self, tree: ast.Module) -> List[Finding]:
        self.visit(tree)
        return self.findings


_CHECKERS: Dict[str, Type[Checker]] = {}


def register(cls: Type[Checker]) -> Type[Checker]:
    if cls.rule in _CHECKERS:
        raise ValueError(f'duplicate checker rule {cls.rule}')
    _CHECKERS[cls.rule] = cls
    return cls


def all_checkers() -> Dict[str, Type[Checker]]:
    _load_builtin_checkers()
    return dict(_CHECKERS)


def _load_builtin_checkers() -> None:
    # Import side effect registers each @register'd class exactly once.
    from skypilot_tpu.analysis import checkers  # noqa: F401  pylint: disable=unused-import,cyclic-import


def resolve_select(select: Optional[str]) -> Set[str]:
    """`--select SKY001,SKY003` -> validated rule set (all if None)."""
    checkers = all_checkers()
    if not select:
        return set(checkers)
    rules = {r.strip().upper() for r in select.split(',') if r.strip()}
    unknown = rules - set(checkers)
    if unknown:
        raise ValueError(
            f'unknown rule(s) {sorted(unknown)}; available: '
            f'{sorted(checkers)}')
    return rules


def display_path(path: str) -> str:
    """Repo-relative posix path when under the repo, else as given."""
    abs_path = os.path.abspath(path)
    if abs_path.startswith(REPO_ROOT + os.sep):
        return os.path.relpath(abs_path, REPO_ROOT).replace(os.sep, '/')
    return path.replace(os.sep, '/')


def dotted_name(node: ast.AST) -> Optional[str]:
    """`a.b.c` for Name/Attribute chains, None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def suppressed_lines(source: str) -> Dict[int, Optional[Set[str]]]:
    """line -> suppressed rules on it (None = every rule)."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        if m.group(1) is None:
            out[i] = None
        else:
            out[i] = {r.strip().upper() for r in m.group(1).split(',')
                      if r.strip()}
    return out


def run_source(source: str, path: str,
               select: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run the (selected) checkers over one file's source text."""
    checkers = all_checkers()
    rules = set(select) if select is not None else set(checkers)
    rel = display_path(path)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding('SKY000', rel, e.lineno or 1, e.offset or 0,
                        f'syntax error: {e.msg}')]
    findings: List[Finding] = []
    for rule in sorted(rules):
        cls = checkers[rule]
        if not cls.applies_to(rel):
            continue
        findings.extend(cls(FileContext(path, source)).check(tree))
    suppressed = suppressed_lines(source)
    kept = []
    for f in sorted(findings, key=lambda f: (f.line, f.col, f.rule)):
        rules_here = suppressed.get(f.line, ...)
        if rules_here is None or (rules_here is not ... and
                                  f.rule in rules_here):
            continue
        kept.append(f)
    return kept


def run_file(path: str,
             select: Optional[Iterable[str]] = None) -> List[Finding]:
    with open(path, 'r', encoding='utf-8') as f:
        source = f.read()
    return run_source(source, path, select)


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS and
                                 not d.startswith('.'))
            for fname in sorted(filenames):
                if fname.endswith('.py'):
                    out.append(os.path.join(dirpath, fname))
    return out


def run_paths(paths: Sequence[str],
              select: Optional[Iterable[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(run_file(path, select))
    return sorted(findings,
                  key=lambda f: (f.path, f.line, f.col, f.rule))


# -- baseline ---------------------------------------------------------------
class Baseline:
    """Grandfathered findings: (path, rule, line) -> justification.

    Every entry must carry a non-empty justification — the baseline
    is for triaged FALSE positives, not a mute button."""

    def __init__(self, entries: Optional[List[Dict]] = None) -> None:
        self.entries = entries or []
        self._index: Dict[Tuple[str, str, int], Dict] = {}
        for e in self.entries:
            just = str(e.get('justification') or '').strip()
            if not just:
                raise ValueError(
                    f'baseline entry {e.get("path")}:{e.get("line")} '
                    f'{e.get("rule")} lacks a justification')
            self._index[(e['path'], e['rule'], int(e['line']))] = e

    @classmethod
    def load(cls, path: str) -> 'Baseline':
        if not os.path.exists(path):
            return cls([])
        with open(path, 'r', encoding='utf-8') as f:
            data = json.load(f)
        return cls(data.get('entries', []))

    def save(self, path: str) -> None:
        with open(path, 'w', encoding='utf-8') as f:
            json.dump({'version': 1, 'entries': self.entries}, f,
                      indent=2, sort_keys=False)
            f.write('\n')

    def contains(self, finding: Finding) -> bool:
        return finding.key() in self._index

    def split(self, findings: Sequence[Finding]
              ) -> Tuple[List[Finding], List[Finding]]:
        """-> (new findings, baselined findings)."""
        new, old = [], []
        for f in findings:
            (old if self.contains(f) else new).append(f)
        return new, old

    def stale_entries(self, findings: Sequence[Finding]) -> List[Dict]:
        """Entries matching no current finding — fixed code whose
        baseline row should be deleted."""
        live = {f.key() for f in findings}
        return [e for key, e in sorted(self._index.items())
                if key not in live]

    @classmethod
    def from_findings(cls, findings: Sequence[Finding],
                      justification: str) -> 'Baseline':
        return cls([{'rule': f.rule, 'path': f.path, 'line': f.line,
                     'message': f.message,
                     'justification': justification}
                    for f in findings])


# -- reporters --------------------------------------------------------------
def render_text(findings: Sequence[Finding],
                baselined: Sequence[Finding] = ()) -> str:
    lines = [f.render() for f in findings]
    n = len(findings)
    summary = f'{n} finding{"s" if n != 1 else ""}'
    if baselined:
        summary += f' ({len(baselined)} baselined, not shown)'
    lines.append(summary)
    return '\n'.join(lines)


def render_json(findings: Sequence[Finding],
                baselined: Sequence[Finding] = ()) -> str:
    return json.dumps({
        'version': 1,
        'count': len(findings),
        'baselined_count': len(baselined),
        'findings': [f.to_dict() for f in findings],
    }, indent=2)
