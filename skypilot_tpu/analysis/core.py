"""The `stpu check` lint framework: AST visitors, zero dependencies.

This is project-specific static analysis — the rules encode contracts
unit tests catch late or never (blocking calls stalling the asyncio
event loop, retrace hazards in jitted step functions, unlocked shared
state on the serving/scheduling hot paths, metric names drifting from
the catalog, control-plane exceptions vanishing without a log line).

Pieces:

  Finding            one (rule, path, line, col, message) diagnostic
  Checker            ast.NodeVisitor base; subclasses register with
                     @register and carry `rule` (SKYxxx) + description
  run_file/run_paths per-file runner: parse once, run every selected
                     checker, drop `# stpu: ignore[SKYxxx]` lines
  Baseline           committed grandfather list (analysis/baseline.json)
                     v2: keyed (path, rule, qualified symbol) so line
                     churn no longer invalidates rows; v1 line-keyed
                     entries still load. Every entry justified; a
                     `rule_versions` map invalidates a rule's rows
                     when the checker's logic version bumps.
  render_text/json   reporters for the CLI and the CI gate (JSON
                     carries per-rule wall-clock timings)

Suppression: append `# stpu: ignore[SKY001]` (or a bare
`# stpu: ignore` for every rule) to the flagged line.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import time
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple, Type

# Repo root = the directory holding the `skypilot_tpu` package; paths
# in findings and the baseline are stored relative to it so runs from
# any cwd agree.
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(_PKG_DIR)
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                'baseline.json')

_SUPPRESS_RE = re.compile(
    r'#\s*stpu:\s*ignore(?:\[\s*([A-Za-z0-9_,\s]+?)\s*\])?')

_SKIP_DIRS = {'__pycache__', 'dashboard_static', 'node_modules',
              '.git', '.eggs'}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    # Qualified name of the enclosing def ('Cls.method.inner'),
    # '<module>' at top level. Stamped by run_source; the v2 baseline
    # keys on it so findings survive line churn.
    symbol: str = '<module>'

    def key(self) -> Tuple[str, str, int]:
        return (self.path, self.rule, self.line)

    def symbol_key(self) -> Tuple[str, str, str]:
        return (self.path, self.rule, self.symbol)

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f'{self.path}:{self.line}:{self.col}: {self.rule} ' \
               f'{self.message}'


class FileContext:
    """Everything a checker may need about the file under analysis."""

    def __init__(self, path: str, source: str) -> None:
        self.abs_path = os.path.abspath(path)
        self.path = display_path(path)
        self.source = source
        self.lines = source.splitlines()


class Checker(ast.NodeVisitor):
    """Base class: subclass, set `rule`/`name`/`description`, override
    visit_* methods, call `self.add(node, message)` per diagnostic."""

    rule: str = 'SKY000'
    name: str = 'base'
    description: str = ''
    # Bump when the rule's LOGIC changes enough that old baseline
    # rows must be re-triaged; the baseline stores the version it was
    # written against and drops rows whose rule has moved on.
    version: int = 1

    def __init__(self, ctx: FileContext) -> None:
        self.ctx = ctx
        self.findings: List[Finding] = []

    @classmethod
    def applies_to(cls, path: str) -> bool:
        """Override to scope a rule to a subtree (posix relpath in)."""
        del path
        return True

    def add(self, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            self.rule, self.ctx.path, getattr(node, 'lineno', 1),
            getattr(node, 'col_offset', 0), message))

    def check(self, tree: ast.Module) -> List[Finding]:
        self.visit(tree)
        return self.findings


_CHECKERS: Dict[str, Type[Checker]] = {}


def register(cls: Type[Checker]) -> Type[Checker]:
    if cls.rule in _CHECKERS:
        raise ValueError(f'duplicate checker rule {cls.rule}')
    _CHECKERS[cls.rule] = cls
    return cls


def all_checkers() -> Dict[str, Type[Checker]]:
    _load_builtin_checkers()
    return dict(_CHECKERS)


def checker_versions() -> Dict[str, int]:
    """rule -> current logic version, for the baseline's
    `rule_versions` gate."""
    return {rule: cls.version for rule, cls in all_checkers().items()}


def _load_builtin_checkers() -> None:
    # Import side effect registers each @register'd class exactly once.
    from skypilot_tpu.analysis import checkers  # noqa: F401  pylint: disable=unused-import,cyclic-import


def resolve_select(select: Optional[str]) -> Set[str]:
    """`--select SKY001,SKY003` -> validated rule set (all if None)."""
    checkers = all_checkers()
    if not select:
        return set(checkers)
    rules = {r.strip().upper() for r in select.split(',') if r.strip()}
    unknown = rules - set(checkers)
    if unknown:
        raise ValueError(
            f'unknown rule(s) {sorted(unknown)}; available: '
            f'{sorted(checkers)}')
    return rules


def display_path(path: str) -> str:
    """Repo-relative posix path when under the repo, else as given."""
    abs_path = os.path.abspath(path)
    if abs_path.startswith(REPO_ROOT + os.sep):
        return os.path.relpath(abs_path, REPO_ROOT).replace(os.sep, '/')
    return path.replace(os.sep, '/')


def dotted_name(node: ast.AST) -> Optional[str]:
    """`a.b.c` for Name/Attribute chains, None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


def suppressed_lines(source: str) -> Dict[int, Optional[Set[str]]]:
    """line -> suppressed rules on it (None = every rule)."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        if m.group(1) is None:
            out[i] = None
        else:
            out[i] = {r.strip().upper() for r in m.group(1).split(',')
                      if r.strip()}
    return out


def symbol_spans(tree: ast.Module) -> List[Tuple[int, int, str]]:
    """(start, end, qualname) for every def, innermost-resolvable:
    the v2 baseline's symbol key. Classes contribute to the dotted
    prefix but are not spans themselves (a finding on a class-body
    line outside any method is effectively module-level churn-wise).
    """
    spans: List[Tuple[int, int, str]] = []

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                qual = prefix + child.name
                spans.append((child.lineno,
                              child.end_lineno or child.lineno, qual))
                walk(child, qual + '.')
            elif isinstance(child, ast.ClassDef):
                walk(child, prefix + child.name + '.')
            else:
                walk(child, prefix)

    walk(tree, '')
    return spans


def _symbol_for(spans: List[Tuple[int, int, str]], line: int) -> str:
    best: Optional[Tuple[int, int, str]] = None
    for span in spans:
        if span[0] <= line <= span[1]:
            if best is None or span[0] > best[0]:
                best = span
    return best[2] if best is not None else '<module>'


def run_source(source: str, path: str,
               select: Optional[Iterable[str]] = None,
               timings: Optional[Dict[str, float]] = None
               ) -> List[Finding]:
    """Run the (selected) checkers over one file's source text.

    `timings` (if given) accumulates per-rule wall-clock seconds
    across calls — the CLI surfaces it so a slow checker is visible.
    """
    checkers = all_checkers()
    rules = set(select) if select is not None else set(checkers)
    rel = display_path(path)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding('SKY000', rel, e.lineno or 1, e.offset or 0,
                        f'syntax error: {e.msg}')]
    findings: List[Finding] = []
    for rule in sorted(rules):
        cls = checkers[rule]
        if not cls.applies_to(rel):
            continue
        start = time.perf_counter()
        findings.extend(cls(FileContext(path, source)).check(tree))
        if timings is not None:
            timings[rule] = timings.get(rule, 0.0) + \
                (time.perf_counter() - start)
    suppressed = suppressed_lines(source)
    spans = symbol_spans(tree)
    kept = []
    for f in sorted(findings, key=lambda f: (f.line, f.col, f.rule)):
        rules_here = suppressed.get(f.line, ...)
        if rules_here is None or (rules_here is not ... and
                                  f.rule in rules_here):
            continue
        kept.append(dataclasses.replace(
            f, symbol=_symbol_for(spans, f.line)))
    return kept


def run_file(path: str,
             select: Optional[Iterable[str]] = None,
             timings: Optional[Dict[str, float]] = None
             ) -> List[Finding]:
    with open(path, 'r', encoding='utf-8') as f:
        source = f.read()
    return run_source(source, path, select, timings)


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS and
                                 not d.startswith('.'))
            for fname in sorted(filenames):
                if fname.endswith('.py'):
                    out.append(os.path.join(dirpath, fname))
    return out


def run_paths(paths: Sequence[str],
              select: Optional[Iterable[str]] = None,
              timings: Optional[Dict[str, float]] = None
              ) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        findings.extend(run_file(path, select, timings))
    return sorted(findings,
                  key=lambda f: (f.path, f.line, f.col, f.rule))


# -- baseline ---------------------------------------------------------------
class Baseline:
    """Grandfathered findings, keyed (path, rule, qualified symbol).

    v2 entries carry `symbol` (the enclosing def's dotted name) so a
    triaged row survives line churn; v1 entries carry `line` and
    still load and match — `--migrate-baseline` converts them. The
    file also records `rule_versions`: when a checker's `version`
    class attr is bumped (its logic changed), every row for that rule
    stops matching and must be re-triaged against the new logic.

    Every entry must carry a non-empty justification — the baseline
    is for triaged FALSE positives, not a mute button."""

    def __init__(self, entries: Optional[List[Dict]] = None,
                 rule_versions: Optional[Dict[str, int]] = None
                 ) -> None:
        self.entries = entries or []
        self.rule_versions = dict(rule_versions or {})
        self._line_index: Dict[Tuple[str, str, int], Dict] = {}
        self._symbol_index: Dict[Tuple[str, str, str], Dict] = {}
        for e in self.entries:
            just = str(e.get('justification') or '').strip()
            if not just:
                raise ValueError(
                    f'baseline entry {e.get("path")}:'
                    f'{e.get("symbol", e.get("line"))} '
                    f'{e.get("rule")} lacks a justification')
            if 'symbol' in e:
                self._symbol_index[
                    (e['path'], e['rule'], str(e['symbol']))] = e
            else:
                self._line_index[
                    (e['path'], e['rule'], int(e['line']))] = e

    @classmethod
    def load(cls, path: str) -> 'Baseline':
        if not os.path.exists(path):
            return cls([])
        with open(path, 'r', encoding='utf-8') as f:
            data = json.load(f)
        return cls(data.get('entries', []),
                   data.get('rule_versions', {}))

    def save(self, path: str) -> None:
        version = 2 if not self._line_index else 1
        doc: Dict[str, object] = {'version': version}
        if version == 2:
            doc['rule_versions'] = {
                rule: self.rule_versions.get(
                    rule, checker_versions().get(rule, 1))
                for rule in sorted({e['rule'] for e in self.entries})}
        doc['entries'] = self.entries
        with open(path, 'w', encoding='utf-8') as f:
            json.dump(doc, f, indent=2, sort_keys=False)
            f.write('\n')

    def _rule_current(self, rule: str) -> bool:
        """False when the checker's logic version moved past the one
        this baseline was written against (rows need re-triage)."""
        stored = self.rule_versions.get(rule)
        if stored is None:
            return True
        return checker_versions().get(rule, 1) == int(stored)

    def contains(self, finding: Finding) -> bool:
        if not self._rule_current(finding.rule):
            return False
        return (finding.symbol_key() in self._symbol_index or
                finding.key() in self._line_index)

    def split(self, findings: Sequence[Finding]
              ) -> Tuple[List[Finding], List[Finding]]:
        """-> (new findings, baselined findings)."""
        new, old = [], []
        for f in findings:
            (old if self.contains(f) else new).append(f)
        return new, old

    def stale_entries(self, findings: Sequence[Finding]) -> List[Dict]:
        """Entries matching no current finding — fixed code whose
        baseline row should be deleted."""
        live_lines = {f.key() for f in findings}
        live_symbols = {f.symbol_key() for f in findings}
        stale = [e for key, e in sorted(self._line_index.items())
                 if key not in live_lines]
        stale += [e for key, e in sorted(self._symbol_index.items())
                  if key not in live_symbols]
        return stale

    def migrated(self, findings: Sequence[Finding]) -> 'Baseline':
        """v1 -> v2: rekey every line-keyed entry by the symbol of
        the current finding it matches; entries matching nothing are
        dropped (they were stale anyway). Symbol-keyed entries pass
        through; duplicates collapse to one row per symbol key."""
        by_line = {f.key(): f for f in findings}
        entries: List[Dict] = []
        seen: Set[Tuple[str, str, str]] = set()

        def emit(entry: Dict, symbol: str) -> None:
            key = (entry['path'], entry['rule'], symbol)
            if key in seen:
                return
            seen.add(key)
            entries.append({
                'rule': entry['rule'], 'path': entry['path'],
                'symbol': symbol,
                'message': entry.get('message', ''),
                'justification': entry['justification']})

        for e in self.entries:
            if 'symbol' in e:
                emit(e, str(e['symbol']))
                continue
            f = by_line.get((e['path'], e['rule'], int(e['line'])))
            if f is not None:
                emit(e, f.symbol)
        return Baseline(entries, checker_versions())

    @classmethod
    def from_findings(cls, findings: Sequence[Finding],
                      justification: str) -> 'Baseline':
        entries: List[Dict] = []
        seen: Set[Tuple[str, str, str]] = set()
        for f in findings:
            if f.symbol_key() in seen:
                continue
            seen.add(f.symbol_key())
            entries.append({'rule': f.rule, 'path': f.path,
                            'symbol': f.symbol, 'message': f.message,
                            'justification': justification})
        return cls(entries, checker_versions())


# -- reporters --------------------------------------------------------------
def render_text(findings: Sequence[Finding],
                baselined: Sequence[Finding] = ()) -> str:
    lines = [f.render() for f in findings]
    n = len(findings)
    summary = f'{n} finding{"s" if n != 1 else ""}'
    if baselined:
        summary += f' ({len(baselined)} baselined, not shown)'
    lines.append(summary)
    return '\n'.join(lines)


def render_json(findings: Sequence[Finding],
                baselined: Sequence[Finding] = (),
                timings: Optional[Dict[str, float]] = None) -> str:
    doc: Dict[str, object] = {
        'version': 1,
        'count': len(findings),
        'baselined_count': len(baselined),
        'findings': [f.to_dict() for f in findings],
    }
    if timings is not None:
        doc['timings_ms'] = {rule: round(sec * 1000.0, 3)
                             for rule, sec in sorted(timings.items())}
    return json.dumps(doc, indent=2)
