"""Storage: bucket objects mounted or copied onto clusters. GCS-first.

Reference: sky/data/storage.py (~5600 LoC, S3/GCS/Azure/R2/...). The
TPU build is GCS-first (checkpoints + datasets live next to the TPUs;
intra-GCP traffic is free): Storage wraps a gs:// bucket with three
modes — MOUNT (gcsfuse), MOUNT_CACHED (rclone vfs cache), COPY
(gcloud storage rsync to disk). S3 sources are supported as
COPY-in via the s3 CLI when present.
"""
from __future__ import annotations

import enum
import os
import shlex
import typing
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu.utils import subprocess_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu.utils import command_runner as runner_lib


class StorageMode(enum.Enum):
    MOUNT = 'MOUNT'
    COPY = 'COPY'
    MOUNT_CACHED = 'MOUNT_CACHED'


class StoreType(enum.Enum):
    GCS = 'GCS'
    S3 = 'S3'
    AZURE = 'AZURE'
    R2 = 'R2'
    HF = 'HF'  # HuggingFace Hub, download-only (models/datasets)

    @classmethod
    def from_url(cls, url: str) -> 'StoreType':
        for prefix, store in (('gs://', cls.GCS), ('s3://', cls.S3),
                              ('az://', cls.AZURE), ('r2://', cls.R2),
                              ('hf://', cls.HF)):
            if url.startswith(prefix):
                return store
        raise exceptions.StorageSpecError(
            f'Unsupported storage url {url!r} '
            '(gs://, s3://, az://, r2://, or hf://).')

    @property
    def url_prefix(self) -> str:
        return {StoreType.GCS: 'gs', StoreType.S3: 's3',
                StoreType.AZURE: 'az', StoreType.R2: 'r2',
                StoreType.HF: 'hf'}[self]


class Storage:
    """A named bucket + how to expose it on cluster hosts."""

    def __init__(self, name: Optional[str] = None,
                 source: Optional[str] = None,
                 mode: StorageMode = StorageMode.MOUNT,
                 store: Optional[StoreType] = None,
                 persistent: bool = True) -> None:
        if name is None and source is None:
            raise exceptions.StorageSpecError(
                'Storage needs a name (new bucket) or source (existing '
                'bucket / local dir).')
        self.name = name
        self.source = source
        self.mode = mode
        self.persistent = persistent
        if store is None and source is not None and '://' in source:
            store = StoreType.from_url(source)
        self.store = store or StoreType.GCS
        if self.store == StoreType.HF:
            # The Hub is a snapshot source, not a filesystem
            # (reference: HuggingFaceStore, sky/data/storage.py:5383).
            if mode != StorageMode.COPY:
                raise exceptions.StorageSpecError(
                    'hf:// sources are download-only: use mode: COPY '
                    f'(got {mode.value}).')
            if self.source is None or '://' not in str(self.source):
                raise exceptions.StorageSpecError(
                    'hf:// storage needs a source like '
                    'hf://org/model or hf://datasets/org/name.')

    # -- bucket url ------------------------------------------------------------
    @property
    def bucket_url(self) -> str:
        if self.source and '://' in self.source:
            return self.source.rstrip('/')
        assert self.name, self
        return f'{self.store.url_prefix}://{self.name}'

    def is_local_source(self) -> bool:
        return bool(self.source) and '://' not in str(self.source)

    # -- yaml ---------------------------------------------------------------------
    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'Storage':
        config = dict(config)
        mode = StorageMode(str(config.pop('mode', 'MOUNT')).upper())
        store = config.pop('store', None)
        out = cls(name=config.pop('name', None),
                  source=config.pop('source', None),
                  mode=mode,
                  store=StoreType(store.upper()) if store else None,
                  persistent=config.pop('persistent', True))
        if config:
            raise exceptions.StorageSpecError(
                f'Unknown storage fields: {sorted(config)}')
        return out

    def to_yaml_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.name:
            out['name'] = self.name
        if self.source:
            out['source'] = self.source
        out['mode'] = self.mode.value
        out['store'] = self.store.value
        if not self.persistent:
            out['persistent'] = False
        return out

    # -- server-side sync (local source -> bucket) ---------------------------------
    def sync_local_source(self) -> None:
        """Upload a local-dir source to the bucket before mounting."""
        if not self.is_local_source():
            return
        assert self.name, 'local-source storage needs a bucket name'
        if self.store == StoreType.HF:
            raise exceptions.StorageSpecError(
                'Cannot upload to hf:// (download-only store).')
        src = os.path.expanduser(str(self.source))
        url = self.bucket_url
        if self.store == StoreType.GCS:
            cmd = (f'gcloud storage rsync -r {shlex.quote(src)} '
                   f'{shlex.quote(url)}')
        elif self.store == StoreType.AZURE:
            container, _, subpath = url.split('://', 1)[1].partition('/')
            cmd = (f'az storage blob sync -s {shlex.quote(src)} '
                   f'-c {shlex.quote(container)}')
            if subpath:
                cmd += f' -d {shlex.quote(subpath)}'
        elif self.store == StoreType.R2:
            bucket_path = url.split('://', 1)[1]
            cmd = (f'aws s3 sync {shlex.quote(src)} '
                   f's3://{shlex.quote(bucket_path)} '
                   f'--endpoint-url {shlex.quote(_r2_endpoint())}'
                   f'{_r2_profile_flag()}')
        else:
            cmd = f'aws s3 sync {shlex.quote(src)} {shlex.quote(url)}'
        rc = os.system(cmd)
        if rc != 0:
            raise exceptions.StorageUploadError(
                f'Failed to sync {src} to {url} (rc={rc}).')
        global_state.add_or_update_storage(self.name, self.to_yaml_config(),
                                           'READY')

    def __repr__(self) -> str:
        return (f'Storage({self.bucket_url}, mode={self.mode.value})')


# ---------------------------------------------------------------------------
# On-host commands (reference: sky/data/mounting_utils.py)
# ---------------------------------------------------------------------------
def _r2_endpoint() -> str:
    """Cloudflare R2 S3-compatible endpoint from config/env."""
    from skypilot_tpu import sky_config
    account = sky_config.get_nested(('r2', 'account_id')) or \
        os.environ.get('R2_ACCOUNT_ID')
    if not account:
        raise exceptions.StorageSpecError(
            'R2 storage needs an account id: set r2.account_id in '
            'config or the R2_ACCOUNT_ID env var.')
    return f'https://{account}.r2.cloudflarestorage.com'


def _r2_profile_flag() -> str:
    """` --profile <name>` when r2.profile is configured, else ''.

    Default is env credentials (matching the rclone env_auth mount
    path); a dedicated AWS-CLI profile for R2 keys is opt-in via
    config, not hardcoded.
    """
    from skypilot_tpu import sky_config
    profile = sky_config.get_nested(('r2', 'profile'))
    return f' --profile {shlex.quote(str(profile))}' if profile else ''


def download_command(uri: str, dst: str) -> str:
    """Shell command to copy a bucket (or https file) onto a host."""
    q = shlex.quote
    if uri.startswith('gs://'):
        return (f'mkdir -p {q(dst)} && '
                f'(gcloud storage rsync -r {q(uri)} {q(dst)} || '
                f'gsutil -m rsync -r {q(uri)} {q(dst)})')
    if uri.startswith('s3://'):
        return f'mkdir -p {q(dst)} && aws s3 sync {q(uri)} {q(dst)}'
    if uri.startswith('az://'):
        container, _, subpath = uri.split('://', 1)[1].partition('/')
        pattern = f' --pattern {q(subpath + "/*")}' if subpath else ''
        return (f'mkdir -p {q(dst)} && '
                f'az storage blob download-batch -s {q(container)} '
                f'-d {q(dst)}{pattern}')
    if uri.startswith('r2://'):
        bucket_path = uri.split('://', 1)[1]
        return (f'mkdir -p {q(dst)} && '
                f'aws s3 sync s3://{q(bucket_path)} {q(dst)} '
                f'--endpoint-url {q(_r2_endpoint())}'
                f'{_r2_profile_flag()}')
    if uri.startswith('hf://'):
        # hf CLI ships with huggingface_hub; snapshots resume on retry.
        repo = uri[len('hf://'):].strip('/')
        repo_type = ''
        if repo.startswith('datasets/'):
            repo = repo[len('datasets/'):]
            repo_type = ' --repo-type dataset'
        return (f'mkdir -p {q(dst)} && '
                f'huggingface-cli download {q(repo)}{repo_type} '
                f'--local-dir {q(dst)}')
    if uri.startswith('https://'):
        return (f'mkdir -p $(dirname {q(dst)}) && '
                f'curl -fsSL {q(uri)} -o {q(dst)}')
    raise exceptions.StorageSpecError(f'Unsupported uri {uri!r}')


def mount_command(storage: 'Storage', mount_path: str) -> str:
    """Shell command mounting the bucket at mount_path on a host.

    GCS mounts via gcsfuse (or rclone for the cached mode); S3 mounts
    via rclone's :s3: backend with env-provided AWS credentials —
    the reference uses goofys/mount-s3 for the same role
    (sky/data/mounting_utils.py)."""
    q = shlex.quote
    url = storage.bucket_url
    bucket = url.split('://', 1)[1].split('/', 1)[0]
    if storage.mode == StorageMode.COPY:
        return download_command(url, mount_path)
    if storage.store in (StoreType.S3, StoreType.AZURE, StoreType.R2):
        # Non-GCS stores all mount via rclone backends with env auth
        # (the reference's goofys/blobfuse2 role,
        # sky/data/mounting_utils.py:297-698).
        if storage.store == StoreType.S3:
            remote = f':s3,env_auth=true:{bucket}'
        elif storage.store == StoreType.R2:
            # rclone connection-string values containing ':' must be
            # quoted, or parsing stops at 'https'.
            remote = (f':s3,env_auth=true,'
                      f'endpoint="{_r2_endpoint()}":{bucket}')
        else:
            remote = f':azureblob,env_auth=true:{bucket}'
        cache = ('--vfs-cache-mode writes --vfs-cache-max-size 10G '
                 if storage.mode == StorageMode.MOUNT_CACHED else '')
        return (
            f'mkdir -p {q(mount_path)} ~/.cache/rclone && '
            f'(mountpoint -q {q(mount_path)} && echo already mounted) || '
            f'rclone mount {q(remote)} {q(mount_path)} '
            f'--daemon {cache}--dir-cache-time 10s')
    if storage.mode == StorageMode.MOUNT:
        return (
            f'mkdir -p {q(mount_path)} && '
            f'(mountpoint -q {q(mount_path)} && echo already mounted) || '
            f'gcsfuse --implicit-dirs '
            f'--rename-dir-limit 10000 '
            f'--stat-cache-ttl 10s --type-cache-ttl 10s '
            f'{q(bucket)} {q(mount_path)}')
    # MOUNT_CACHED: rclone VFS write-back cache — fast local writes,
    # async upload; the checkpoint-friendly mode (reference
    # mounting_utils.py:698).
    return (
        f'mkdir -p {q(mount_path)} ~/.cache/rclone && '
        f'rclone mount :gcs:{q(bucket)} {q(mount_path)} '
        f'--daemon --vfs-cache-mode writes '
        f'--vfs-cache-max-size 10G --dir-cache-time 10s')


def mount_storage_on_hosts(storage: 'Storage', mount_path: str,
                           runners: List['runner_lib.CommandRunner']) -> None:
    storage.sync_local_source()
    cmd = mount_command(storage, mount_path)

    def mount_one(runner) -> None:
        rc = runner.run(cmd, stream_logs=False)
        if rc != 0:
            raise exceptions.StorageError(
                f'Failed to mount {storage.bucket_url} at {mount_path} '
                f'on {runner.node_id} (rc={rc}).')

    subprocess_utils.run_in_parallel(mount_one, runners)
