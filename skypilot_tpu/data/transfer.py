"""Cross-cloud bucket-to-bucket transfer pipelines.

Reference: sky/data/data_transfer.py:40-194 — GCS↔S3 transfers via the
GCP Storage Transfer Service (large jobs) or streaming CLI copy (small
ones). Same split here, TPU-deployment-first: the common direction is
S3 → GCS (pull external datasets next to the TPUs, then serve them
over gcsfuse/rclone), which Storage Transfer Service runs entirely
server-side — no bytes through the API host.

All functions *build* the operation; `run=False` returns the command/
request for inspection (how the unit tests exercise this without
cloud credentials)."""
from __future__ import annotations

import json
import shlex
import subprocess
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions

# Above this size, prefer the server-side Storage Transfer Service
# over a streamed CLI copy (reference threshold semantics).
_STS_THRESHOLD_GIGABYTES = 50.0


def _split_bucket(url: str) -> str:
    if '://' not in url:
        raise exceptions.StorageSpecError(f'Not a bucket url: {url!r}')
    return url.split('://', 1)[1].split('/', 1)[0]


def stream_copy_command(src_url: str, dst_url: str) -> str:
    """One-shot streamed copy command between any two supported stores.

    `gcloud storage` speaks both gs:// and s3:// (reading s3 with AWS
    creds from the environment), so a single binary covers all four
    directions; pure-S3 copies fall back to the aws CLI.
    """
    q = shlex.quote
    schemes = {u.split('://', 1)[0] for u in (src_url, dst_url)}
    if not schemes <= {'gs', 's3'}:
        raise exceptions.StorageSpecError(
            f'Unsupported transfer {src_url!r} -> {dst_url!r} '
            '(gs:// and s3:// only).')
    if schemes == {'s3'}:
        return f'aws s3 sync {q(src_url)} {q(dst_url)}'
    return f'gcloud storage rsync -r {q(src_url)} {q(dst_url)}'


def sts_transfer_job_body(src_url: str, dst_url: str,
                          project_id: str) -> Dict[str, Any]:
    """Storage Transfer Service transferJobs.create request body for an
    S3 → GCS pull (reference: data_transfer.py:94-143)."""
    if not src_url.startswith('s3://') or not dst_url.startswith('gs://'):
        raise exceptions.StorageSpecError(
            'Storage Transfer Service handles s3:// -> gs:// here; use '
            f'stream_copy_command for {src_url} -> {dst_url}.')
    return {
        'projectId': project_id,
        'status': 'ENABLED',
        'transferSpec': {
            'awsS3DataSource': {'bucketName': _split_bucket(src_url)},
            'gcsDataSink': {'bucketName': _split_bucket(dst_url)},
            'transferOptions': {'overwriteWhen': 'DIFFERENT'},
        },
    }


def transfer(src_url: str, dst_url: str,
             size_gigabytes: Optional[float] = None,
             project_id: Optional[str] = None,
             run: bool = True) -> Dict[str, Any]:
    """Move a bucket's contents across clouds.

    Picks Storage Transfer Service for large S3→GCS jobs (server-side,
    no local bandwidth), a streamed CLI copy otherwise. Returns a plan
    dict {'method', 'command' | 'request_body'}; executes it when
    `run` (the default).
    """
    big = size_gigabytes is not None and \
        size_gigabytes >= _STS_THRESHOLD_GIGABYTES
    if big and src_url.startswith('s3://') and dst_url.startswith('gs://') \
            and project_id:
        body = sts_transfer_job_body(src_url, dst_url, project_id)
        plan: Dict[str, Any] = {'method': 'sts', 'request_body': body}
        if run:
            cmd = (
                'curl -sf -X POST '
                '-H "Authorization: Bearer $(gcloud auth '
                'print-access-token)" -H "Content-Type: application/json" '
                f'-d {shlex.quote(json.dumps(body))} '
                'https://storagetransfer.googleapis.com/v1/transferJobs')
            _run_shell(cmd, src_url, dst_url)
        return plan
    cmd = stream_copy_command(src_url, dst_url)
    plan = {'method': 'stream', 'command': cmd}
    if run:
        _run_shell(cmd, src_url, dst_url)
    return plan


def _run_shell(cmd: str, src_url: str, dst_url: str) -> None:
    proc = subprocess.run(['bash', '-c', cmd], capture_output=True,
                          text=True, check=False)
    if proc.returncode != 0:
        raise exceptions.StorageError(
            f'Transfer {src_url} -> {dst_url} failed (rc='
            f'{proc.returncode}): {proc.stderr[-500:]}')
