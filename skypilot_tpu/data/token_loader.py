"""Token data loader: C++ mmap+prefetch core with ctypes binding.

Training input pipeline for the recipe models: binary token shards
(nanoGPT-style .bin of uint16/uint32) → [batch, seq+1] uint32 arrays,
deterministic per (seed, step, rank) so data-parallel hosts draw
disjoint streams. The native core (native/token_loader.cpp) mmaps
shards and prefetches on background threads; a pure-numpy fallback
keeps everything working where the .so is not built.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), 'native')
_SO_PATH = os.path.join(_NATIVE_DIR, 'libtoken_loader.so')

_lib: Optional[ctypes.CDLL] = None
# Why the native core is unusable, when it is (None = usable or not
# yet probed). Tests key skip-with-reason off this instead of failing
# in environments that cannot build or load the .so.
_native_error: Optional[str] = None


def _build_native(force: bool = False) -> bool:
    if not os.path.exists(os.path.join(_NATIVE_DIR, 'token_loader.cpp')):
        return False
    try:
        cmd = ['make', '-C', _NATIVE_DIR]
        if force:
            cmd.insert(1, '-B')
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def native_unavailable_reason() -> Optional[str]:
    """None when the native loader works here; otherwise why not
    (no toolchain, GLIBC mismatch, ...)."""
    _load_lib()
    return _native_error


def _dlopen_or_rebuild() -> Optional[ctypes.CDLL]:
    """dlopen the .so; on failure (typically a stale binary built
    against another toolchain's GLIBC) force one rebuild and retry."""
    global _native_error
    try:
        return ctypes.CDLL(_SO_PATH)
    except OSError as e:
        first_error = str(e)
    if not _build_native(force=True):
        _native_error = (f'cannot load {_SO_PATH} ({first_error}) and '
                         f'rebuild failed (no usable C++ toolchain?)')
        return None
    try:
        return ctypes.CDLL(_SO_PATH)
    except OSError as e:
        _native_error = f'rebuilt .so still does not load: {e}'
        return None


def _load_lib() -> Optional[ctypes.CDLL]:
    global _lib, _native_error
    if _lib is not None:
        return _lib
    if _native_error is not None:
        return None
    if not os.path.exists(_SO_PATH) and not _build_native():
        _native_error = (f'{_SO_PATH} missing and `make -C '
                         f'{_NATIVE_DIR}` did not produce it')
        return None
    lib = _dlopen_or_rebuild()
    if lib is None:
        return None
    lib.tl_open.restype = ctypes.c_void_p
    lib.tl_open.argtypes = [ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
                            ctypes.c_int]
    lib.tl_total_tokens.restype = ctypes.c_uint64
    lib.tl_total_tokens.argtypes = [ctypes.c_void_p]
    lib.tl_start.restype = ctypes.c_int
    lib.tl_start.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                             ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
                             ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.tl_next.restype = ctypes.c_int64
    lib.tl_next.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_uint32)]
    lib.tl_close.restype = None
    lib.tl_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def native_available() -> bool:
    return _load_lib() is not None


class TokenLoader:
    """Iterates [batch, seq+1] uint32 batches from token shard files."""

    def __init__(self, shard_paths: Sequence[str], batch: int, seq: int,
                 *, seed: int = 0, rank: int = 0, world: int = 1,
                 shuffle: bool = True, dtype_bytes: int = 2,
                 prefetch_threads: int = 2, use_native: bool = True) -> None:
        self.paths = [os.path.abspath(os.path.expanduser(p))
                      for p in shard_paths]
        for p in self.paths:
            if not os.path.exists(p):
                raise FileNotFoundError(p)
        self.batch, self.seq = batch, seq
        self.seed, self.rank, self.world = seed, rank, world
        self.shuffle = shuffle
        self.dtype_bytes = dtype_bytes
        self._handle = None
        self._lib = _load_lib() if use_native else None
        if self._lib is not None:
            arr = (ctypes.c_char_p * len(self.paths))(
                *[p.encode() for p in self.paths])
            handle = self._lib.tl_open(arr, len(self.paths), dtype_bytes)
            if not handle:
                raise OSError(f'tl_open failed for {self.paths}')
            self._handle = ctypes.c_void_p(handle)
            rc = self._lib.tl_start(self._handle, batch, seq, seed, rank,
                                    world, int(shuffle), prefetch_threads, 4)
            if rc != 0:
                raise ValueError('dataset smaller than one window')
            self.total_tokens = int(
                self._lib.tl_total_tokens(self._handle))
        else:
            # numpy fallback: concat-mmap the shards.
            dt = np.uint16 if dtype_bytes == 2 else np.uint32
            self._arrays = [np.memmap(p, dtype=dt, mode='r')
                            for p in self.paths]
            self._cum = np.cumsum([0] + [len(a) for a in self._arrays])
            self.total_tokens = int(self._cum[-1])
            if seq + 1 >= self.total_tokens:
                raise ValueError('dataset smaller than one window')
            self._step = 0
            self._rng_base = np.random.SeedSequence(seed)

    # -- numpy fallback helpers --------------------------------------------
    def _window_np(self, start: int, count: int) -> np.ndarray:
        out = np.empty(count, np.uint32)
        filled = 0
        while filled < count:
            shard = int(np.searchsorted(self._cum, start + filled,
                                        side='right')) - 1
            off = start + filled - self._cum[shard]
            take = min(count - filled,
                       len(self._arrays[shard]) - int(off))
            out[filled:filled + take] = self._arrays[shard][off:off + take]
            filled += take
        return out

    def _next_np(self) -> np.ndarray:
        step = self._step
        self._step += 1
        out = np.empty((self.batch, self.seq + 1), np.uint32)
        n_windows = self.total_tokens // self.seq
        for b in range(self.batch):
            if self.shuffle:
                rng = np.random.default_rng(
                    [self.seed, step, self.rank, b])
                start = int(rng.integers(
                    0, self.total_tokens - self.seq - 1))
            else:
                window = (step * self.world + self.rank) * self.batch + b
                start = (window % n_windows) * self.seq
                start = min(start, self.total_tokens - self.seq - 1)
            out[b] = self._window_np(start, self.seq + 1)
        return out

    # -- public --------------------------------------------------------------
    def next_batch(self) -> np.ndarray:
        """[batch, seq+1] uint32; inputs = [:, :-1], targets = [:, 1:]."""
        if self._handle is not None:
            out = np.empty((self.batch, self.seq + 1), np.uint32)
            step = self._lib.tl_next(
                self._handle,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
            if step < 0:
                raise StopIteration
            return out
        return self._next_np()

    def __iter__(self):
        return self

    def __next__(self) -> np.ndarray:
        return self.next_batch()

    def close(self) -> None:
        if self._handle is not None:
            self._lib.tl_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # pylint: disable=broad-except
            pass
