"""`check`: probe per-cloud credentials, cache enabled clouds.

Reference: sky/check.py (:476-546 caches enabled clouds).
"""
from __future__ import annotations

import json
from typing import List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu.utils.registry import CLOUD_REGISTRY

_CACHE_KEY = 'enabled_clouds'


def check(quiet: bool = False) -> List[str]:
    """Probe all registered clouds; persist and return the enabled set."""
    import skypilot_tpu.clouds  # noqa: F401
    enabled = []
    details = {}
    for cloud_cls in CLOUD_REGISTRY.values():
        name = cloud_cls.canonical_name()
        try:
            ok, reason = cloud_cls.check_credentials()
        except Exception as e:  # pylint: disable=broad-except
            ok, reason = False, str(e)
        details[name] = (ok, reason)
        if ok:
            enabled.append(name)
    global_state.set_system_config(_CACHE_KEY, json.dumps(sorted(enabled)))
    if not quiet:
        for name, (ok, reason) in sorted(details.items()):
            mark = '\x1b[32m✓\x1b[0m' if ok else '\x1b[31m✗\x1b[0m'
            line = f'  {mark} {name}'
            if not ok and reason:
                line += f': {reason.splitlines()[0]}'
            print(line)
    # Best-effort pricing refresh from the configured mirror
    # (SKYPILOT_CATALOG_MIRROR; TTL-cached; no-op when unset, so
    # zero-egress environments keep the bundled snapshot silently).
    # Reference: sky/catalog/common.py:245 refreshes at read time; here
    # `check` is the explicit refresh point so launches never block on
    # a slow mirror.
    try:
        from skypilot_tpu.catalog import common as catalog_common
        refreshed = catalog_common.refresh_catalogs(timeout=5.0,
                                                    verbose=not quiet)
        if refreshed and not quiet:
            print(f'  catalog: {len(refreshed)} file(s) fresh from mirror')
    except Exception:  # pylint: disable=broad-except
        pass
    return enabled


def get_cached_enabled_clouds(refresh_if_empty: bool = True) -> List[str]:
    cached = global_state.get_system_config(_CACHE_KEY)
    if cached is None:
        if not refresh_if_empty:
            return []
        return check(quiet=True)
    return json.loads(cached)


def get_cloud_or_raise(enabled: Optional[List[str]] = None):
    if enabled is None:
        enabled = get_cached_enabled_clouds()
    if not enabled:
        raise exceptions.NoCloudAccessError(
            'No cloud is enabled. Run `stpu check` after configuring '
            'credentials.')
    return enabled
