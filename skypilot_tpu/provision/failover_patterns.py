"""Per-cloud provisioning-error pattern library → (category, scope).

This is the declarative form of what SURVEY.md calls "the real IP of
SkyPilot": the mapping from raw cloud error text to a failover
decision. Reference: sky/backends/cloud_vm_ray_backend.py:395
(FailoverCloudErrorHandlerV1) and :522 (FailoverCloudErrorHandlerV2),
whose per-cloud handlers encode which errors block a zone, a region,
the whole cloud, or abort failover outright. Here each cloud gets a
first-match-wins ordered table of regex patterns over the error code
+ message, so the knowledge is data, unit-testable row by row, and
extensible without touching engine code.

Scopes (consumed by backends.tpu_backend.RetryingProvisioner):
  zone   — block this zone, keep walking (stockouts, transient).
  region — block the region's remaining zones (quotas are regional;
           subnet/opt-in problems are regional).
  cloud  — stop walking this cloud entirely, but the request could
           succeed elsewhere (credentials, billing, TOS, global VPC).
  abort  — non-retryable anywhere: the request itself is broken.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Optional, Tuple  # noqa: F401 (Tuple: table types)

from skypilot_tpu import exceptions

_P = exceptions.ProvisionerError

ZONE = 'zone'
REGION = 'region'
CLOUD = 'cloud'
ABORT = 'abort'


@dataclasses.dataclass(frozen=True)
class ErrorPattern:
    """One classified cloud-error shape.

    `pattern` is a case-insensitive regex, matched (re.search) against
    `"{code}: {message}"` — cloud API error codes and free-text
    messages both participate, so 'QUOTA_EXCEEDED' and 'Quota ...
    exceeded in region us-west1' are both expressible.
    """
    pattern: str
    category: str
    scope: str
    note: str = ''

    def matches(self, text: str) -> bool:
        return re.search(self.pattern, text, re.IGNORECASE) is not None


# ---------------------------------------------------------------------------
# GCP: GCE VM + TPU API (REST error codes and message fragments).
# Code provenance: cloud.google.com/compute/docs/troubleshooting +
# the TPU API's numeric gRPC codes observed via the reference's
# handler (cloud_vm_ray_backend.py:554-707).
GCP_PATTERNS: Tuple[ErrorPattern, ...] = (
    # -- API throttling first: would otherwise match the quota rows.
    ErrorPattern(r'rate.?limit|per minute|RESOURCE_OPERATION_RATE_EXCEEDED',
                 _P.TRANSIENT, ZONE, 'API throttle, not capacity'),
    # -- capacity / stockout: block the zone, keep walking.
    ErrorPattern(r'ZONE_RESOURCE_POOL_EXHAUSTED', _P.CAPACITY, ZONE,
                 'GCE stockout (with or without _WITH_DETAILS)'),
    ErrorPattern(r'insufficientCapacity|does not have enough resources',
                 _P.CAPACITY, ZONE),
    ErrorPattern(r'no more capacity in the zone', _P.CAPACITY, ZONE,
                 'TPU API code 8'),
    ErrorPattern(r'Insufficient reserved capacity', _P.CAPACITY, ZONE,
                 'TPU API code 9'),
    ErrorPattern(r'not enough resources|stockout|currently unavailable',
                 _P.CAPACITY, ZONE),
    ErrorPattern(r'update is not supported while in state PREEMPTED',
                 _P.CAPACITY, ZONE, 'TPU preempted mid-creation (code 3)'),
    ErrorPattern(r'UNSUPPORTED_OPERATION', _P.CAPACITY, ZONE,
                 'empirically: VM preempted during creation'),
    ErrorPattern(r'RESOURCE_NOT_READY', _P.TRANSIENT, ZONE,
                 'VM still STOPPING; zone is busy'),
    # -- quota: regional unless explicitly global. These rows MUST
    # precede the bare RESOURCE_EXHAUSTED capacity row: real Google
    # quota bodies carry status 'RESOURCE_EXHAUSTED' alongside the
    # 'Quota ... exceeded' message.
    ErrorPattern(r"GPUS_ALL_REGIONS.{0,20}exceeded", _P.QUOTA, CLOUD,
                 'global GPU quota: no region will differ'),
    ErrorPattern(r'QuotaFailure.*in zone|exhausted.*in zone', _P.QUOTA,
                 ZONE, 'TPU per-zone quota'),
    ErrorPattern(r'QUOTA_EXCEEDED|quotaExceeded|Quota .{0,60}exceeded',
                 _P.QUOTA, REGION),
    ErrorPattern(r'RESOURCE_EXHAUSTED', _P.CAPACITY, ZONE,
                 'bare gRPC status with no quota text'),
    # -- config: scope depends on what is misconfigured.
    ErrorPattern(r'VPC_NOT_FOUND', _P.CONFIG, CLOUD,
                 'GCP VPCs are global: skip the whole cloud'),
    ErrorPattern(r'SUBNET_NOT_FOUND_FOR_VPC', _P.CONFIG, REGION,
                 'subnets are regional'),
    ErrorPattern(r'disk size cannot be smaller than the image size',
                 _P.CONFIG, ABORT, 'same request fails everywhere'),
    # Zone-coverage miss BEFORE the generic invalid-field abort row:
    # the real GCE 400 reads "Invalid value for field
    # 'resource.machineType': ... Machine type X does not exist in
    # zone Y." and must stay zone-scoped.
    ErrorPattern(r'Machine type .{0,80} does not exist in zone',
                 _P.CONFIG, ZONE, 'family coverage varies by zone'),
    ErrorPattern(r'Invalid (value for field|acceleratorType|machine type)',
                 _P.CONFIG, ABORT),
    ErrorPattern(r'(acceleratorType|runtime_version).{0,60}not '
                 r'(available|found|supported)', _P.CONFIG, ZONE),
    # -- permission / account state.
    ErrorPattern(r'Policy update access denied|IAM_PERMISSION_DENIED',
                 _P.PERMISSION, CLOUD,
                 'service-account misconfiguration is project-wide'),
    ErrorPattern(r'is not found or access is unauthorized', _P.PERMISSION,
                 ZONE, 'location-restricted project'),
    ErrorPattern(r'billing (account|to be enabled|is disabled)'
                 r'|Billing must be enabled', _P.PERMISSION, CLOUD),
    ErrorPattern(r'Terms of Service|has not accepted', _P.PERMISSION, CLOUD),
    ErrorPattern(r'caller lacks permission|PERMISSION_DENIED|'
                 r'Request had insufficient authentication',
                 _P.PERMISSION, CLOUD),
    ErrorPattern(r'ACCESS_TOKEN_EXPIRED|invalid_grant', _P.PERMISSION,
                 CLOUD, 'credentials fixable only by the user'),
    # -- transient backend hiccups: retry elsewhere, zone-scoped.
    ErrorPattern(r'backendError|internal error|INTERNAL_ERROR',
                 _P.TRANSIENT, ZONE),
    ErrorPattern(r'RESOURCE_NOT_FOUND', _P.CAPACITY, ZONE,
                 'post-retry disappearance == likely stockout (ref #1797)'),
    ErrorPattern(r'invalid state, please retry|serviceUnavailable|'
                 r'temporarily unavailable', _P.TRANSIENT, ZONE),
)

# ---------------------------------------------------------------------------
# AWS: EC2 API error codes (docs.aws.amazon.com/AWSEC2/latest/APIReference
# /errors-overview.html); scope notes follow the reference's
# _aws_handler + the per-code semantics.
AWS_PATTERNS: Tuple[ErrorPattern, ...] = (
    # -- throttling first (RequestLimitExceeded would match 'limit').
    ErrorPattern(r'RequestLimitExceeded|Throttling|ThrottlingException',
                 _P.TRANSIENT, ZONE),
    # -- capacity.
    ErrorPattern(r'InsufficientInstanceCapacity', _P.CAPACITY, ZONE),
    ErrorPattern(r'InsufficientHostCapacity', _P.CAPACITY, ZONE),
    ErrorPattern(r'InsufficientReservedInstanceCapacity', _P.CAPACITY, ZONE),
    ErrorPattern(r'InsufficientCapacityOnOutpost', _P.CAPACITY, ZONE),
    ErrorPattern(r'UnfulfillableCapacity', _P.CAPACITY, ZONE),
    ErrorPattern(r'SpotMaxPriceTooLow', _P.CAPACITY, ZONE,
                 'spot market price above bid'),
    ErrorPattern(r'MarketCapacityOversubscribed', _P.CAPACITY, ZONE),
    ErrorPattern(r'^Unsupported$|not supported in your requested '
                 r'Availability Zone', _P.CAPACITY, ZONE,
                 'instance family absent from this AZ'),
    # -- quota (regional).
    ErrorPattern(r'MaxSpotInstanceCountExceeded', _P.QUOTA, REGION),
    ErrorPattern(r'InstanceLimitExceeded', _P.QUOTA, REGION),
    ErrorPattern(r'VcpuLimitExceeded', _P.QUOTA, REGION),
    ErrorPattern(r'VolumeLimitExceeded|MaxIOPSLimitExceeded', _P.QUOTA,
                 REGION),
    ErrorPattern(r'AddressLimitExceeded|RouteLimitExceeded', _P.QUOTA,
                 REGION),
    # Transient count-exceeded shapes BEFORE the quota catch-all, or
    # they would region-block on a retryable error.
    ErrorPattern(r'ResourceCountExceeded', _P.TRANSIENT, ZONE,
                 'API-side concurrent-mutation throttle'),
    ErrorPattern(r'LimitExceeded|CountExceeded|quota', _P.QUOTA, REGION,
                 'catch-all for the *LimitExceeded family'),
    # -- account / permission.
    ErrorPattern(r'OptInRequired', _P.PERMISSION, REGION,
                 'region not opted in; other regions may be'),
    ErrorPattern(r'PendingVerification', _P.PERMISSION, CLOUD,
                 'account under review'),
    ErrorPattern(r'UnauthorizedOperation', _P.PERMISSION, CLOUD,
                 'IAM policy gap is account-wide'),
    ErrorPattern(r'AuthFailure|InvalidClientTokenId|ExpiredToken|'
                 r'IncompleteSignature|SignatureDoesNotMatch',
                 _P.PERMISSION, CLOUD, 'credential problem'),
    # -- config.
    ErrorPattern(r'InvalidAMIID|InvalidImageID', _P.CONFIG, REGION,
                 'AMIs are regional'),
    ErrorPattern(r'InvalidSubnetID|InvalidGroup\.NotFound|'
                 r'InvalidSecurityGroupID|InvalidVpcID', _P.CONFIG, REGION,
                 'network objects are regional'),
    ErrorPattern(r'InvalidKeyPair', _P.CONFIG, REGION),
    ErrorPattern(r'Unsupported.*instance type|InvalidInstanceType',
                 _P.CONFIG, ABORT),
    ErrorPattern(r'InvalidParameter|MissingParameter|ValidationError',
                 _P.CONFIG, ABORT),
    # -- transient.
    ErrorPattern(r'InternalError|InternalFailure|ServiceUnavailable|'
                 r'^Unavailable$', _P.TRANSIENT, ZONE),
    ErrorPattern(r'InsufficientAddressCapacity', _P.TRANSIENT, ZONE),
)

# ---------------------------------------------------------------------------
# Azure: ARM deployment/compute error codes (reference _azure_handler
# plus learn.microsoft.com/azure/azure-resource-manager/troubleshooting
# /common-deployment-errors); Azure zones are '1'/'2'/'3' within a
# region, so zone-scoped rows matter when zonal placement is pinned.
AZURE_PATTERNS: Tuple[ErrorPattern, ...] = (
    # -- capacity.
    ErrorPattern(r'ZonalAllocationFailed|'
                 r'OverconstrainedZonalAllocationRequest',
                 _P.CAPACITY, ZONE),
    ErrorPattern(r'SkuNotAvailable', _P.CAPACITY, REGION,
                 'SKU restricted/out of stock for the subscription here'),
    ErrorPattern(r'AllocationFailed|OverconstrainedAllocation',
                 _P.CAPACITY, REGION),
    ErrorPattern(r'SpotEvictedNotAvailable|EvictionPolicyViolation',
                 _P.CAPACITY, REGION),
    ErrorPattern(r'VMStartTimedOut', _P.CAPACITY, REGION),
    # -- quota.
    ErrorPattern(r'LowPriorityQuotaExceeded|SpotQuotaExceeded', _P.QUOTA,
                 REGION, 'spot core quota'),
    ErrorPattern(r'QuotaExceeded|exceeding( approved)? quota', _P.QUOTA,
                 REGION),
    ErrorPattern(r'OperationNotAllowed.*quota|quota.*OperationNotAllowed',
                 _P.QUOTA, REGION),
    # -- subscription / account state.
    ErrorPattern(r'ReadOnlyDisabledSubscription', _P.PERMISSION, CLOUD,
                 'subscription disabled (reference blocks all of Azure)'),
    ErrorPattern(r'SubscriptionNotRegistered', _P.PERMISSION, CLOUD,
                 'resource provider not registered'),
    ErrorPattern(r'SubscriptionNotFound', _P.PERMISSION, CLOUD),
    ErrorPattern(r'ResourcePurchaseValidationFailed', _P.PERMISSION, CLOUD,
                 'billing/offer cannot purchase this SKU'),
    ErrorPattern(r'RequestDisallowedByPolicy|DisallowedProvider',
                 _P.PERMISSION, CLOUD, 'org policy forbids the request'),
    ErrorPattern(r'ClientAuthenticationError|AuthorizationFailed|'
                 r'AuthenticationFailed', _P.PERMISSION, CLOUD),
    ErrorPattern(r'InvalidAuthenticationToken|ExpiredAuthenticationToken',
                 _P.PERMISSION, CLOUD),
    ErrorPattern(r'ProvisioningDisabled', _P.PERMISSION, REGION),
    # -- config.
    ErrorPattern(r'ImageNotFound|PlatformImageNotFound|'
                 r'InvalidImageReference', _P.CONFIG, ABORT),
    ErrorPattern(r'InvalidTemplateDeployment|InvalidTemplate', _P.CONFIG,
                 ABORT),
    ErrorPattern(r'InvalidParameter|BadRequest', _P.CONFIG, ABORT),
    ErrorPattern(r'ResourceGroupNotFound', _P.CONFIG, REGION,
                 'resource groups live in one region'),
    ErrorPattern(r'ResourceNotFound', _P.CONFIG, REGION),
    ErrorPattern(r'VMMarketplaceInvalidInput', _P.CONFIG, ABORT),
    # -- transient.
    ErrorPattern(r'TooManyRequests|RetryableError', _P.TRANSIENT, ZONE),
    ErrorPattern(r'InternalServerError|ServerTimeout|ServiceUnavailable|'
                 r'GatewayTimeout|InternalExecutionError',
                 _P.TRANSIENT, ZONE),
)

# ---------------------------------------------------------------------------
# Kubernetes: API error bodies + pod/scheduler condition messages.
# A k8s "zone" is the cluster's node pool (zones_provision_loop yields
# None); capacity blocks let the caller fail over to another context
# or cloud. Reference: the k8s paths of FailoverCloudErrorHandlerV2.
K8S_PATTERNS: Tuple[ErrorPattern, ...] = (
    # -- capacity / scheduling.
    ErrorPattern(r'Unschedulable|FailedScheduling', _P.CAPACITY, ZONE),
    ErrorPattern(r'Insufficient (cpu|memory|ephemeral-storage|'
                 r'[\w./-]*tpu[\w./-]*|nvidia\.com/gpu)',
                 _P.CAPACITY, ZONE),
    ErrorPattern(r'No nodes are available|nodes? didn.t match',
                 _P.CAPACITY, ZONE),
    ErrorPattern(r'Preempting|preempted|Evicted', _P.CAPACITY, ZONE),
    # -- quota.
    ErrorPattern(r'exceeded quota|ResourceQuota', _P.QUOTA, REGION),
    ErrorPattern(r'LimitRange|maximum.{0,40}limit', _P.QUOTA, REGION),
    # -- permission (cluster-scoped: another context/cloud may work).
    ErrorPattern(r'Forbidden|forbidden', _P.PERMISSION, CLOUD),
    ErrorPattern(r'Unauthorized|cannot (create|get|list|delete) '
                 r'resource|RBAC', _P.PERMISSION, CLOUD),
    # -- config.
    ErrorPattern(r'InvalidImageName|invalid reference format',
                 _P.CONFIG, ABORT),
    ErrorPattern(r'admission webhook.{0,80}denied', _P.CONFIG, CLOUD),
    ErrorPattern(r'Invalid value|unknown field|BadRequest|'
                 r'is invalid', _P.CONFIG, ABORT),
    # -- transient.
    ErrorPattern(r'ImagePullBackOff|ErrImagePull', _P.TRANSIENT, ZONE,
                 'registry hiccup (a WRONG image matches the config '
                 'rows above)'),
    ErrorPattern(r'TooManyRequests|etcdserver|leader changed',
                 _P.TRANSIENT, ZONE),
    ErrorPattern(r'timeout|timed out|connection refused|'
                 r'ServiceUnavailable|InternalError',
                 _P.TRANSIENT, ZONE),
)

_TABLES = {
    'gcp': GCP_PATTERNS,
    'aws': AWS_PATTERNS,
    'azure': AZURE_PATTERNS,
    'kubernetes': K8S_PATTERNS,
}


def classify(cloud: str, code: str, message: str = ''
             ) -> Optional[ErrorPattern]:
    """First matching pattern for `"{code}: {message}"`, or None.

    This is the library's ONLY entry point: each cloud's
    `_classify_error` consults it first and applies its own
    status-code fallback on a miss (an unmatched error must degrade to
    TRANSIENT/zone — walk on — rather than guess a broader block).
    """
    text = f'{code}: {message}' if message else str(code)
    for pat in _TABLES[cloud]:
        if pat.matches(text):
            return pat
    return None
