"""Autostop hook for Local clusters: the cluster stops/terminates itself.

Reference pattern: sky/skylet/autostop_lib.py — the cluster executes
the stop from within, using its own credentials. For local sandboxes
that reduces to invoking the local provisioner.
"""
from __future__ import annotations

import argparse


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--cluster', required=True)
    parser.add_argument('--action', choices=['stop', 'terminate'],
                        default='stop')
    args = parser.parse_args()
    from skypilot_tpu.provision.local import instance
    if args.action == 'stop':
        instance.stop_instances(args.cluster)
    else:
        instance.terminate_instances(args.cluster)


if __name__ == '__main__':
    main()
