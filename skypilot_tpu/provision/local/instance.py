"""Local provisioner: sandbox-dir "hosts" with real agent processes.

Emulates a TPU slice's host layout: one Task node = `tpu_num_hosts`
sandboxes, each with its own agent process on 127.0.0.1:<port>. The
whole backend path (bootstrap, gang exec, logs, autostop, teardown)
runs for real — the role the reference fills with mocked clouds +
kind clusters (SURVEY §4).
"""
from __future__ import annotations

import json
import os
import shutil
import socket
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import constants
from skypilot_tpu.provision import common
from skypilot_tpu.utils import subprocess_utils


def _cluster_dir(cluster_name_on_cloud: str) -> str:
    return os.path.join(constants.local_clusters_dir(), cluster_name_on_cloud)


def _meta_path(cluster_name_on_cloud: str) -> str:
    return os.path.join(_cluster_dir(cluster_name_on_cloud), 'meta.json')


def _load_meta(cluster_name_on_cloud: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_meta_path(cluster_name_on_cloud), 'r',
                  encoding='utf-8') as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _save_meta(cluster_name_on_cloud: str, meta: Dict[str, Any]) -> None:
    os.makedirs(_cluster_dir(cluster_name_on_cloud), exist_ok=True)
    with open(_meta_path(cluster_name_on_cloud), 'w', encoding='utf-8') as f:
        json.dump(meta, f, indent=1)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _start_agent(host: Dict[str, Any], cluster: str,
                 secret: Optional[str] = None) -> int:
    agent_home = os.path.join(host['dir'], '.sky-tpu-agent')
    if secret is not None:
        os.makedirs(agent_home, exist_ok=True)
        sp = os.path.join(agent_home, 'agent_secret')
        with open(sp, 'w', encoding='utf-8') as f:
            f.write(secret)
        os.chmod(sp, 0o600)
    from skypilot_tpu import sky_config
    log_store = sky_config.get_nested(('logs', 'store'))
    if log_store:
        os.makedirs(agent_home, exist_ok=True)
        with open(os.path.join(agent_home, 'log_store'), 'w',
                  encoding='utf-8') as f:
            f.write(str(log_store))
    cmd = [sys.executable, '-m', 'skypilot_tpu.agent.agent',
           '--port', str(host['agent_port']),
           '--home', agent_home,
           '--cluster', cluster,
           '--bind', '127.0.0.1']
    if host['is_head']:
        cmd.append('--head')
    env = dict(os.environ)
    env['HOME'] = host['dir']
    env.setdefault('PYTHONPATH', '')
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    env['PYTHONPATH'] = f'{repo_root}:{env["PYTHONPATH"]}'
    pid = subprocess_utils.launch_daemon(
        cmd, log_path=os.path.join(host['dir'], 'agent.log'), env=env,
        cwd=host['dir'])
    return pid


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    del region
    meta = _load_meta(cluster_name_on_cloud)
    hosts_per_node = int(config.provider_config.get('tpu_num_hosts') or 1)
    num_nodes = config.count
    created: List[str] = []
    resumed: List[str] = []

    if meta is None:
        hosts = []
        for node in range(num_nodes):
            for hrank in range(hosts_per_node):
                host_id = f'host-{node}-{hrank}'
                host_dir = os.path.join(_cluster_dir(cluster_name_on_cloud),
                                        host_id)
                os.makedirs(host_dir, exist_ok=True)
                hosts.append({
                    'id': host_id,
                    'dir': host_dir,
                    'agent_port': _free_port(),
                    'agent_pid': -1,
                    'node_rank': node,
                    'host_rank': hrank,
                    'is_head': node == 0 and hrank == 0,
                })
        import secrets as secrets_lib
        meta = {
            'cluster': cluster_name_on_cloud,
            'num_nodes': num_nodes,
            'hosts_per_node': hosts_per_node,
            'hosts': hosts,
            'provider_config': config.provider_config,
            'created_at': time.time(),
            'agent_secret': secrets_lib.token_hex(16),
        }
        created = [h['id'] for h in hosts]
    else:
        if (meta['num_nodes'] != num_nodes or
                meta['hosts_per_node'] != hosts_per_node):
            raise RuntimeError(
                f'Cluster {cluster_name_on_cloud} exists with different '
                f'shape ({meta["num_nodes"]}x{meta["hosts_per_node"]}); '
                f'requested {num_nodes}x{hosts_per_node}.')

    # (Re)start dead agents — also the resume-stopped path.
    for host in meta['hosts']:
        if not subprocess_utils.process_alive(host['agent_pid']):
            host['agent_pid'] = _start_agent(host, cluster_name_on_cloud,
                                             meta.get('agent_secret'))
            if host['id'] not in created:
                resumed.append(host['id'])
    meta['status'] = 'running'
    _save_meta(cluster_name_on_cloud, meta)

    head = next(h for h in meta['hosts'] if h['is_head'])
    return common.ProvisionRecord(
        provider_name='local',
        cluster_name=cluster_name_on_cloud,
        region='local',
        zone='local-a',
        head_instance_id=head['id'],
        created_instance_ids=created,
        resumed_instance_ids=resumed,
    )


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None,
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    del region, state, provider_config  # agents start instantly


def _kill_agents(meta: Dict[str, Any]) -> None:
    for host in meta.get('hosts', []):
        pid = host.get('agent_pid', -1)
        if pid > 0:
            subprocess_utils.kill_process_tree(pid)
        host['agent_pid'] = -1


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    del provider_config, worker_only
    meta = _load_meta(cluster_name_on_cloud)
    if meta is None:
        return
    _kill_agents(meta)
    meta['status'] = 'stopped'
    _save_meta(cluster_name_on_cloud, meta)


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    del provider_config, worker_only
    meta = _load_meta(cluster_name_on_cloud)
    if meta is not None:
        _kill_agents(meta)
    shutil.rmtree(_cluster_dir(cluster_name_on_cloud), ignore_errors=True)


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    del provider_config
    meta = _load_meta(cluster_name_on_cloud)
    if meta is None:
        return {}
    out: Dict[str, Optional[str]] = {}
    for host in meta['hosts']:
        alive = subprocess_utils.process_alive(host.get('agent_pid', -1))
        status = 'running' if alive else 'stopped'
        if non_terminated_only and status is None:
            continue
        out[host['id']] = status
    return out


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    del region, provider_config
    meta = _load_meta(cluster_name_on_cloud)
    if meta is None:
        raise RuntimeError(f'Local cluster {cluster_name_on_cloud} not found')
    instances = []
    sandbox_dirs = {}
    for host in meta['hosts']:
        instances.append(common.InstanceInfo(
            instance_id=host['id'],
            internal_ip='127.0.0.1',
            external_ip='127.0.0.1',
            ssh_port=-1,
            agent_port=host['agent_port'],
            node_rank=host['node_rank'],
            host_rank=host['host_rank'],
        ))
        sandbox_dirs[host['id']] = host['dir']
    head = next(h for h in meta['hosts'] if h['is_head'])
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head['id'],
        provider_name='local',
        provider_config=meta.get('provider_config', {}),
        ssh_user=os.environ.get('USER', 'root'),
        ssh_private_key=None,
        custom={'sandbox_dirs': sandbox_dirs,
                'agent_secret': meta.get('agent_secret')},
    )


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    pass  # localhost: nothing to open


def cleanup_ports(cluster_name_on_cloud: str, ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    pass


# -- volume ops: host directories (the dev analog of a PD/PVC) --------------
def _volume_dir(name: str) -> str:
    return os.path.join(constants.sky_home(), 'local_volumes', name)


def apply_volume(config: Dict[str, Any]) -> Dict[str, Any]:
    d = _volume_dir(config['name'])
    os.makedirs(d, exist_ok=True)
    return {'name': config['name'], 'path': d, 'status': 'READY'}


def delete_volume(config: Dict[str, Any]) -> None:
    shutil.rmtree(_volume_dir(config['name']), ignore_errors=True)


def attach_volume(config: Dict[str, Any], instance_id: str) -> str:
    """Local volumes 'attach' by path: the backend symlinks the volume
    dir to the task's mount path inside each sandbox."""
    del instance_id
    d = _volume_dir(config['name'])
    os.makedirs(d, exist_ok=True)
    return d
