"""SSH-pool provisioner: allocate BYO hosts, bookkeeping in state dir.

"Provisioning" = claiming free pool hosts for a cluster (allocations
persisted as JSON under the state dir with a file lock); teardown
releases them. Runtime bootstrap happens through the normal
instance_setup SSH path.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import constants
from skypilot_tpu import exceptions
from skypilot_tpu.clouds import ssh as ssh_cloud
from skypilot_tpu.provision import common
from skypilot_tpu.utils import locks


def _alloc_path() -> str:
    return os.path.join(constants.sky_home(), 'ssh_allocations.json')


def _load_allocations() -> Dict[str, Any]:
    try:
        with open(_alloc_path(), 'r', encoding='utf-8') as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _save_allocations(alloc: Dict[str, Any]) -> None:
    os.makedirs(os.path.dirname(_alloc_path()), exist_ok=True)
    with open(_alloc_path(), 'w', encoding='utf-8') as f:
        json.dump(alloc, f, indent=1)


def list_allocations() -> Dict[str, Any]:
    """Public read view of cluster->hosts allocations (CLI uses this
    for the pool busy-check)."""
    return _load_allocations()


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    pc = dict(config.provider_config)
    pool_name = pc.get('pool') or region
    pools = ssh_cloud.load_pools()
    if pool_name not in pools:
        raise exceptions.ProvisionerError(
            f'SSH pool {pool_name!r} not found.',
            category=exceptions.ProvisionerError.CONFIG)
    hosts = pools[pool_name]['hosts']

    with locks.FileLock(_alloc_path() + '.lock'):
        alloc = _load_allocations()
        mine = alloc.get(cluster_name_on_cloud)
        if mine is None:
            taken = {h['ip'] for entry in alloc.values()
                     for h in entry['hosts']}
            free = [h for h in hosts if h['ip'] not in taken]
            if len(free) < config.count:
                raise exceptions.ProvisionerError(
                    f'Pool {pool_name!r} has {len(free)} free hosts; '
                    f'need {config.count}.',
                    category=exceptions.ProvisionerError.CAPACITY)
            mine = {'pool': pool_name, 'hosts': free[:config.count],
                    'created_at': time.time()}
            alloc[cluster_name_on_cloud] = mine
            _save_allocations(alloc)
        created = [h['ip'] for h in mine['hosts']]

    pc['pool'] = pool_name
    return common.ProvisionRecord(
        provider_name='ssh',
        cluster_name=cluster_name_on_cloud,
        region=pool_name,
        zone=None,
        head_instance_id=created[0],
        created_instance_ids=created,
        provider_config=pc,
    )


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None,
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    del region, cluster_name_on_cloud, state, provider_config
    # Hosts already exist; reachability is validated by agent bootstrap.


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    raise exceptions.NotSupportedError(
        'BYO SSH hosts cannot be stopped; use down to release them.')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    del provider_config, worker_only
    with locks.FileLock(_alloc_path() + '.lock'):
        alloc = _load_allocations()
        if cluster_name_on_cloud in alloc:
            del alloc[cluster_name_on_cloud]
            _save_allocations(alloc)


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    del provider_config, non_terminated_only
    alloc = _load_allocations().get(cluster_name_on_cloud)
    if alloc is None:
        return {}
    return {h['ip']: 'running' for h in alloc['hosts']}


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    del region
    alloc = _load_allocations().get(cluster_name_on_cloud)
    if alloc is None:
        raise exceptions.FetchClusterInfoError(
            exceptions.FetchClusterInfoError.Reason.HEAD)
    instances = []
    for rank, host in enumerate(alloc['hosts']):
        instances.append(common.InstanceInfo(
            instance_id=host['ip'],
            internal_ip=host['ip'],
            external_ip=host['ip'],
            ssh_port=host.get('port', 22),
            agent_port=constants.AGENT_PORT,
            node_rank=rank,
            host_rank=0,
        ))
    first = alloc['hosts'][0]
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=instances[0].instance_id,
        provider_name='ssh',
        provider_config=dict(provider_config or {}),
        ssh_user=first.get('user', 'root'),
        ssh_private_key=first.get('identity_file'),
    )


def open_ports(cluster_name_on_cloud, ports, provider_config=None):
    pass  # user-managed network


def cleanup_ports(cluster_name_on_cloud, ports, provider_config=None):
    pass
