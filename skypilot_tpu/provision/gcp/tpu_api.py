"""Thin client for the GCP TPU API (tpu.googleapis.com, v2).

Reference analog: sky/provision/gcp/instance_utils.py GCPTPUVMInstance
(:1258) — but the reference drives TPUs through discovery-client
googleapiclient; this build speaks REST directly (google.auth token +
requests), with QueuedResources for spot/pod capacity.

All HTTP goes through `_request()` so tests can fake the API surface
(the reference's fake-cloud strategy, SURVEY §4).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import requests

from skypilot_tpu import exceptions

_TPU_API = 'https://tpu.googleapis.com/v2'
_SCOPES = ['https://www.googleapis.com/auth/cloud-platform']

_session: Optional[Any] = None


def _get_session():
    """AuthorizedSession via application-default credentials."""
    global _session
    if _session is None:
        import google.auth
        import google.auth.transport.requests
        credentials, _ = google.auth.default(scopes=_SCOPES)
        _session = google.auth.transport.requests.AuthorizedSession(
            credentials)
    return _session


def default_project() -> str:
    import google.auth
    _, project = google.auth.default(scopes=_SCOPES)
    if project is None:
        raise exceptions.NoCloudAccessError(
            'No GCP project configured; set gcp.project_id in config or '
            'run `gcloud config set project`.')
    return project


def _request(method: str, path: str, *, json_body: Optional[Dict] = None,
             params: Optional[Dict] = None) -> Dict[str, Any]:
    """Single HTTP call to the TPU API; raises ProvisionerError on 4xx/5xx."""
    session = _get_session()
    url = f'{_TPU_API}/{path}'
    resp = session.request(method, url, json=json_body, params=params,
                           timeout=60)
    if resp.status_code == 404:
        raise exceptions.FetchClusterInfoError(
            exceptions.FetchClusterInfoError.Reason.HEAD)
    if resp.status_code >= 400:
        category, scope = _classify_error(resp.status_code, resp.text)
        raise exceptions.ProvisionerError(
            f'TPU API {method} {path} -> {resp.status_code}: '
            f'{resp.text[:500]}', category=category, scope=scope)
    return resp.json() if resp.text else {}


def _classify_error(status_code: int, text: str) -> tuple:
    """(category, scope) for a TPU/GCE API error.

    The per-cloud pattern table (provision/failover_patterns.py — the
    declarative form of the reference's FailoverCloudErrorHandlerV2,
    cloud_vm_ray_backend.py:522) is consulted first; HTTP-status
    heuristics catch whatever no pattern knows."""
    from skypilot_tpu.provision import failover_patterns
    pat = failover_patterns.classify('gcp', str(status_code), text)
    if pat is not None:
        return pat.category, pat.scope
    lower = text.lower()
    if status_code == 429:
        # Unmatched 429s (no 'per minute' throttle text) are capacity.
        return exceptions.ProvisionerError.CAPACITY, None
    if status_code == 403 and 'quota' in lower:
        return exceptions.ProvisionerError.QUOTA, None
    if status_code in (401, 403):
        return exceptions.ProvisionerError.PERMISSION, None
    if status_code == 400:
        return exceptions.ProvisionerError.CONFIG, None
    return exceptions.ProvisionerError.TRANSIENT, None


# ---------------------------------------------------------------------------
# Nodes
# ---------------------------------------------------------------------------
def create_node(project: str, zone: str, node_id: str,
                accelerator_type: str, runtime_version: str,
                *, topology: Optional[str] = None,
                spot: bool = False, labels: Optional[Dict] = None,
                ssh_pub_key: Optional[str] = None,
                startup_script: Optional[str] = None,
                data_disk_gb: Optional[int] = None) -> Dict[str, Any]:
    parent = f'projects/{project}/locations/{zone}'
    body: Dict[str, Any] = {
        'runtimeVersion': runtime_version,
        'labels': labels or {},
        'networkConfig': {'enableExternalIps': True},
    }
    if topology:
        body['acceleratorConfig'] = {
            'type': _accel_config_type(accelerator_type),
            'topology': topology,
        }
    else:
        body['acceleratorType'] = accelerator_type
    if spot:
        body['schedulingConfig'] = {'preemptible': True, 'spot': True}
    metadata = {}
    if ssh_pub_key:
        metadata['ssh-keys'] = f'skypilot:{ssh_pub_key}'
    if startup_script:
        metadata['startup-script'] = startup_script
    if metadata:
        body['metadata'] = metadata
    return _request('POST', f'{parent}/nodes', json_body=body,
                    params={'nodeId': node_id})


def _accel_config_type(accelerator_type: str) -> str:
    # 'v5litepod-16' -> 'V5LITE_POD'; 'v5p-128' -> 'V5P'; 'v4-8' -> 'V4'
    prefix = accelerator_type.split('-')[0]
    return {'v2': 'V2', 'v3': 'V3', 'v4': 'V4', 'v5litepod': 'V5LITE_POD',
            'v5p': 'V5P', 'v6e': 'V6E'}.get(prefix, prefix.upper())


def get_node(project: str, zone: str, node_id: str) -> Dict[str, Any]:
    return _request(
        'GET', f'projects/{project}/locations/{zone}/nodes/{node_id}')


def list_nodes(project: str, zone: str) -> List[Dict[str, Any]]:
    out = _request('GET', f'projects/{project}/locations/{zone}/nodes')
    return out.get('nodes', [])


def delete_node(project: str, zone: str, node_id: str) -> Dict[str, Any]:
    return _request(
        'DELETE', f'projects/{project}/locations/{zone}/nodes/{node_id}')


def stop_node(project: str, zone: str, node_id: str) -> Dict[str, Any]:
    return _request(
        'POST',
        f'projects/{project}/locations/{zone}/nodes/{node_id}:stop')


def start_node(project: str, zone: str, node_id: str) -> Dict[str, Any]:
    return _request(
        'POST',
        f'projects/{project}/locations/{zone}/nodes/{node_id}:start')


# ---------------------------------------------------------------------------
# Queued resources (spot + large pods)
# ---------------------------------------------------------------------------
def create_queued_resource(project: str, zone: str, qr_id: str,
                           node_id: str, accelerator_type: str,
                           runtime_version: str, *,
                           spot: bool = False,
                           topology: Optional[str] = None,
                           ssh_pub_key: Optional[str] = None,
                           valid_until_seconds: int = 3600
                           ) -> Dict[str, Any]:
    parent = f'projects/{project}/locations/{zone}'
    node: Dict[str, Any] = {
        'runtimeVersion': runtime_version,
        'networkConfig': {'enableExternalIps': True},
    }
    if topology:
        node['acceleratorConfig'] = {
            'type': _accel_config_type(accelerator_type),
            'topology': topology,
        }
    else:
        node['acceleratorType'] = accelerator_type
    if ssh_pub_key:
        node['metadata'] = {'ssh-keys': f'skypilot:{ssh_pub_key}'}
    body: Dict[str, Any] = {
        'tpu': {'nodeSpec': [{'parent': parent, 'nodeId': node_id,
                              'node': node}]},
        'queueingPolicy': {
            'validUntilDuration': {'seconds': valid_until_seconds},
        },
    }
    if spot:
        body['spot'] = {}
    return _request('POST', f'{parent}/queuedResources', json_body=body,
                    params={'queuedResourceId': qr_id})


def get_queued_resource(project: str, zone: str,
                        qr_id: str) -> Dict[str, Any]:
    return _request(
        'GET',
        f'projects/{project}/locations/{zone}/queuedResources/{qr_id}')


def delete_queued_resource(project: str, zone: str,
                           qr_id: str) -> Dict[str, Any]:
    return _request(
        'DELETE',
        f'projects/{project}/locations/{zone}/queuedResources/{qr_id}',
        params={'force': 'true'})


# ---------------------------------------------------------------------------
# Waiting
# ---------------------------------------------------------------------------
def wait_node_state(project: str, zone: str, node_id: str,
                    target_states=('READY',), timeout: float = 1800,
                    poll: float = 10,
                    qr_id: Optional[str] = None) -> Dict[str, Any]:
    """Poll until the node reaches a target state.

    A 404 is NOT fatal: a queued resource may not have materialized the
    node yet — keep polling (and fail fast if the QR itself failed).
    """
    deadline = time.time() + timeout
    while True:
        state = None
        try:
            node = get_node(project, zone, node_id)
            state = node.get('state')
            if state in target_states:
                return node
            if state in ('PREEMPTED', 'TERMINATED', 'FAILED'):
                raise exceptions.ProvisionerError(
                    f'TPU node {node_id} entered state {state}.')
        except exceptions.FetchClusterInfoError:
            if qr_id is not None:
                try:
                    qr = get_queued_resource(project, zone, qr_id)
                    qr_state = (qr.get('state') or {}).get('state')
                    if qr_state in ('FAILED', 'SUSPENDED'):
                        raise exceptions.ProvisionerError(
                            f'Queued resource {qr_id} entered state '
                            f'{qr_state}.')
                except exceptions.FetchClusterInfoError:
                    pass
        if time.time() > deadline:
            raise exceptions.ProvisionerError(
                f'Timed out waiting for TPU node {node_id} '
                f'(state={state}).')
        time.sleep(poll)
