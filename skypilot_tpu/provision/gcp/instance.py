"""GCP provisioner: TPU slices as the unit of provisioning.

Reference: sky/provision/gcp/ — but TPU-first: one Task node = one
slice = `tpu_num_hosts` TPU-VM workers created atomically by the TPU
API (the gang, SURVEY §2.4); multi-slice tasks create N nodes named
`<cluster>-<i>`. QueuedResources is used for spot and pod slices
(capacity-queued creation), plain nodes otherwise.

CPU/GPU hosts on GCP are served by the GCE VM path (`gce_api.py`):
requests without a TPU accelerator route to instances.insert-based
provisioning, sharing this module's wait/query/terminate plumbing.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_config
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import gce_api
from skypilot_tpu.provision.gcp import tpu_api


def _project(provider_config: Optional[Dict[str, Any]] = None) -> str:
    cfg = sky_config.get_nested(('gcp', 'project_id'))
    if cfg:
        return str(cfg)
    if provider_config and provider_config.get('project_id'):
        return str(provider_config['project_id'])
    return tpu_api.default_project()


def _node_names(cluster_name_on_cloud: str, count: int) -> List[str]:
    if count == 1:
        return [cluster_name_on_cloud]
    return [f'{cluster_name_on_cloud}-{i}' for i in range(count)]


def _ssh_pub_key() -> Optional[str]:
    from skypilot_tpu import authentication
    try:
        _, pub = authentication.get_or_generate_keys()
        return pub
    except Exception:  # pylint: disable=broad-except
        return None


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    del region
    pc = config.provider_config
    zone = pc['zone']
    project = _project(pc)
    if not pc.get('tpu_vm'):
        return _run_gce_instances(project, zone, cluster_name_on_cloud,
                                  config)
    accelerator_type = pc['tpu_accelerator_type']
    runtime_version = pc['runtime_version']
    use_qr = bool(pc.get('tpu_use_queued_resources'))
    spot = bool(pc.get('use_spot'))
    topology = pc.get('tpu_topology')
    names = _node_names(cluster_name_on_cloud, config.count)
    pub_key = _ssh_pub_key()

    created, resumed = [], []
    for name in names:
        try:
            node = tpu_api.get_node(project, zone, name)
            state = node.get('state')
            if state == 'STOPPED':
                tpu_api.start_node(project, zone, name)
                resumed.append(name)
                continue
            if state in ('PREEMPTED', 'TERMINATED', 'FAILED'):
                # Dead node with the name we need: replace it.
                try:
                    tpu_api.delete_queued_resource(project, zone,
                                                   f'{name}-qr')
                except (exceptions.ProvisionerError,
                        exceptions.FetchClusterInfoError):
                    pass
                tpu_api.delete_node(project, zone, name)
            else:
                continue  # exists and healthy/creating
        except exceptions.FetchClusterInfoError:
            pass  # create below
        if use_qr:
            tpu_api.create_queued_resource(
                project, zone, qr_id=f'{name}-qr', node_id=name,
                accelerator_type=accelerator_type,
                runtime_version=runtime_version, spot=spot,
                topology=topology, ssh_pub_key=pub_key)
        else:
            tpu_api.create_node(
                project, zone, node_id=name,
                accelerator_type=accelerator_type,
                runtime_version=runtime_version, spot=spot,
                topology=topology, ssh_pub_key=pub_key,
                labels={'skypilot-cluster': cluster_name_on_cloud})
        created.append(name)

    return common.ProvisionRecord(
        provider_name='gcp',
        cluster_name=cluster_name_on_cloud,
        region=zone.rsplit('-', 1)[0],
        zone=zone,
        head_instance_id=names[0],
        created_instance_ids=created,
        resumed_instance_ids=resumed,
        provider_config=dict(pc),
    )


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None,
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    del region, state
    pc = provider_config or {}
    zone = pc.get('zone')
    if zone is None:
        raise exceptions.ProvisionerError(
            'wait_instances needs provider_config with a zone.')
    project = _project(pc)
    count = int(pc.get('num_nodes', 1))
    if not pc.get('tpu_vm'):
        from skypilot_tpu.provision.gcp import gce_api
        for name in _gce_names(cluster_name_on_cloud, count):
            gce_api.wait_instance_status(project, zone, name)
        return
    for name in _node_names(cluster_name_on_cloud, count):
        qr_id = (f'{name}-qr'
                 if pc.get('tpu_use_queued_resources') else None)
        tpu_api.wait_node_state(project, zone, name, qr_id=qr_id)


def _iter_cluster_nodes(project: str, zone: str,
                        cluster_name_on_cloud: str) -> List[Dict[str, Any]]:
    out = []
    for node in tpu_api.list_nodes(project, zone):
        name = node.get('name', '').rsplit('/', 1)[-1]
        if name == cluster_name_on_cloud or \
                name.startswith(f'{cluster_name_on_cloud}-'):
            node['_short_name'] = name
            out.append(node)
    return out


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    del worker_only
    pc = provider_config or {}
    zone, project = pc['zone'], _project(pc)
    if not pc.get('tpu_vm'):
        from skypilot_tpu.provision.gcp import gce_api
        for inst in gce_api.list_instances(project, zone,
                                           cluster_name_on_cloud):
            gce_api.stop_instance(project, zone, inst['name'])
        return
    for node in _iter_cluster_nodes(project, zone, cluster_name_on_cloud):
        tpu_api.stop_node(project, zone, node['_short_name'])


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    del worker_only
    pc = provider_config or {}
    zone = pc.get('zone')
    if zone is None:
        return
    project = _project(pc)
    if not pc.get('tpu_vm'):
        from skypilot_tpu.provision.gcp import gce_api
        for inst in gce_api.list_instances(project, zone,
                                           cluster_name_on_cloud):
            try:
                gce_api.delete_instance(project, zone, inst['name'])
            except exceptions.FetchClusterInfoError:
                pass
        return
    for node in _iter_cluster_nodes(project, zone, cluster_name_on_cloud):
        name = node['_short_name']
        try:
            tpu_api.delete_queued_resource(project, zone, f'{name}-qr')
        except (exceptions.ProvisionerError,
                exceptions.FetchClusterInfoError):
            pass
        try:
            tpu_api.delete_node(project, zone, name)
        except exceptions.FetchClusterInfoError:
            pass


# Unknown/transient states (REPAIRING, HIDING, ...) map to 'pending'
# so a live-but-in-maintenance cluster is never reported as terminated.
_TERMINAL_STATES = {'PREEMPTED', 'TERMINATED', 'DELETING', 'FAILED'}
_STATE_MAP = {
    'READY': 'running',
    'CREATING': 'pending',
    'STARTING': 'pending',
    'RESTARTING': 'pending',
    'STOPPED': 'stopped',
    'STOPPING': 'stopping',
}


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    pc = provider_config or {}
    zone, project = pc['zone'], _project(pc)
    if not pc.get('tpu_vm'):
        return _gce_query(project, zone, cluster_name_on_cloud,
                          non_terminated_only)
    out: Dict[str, Optional[str]] = {}
    for node in _iter_cluster_nodes(project, zone, cluster_name_on_cloud):
        state = node.get('state')
        status = (None if state in _TERMINAL_STATES
                  else _STATE_MAP.get(state, 'pending'))
        if non_terminated_only and status is None:
            continue
        out[node['_short_name']] = status
    return out


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    del region
    pc = provider_config or {}
    zone, project = pc['zone'], _project(pc)
    if not pc.get('tpu_vm'):
        return _gce_cluster_info(project, zone, cluster_name_on_cloud, pc)
    from skypilot_tpu import constants
    instances: List[common.InstanceInfo] = []
    nodes = sorted(_iter_cluster_nodes(project, zone, cluster_name_on_cloud),
                   key=lambda n: n['_short_name'])
    if not nodes:
        raise exceptions.FetchClusterInfoError(
            exceptions.FetchClusterInfoError.Reason.HEAD)
    for node_rank, node in enumerate(nodes):
        endpoints = node.get('networkEndpoints', [])
        for host_rank, ep in enumerate(endpoints):
            external = (ep.get('accessConfig') or {}).get('externalIp')
            instances.append(common.InstanceInfo(
                instance_id=f'{node["_short_name"]}/{host_rank}',
                internal_ip=ep.get('ipAddress', ''),
                external_ip=external,
                ssh_port=22,
                agent_port=constants.AGENT_PORT,
                node_rank=node_rank,
                host_rank=host_rank,
            ))
    head = instances[0]
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=head.instance_id,
        provider_name='gcp',
        provider_config=pc,
        ssh_user='skypilot',
        ssh_private_key='~/.ssh/sky-key',
    )


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    """Firewall rules via the compute API (tracked; TPU-VM default VPC
    already allows intra-VPC agent traffic, which the gang path uses)."""
    del cluster_name_on_cloud, ports, provider_config


def cleanup_ports(cluster_name_on_cloud: str, ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    del cluster_name_on_cloud, ports, provider_config


# ---------------------------------------------------------------------------
# GCE (CPU/GPU VM) path
# ---------------------------------------------------------------------------
_GCE_STATUS_MAP = {
    'RUNNING': 'running',
    'PROVISIONING': 'pending',
    'STAGING': 'pending',
    'REPAIRING': 'pending',
    'STOPPING': 'stopping',
    'SUSPENDED': 'stopped',
    'TERMINATED': 'stopped',  # GCE TERMINATED == stopped-but-exists
}


def _gce_names(cluster_name_on_cloud: str, count: int) -> List[str]:
    return _node_names(cluster_name_on_cloud, count)


def _run_gce_instances(project: str, zone: str, cluster_name_on_cloud: str,
                       config: common.ProvisionConfig
                       ) -> common.ProvisionRecord:
    from skypilot_tpu.provision.gcp import gce_api
    pc = config.provider_config
    machine_type = pc.get('instance_type')
    if not machine_type:
        raise exceptions.ProvisionerError(
            'GCE path needs an instance_type.',
            category=exceptions.ProvisionerError.CONFIG)
    names = _gce_names(cluster_name_on_cloud, config.count)
    pub_key = _ssh_pub_key()
    created, resumed = [], []
    for name in names:
        try:
            inst = gce_api.get_instance(project, zone, name)
            if inst.get('status') in ('TERMINATED', 'SUSPENDED'):
                gce_api.start_instance(project, zone, name)
                resumed.append(name)
            continue
        except exceptions.FetchClusterInfoError:
            pass
        gce_api.create_instance(
            project, zone, name, machine_type,
            accelerators=pc.get('accelerators') or None,
            spot=bool(pc.get('use_spot')),
            disk_size_gb=int(pc.get('disk_size') or 256),
            image=pc.get('image_id'),
            ssh_pub_key=pub_key,
            labels={'skypilot-cluster': cluster_name_on_cloud})
        created.append(name)
    return common.ProvisionRecord(
        provider_name='gcp',
        cluster_name=cluster_name_on_cloud,
        region=zone.rsplit('-', 1)[0],
        zone=zone,
        head_instance_id=names[0],
        created_instance_ids=created,
        resumed_instance_ids=resumed,
        provider_config=dict(pc),
    )


def _gce_query(project: str, zone: str, cluster_name_on_cloud: str,
               non_terminated_only: bool) -> Dict[str, Optional[str]]:
    from skypilot_tpu.provision.gcp import gce_api
    out: Dict[str, Optional[str]] = {}
    for inst in gce_api.list_instances(project, zone,
                                       cluster_name_on_cloud):
        status = _GCE_STATUS_MAP.get(inst.get('status'), 'pending')
        if non_terminated_only and status is None:
            continue
        out[inst['name']] = status
    return out


def _gce_cluster_info(project: str, zone: str, cluster_name_on_cloud: str,
                      pc: Dict[str, Any]) -> common.ClusterInfo:
    from skypilot_tpu import constants
    from skypilot_tpu.provision.gcp import gce_api
    instances = []
    items = sorted(gce_api.list_instances(project, zone,
                                          cluster_name_on_cloud),
                   key=lambda i: i['name'])
    if not items:
        raise exceptions.FetchClusterInfoError(
            exceptions.FetchClusterInfoError.Reason.HEAD)
    for rank, inst in enumerate(items):
        instances.append(common.InstanceInfo(
            instance_id=inst['name'],
            internal_ip=gce_api.internal_ip(inst),
            external_ip=gce_api.external_ip(inst),
            ssh_port=22,
            agent_port=constants.AGENT_PORT,
            node_rank=rank,
            host_rank=0,
        ))
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=instances[0].instance_id,
        provider_name='gcp',
        provider_config=dict(pc),
        ssh_user='skypilot',
        ssh_private_key='~/.ssh/sky-key',
    )


# -- volume ops (reference: sky/provision/__init__.py:235-310) --------------
def apply_volume(config: Dict[str, Any]) -> Dict[str, Any]:
    """Create (or adopt) a GCP persistent disk for a named volume."""
    pc = dict(config)
    project = _project(pc)
    zone = pc.get('zone') or sky_config.get_nested(('gcp', 'zone'))
    if not zone:
        raise exceptions.ProvisionerError(
            'GCP volumes need a zone (volume config or gcp.zone).')
    name = pc['name']
    try:
        disk = gce_api.get_disk(project, zone, name)
    except exceptions.FetchClusterInfoError:
        gce_api.create_disk(project, zone, name,
                            size_gb=int(pc.get('size_gb', 100)),
                            disk_type=pc.get('type', 'pd-balanced'),
                            labels={'skypilot-volume': name})
        disk = _wait_disk_ready(project, zone, name)
    return {'name': name, 'zone': zone, 'project': project,
            'size_gb': int(disk.get('sizeGb', pc.get('size_gb', 0))),
            'status': disk.get('status', 'READY')}


def _wait_disk_ready(project: str, zone: str, name: str,
                     timeout: float = 180.0) -> Dict[str, Any]:
    """disks.insert is an async zonal operation: poll until READY
    (tolerating the eventually-consistent 404 right after create)."""
    deadline = time.time() + timeout
    while True:
        try:
            disk = gce_api.get_disk(project, zone, name)
            if disk.get('status') == 'READY':
                return disk
        except exceptions.FetchClusterInfoError:
            pass
        if time.time() > deadline:
            raise exceptions.ProvisionerError(
                f'Disk {name} in {zone} not READY after {timeout:.0f}s.')
        time.sleep(2)


def delete_volume(config: Dict[str, Any]) -> None:
    pc = dict(config)
    project = _project(pc)
    zone = pc.get('zone') or sky_config.get_nested(('gcp', 'zone'))
    try:
        gce_api.delete_disk(project, zone, pc['name'])
    except exceptions.FetchClusterInfoError:
        pass  # already gone


def attach_volume(config: Dict[str, Any], instance_id: str) -> str:
    """Attach the volume's disk to a GCE instance; returns the device
    path the mount command should use."""
    pc = dict(config)
    project = _project(pc)
    zone = pc.get('zone') or sky_config.get_nested(('gcp', 'zone'))
    gce_api.attach_disk(project, zone, instance_id, pc['name'])
    return f'/dev/disk/by-id/google-{pc["name"]}'
