"""Thin client for the GCE compute API (CPU/GPU host VMs).

Completes the GCP provisioner beyond TPU slices: plain VMs for
controllers, CPU tasks, and GPU hosts (a2/g2 families from the
catalog). Same `_request()` seam as tpu_api for fake-API tests; the
error classifier is shared.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision.gcp import tpu_api

_COMPUTE_API = 'https://compute.googleapis.com/compute/v1'

_DEFAULT_IMAGE = ('projects/ubuntu-os-cloud/global/images/family/'
                  'ubuntu-2204-lts')

# GPU accelerator name -> GCE acceleratorType resource name
_GPU_TYPES = {
    'A100': 'nvidia-tesla-a100',
    'A100-80GB': 'nvidia-a100-80gb',
    'H100': 'nvidia-h100-80gb',
    'L4': 'nvidia-l4',
    'T4': 'nvidia-tesla-t4',
    'V100': 'nvidia-tesla-v100',
    'P100': 'nvidia-tesla-p100',
}


def _request(method: str, path: str, *, json_body: Optional[Dict] = None,
             params: Optional[Dict] = None) -> Dict[str, Any]:
    session = tpu_api._get_session()  # pylint: disable=protected-access
    url = f'{_COMPUTE_API}/{path}'
    resp = session.request(method, url, json=json_body, params=params,
                           timeout=60)
    if resp.status_code == 404:
        raise exceptions.FetchClusterInfoError(
            exceptions.FetchClusterInfoError.Reason.HEAD)
    if resp.status_code >= 400:
        category, scope = tpu_api._classify_error(  # pylint: disable=protected-access
            resp.status_code, resp.text)
        raise exceptions.ProvisionerError(
            f'GCE API {method} {path} -> {resp.status_code}: '
            f'{resp.text[:500]}', category=category, scope=scope)
    return resp.json() if resp.text else {}


def create_instance(project: str, zone: str, name: str,
                    machine_type: str, *,
                    accelerators: Optional[Dict[str, int]] = None,
                    spot: bool = False,
                    disk_size_gb: int = 256,
                    image: Optional[str] = None,
                    ssh_pub_key: Optional[str] = None,
                    labels: Optional[Dict[str, str]] = None
                    ) -> Dict[str, Any]:
    body: Dict[str, Any] = {
        'name': name,
        'machineType': f'zones/{zone}/machineTypes/{machine_type}',
        'disks': [{
            'boot': True,
            'autoDelete': True,
            'initializeParams': {
                'sourceImage': image or _DEFAULT_IMAGE,
                'diskSizeGb': str(disk_size_gb),
            },
        }],
        'networkInterfaces': [{
            'network': 'global/networks/default',
            'accessConfigs': [{'type': 'ONE_TO_ONE_NAT',
                               'name': 'External NAT'}],
        }],
        'labels': labels or {},
    }
    if accelerators:
        acc_name, count = next(iter(accelerators.items()))
        gce_type = _GPU_TYPES.get(acc_name)
        if gce_type is None:
            raise exceptions.ProvisionerError(
                f'Unknown GPU type {acc_name!r} for GCE.',
                category=exceptions.ProvisionerError.CONFIG)
        body['guestAccelerators'] = [{
            'acceleratorType':
                f'zones/{zone}/acceleratorTypes/{gce_type}',
            'acceleratorCount': count,
        }]
        body['scheduling'] = {'onHostMaintenance': 'TERMINATE'}
    if spot:
        body.setdefault('scheduling', {}).update({
            'provisioningModel': 'SPOT',
            'instanceTerminationAction': 'DELETE',
        })
    if ssh_pub_key:
        body['metadata'] = {'items': [
            {'key': 'ssh-keys', 'value': f'skypilot:{ssh_pub_key}'}]}
    return _request('POST', f'projects/{project}/zones/{zone}/instances',
                    json_body=body)


def get_instance(project: str, zone: str, name: str) -> Dict[str, Any]:
    return _request('GET',
                    f'projects/{project}/zones/{zone}/instances/{name}')


def list_instances(project: str, zone: str,
                   label_filter: Optional[str] = None
                   ) -> List[Dict[str, Any]]:
    params = {}
    if label_filter:
        params['filter'] = f'labels.skypilot-cluster={label_filter}'
    out = _request('GET', f'projects/{project}/zones/{zone}/instances',
                   params=params)
    return out.get('items', [])


def delete_instance(project: str, zone: str, name: str) -> Dict[str, Any]:
    return _request('DELETE',
                    f'projects/{project}/zones/{zone}/instances/{name}')


def stop_instance(project: str, zone: str, name: str) -> Dict[str, Any]:
    return _request(
        'POST', f'projects/{project}/zones/{zone}/instances/{name}/stop')


def start_instance(project: str, zone: str, name: str) -> Dict[str, Any]:
    return _request(
        'POST', f'projects/{project}/zones/{zone}/instances/{name}/start')


def wait_instance_status(project: str, zone: str, name: str,
                         target=('RUNNING',), timeout: float = 900,
                         poll: float = 5) -> Dict[str, Any]:
    deadline = time.time() + timeout
    while True:
        try:
            inst = get_instance(project, zone, name)
            status = inst.get('status')
            if status in target:
                return inst
            if status in ('TERMINATED', 'SUSPENDED') and \
                    'TERMINATED' not in target:
                raise exceptions.ProvisionerError(
                    f'GCE instance {name} entered {status}.')
        except exceptions.FetchClusterInfoError:
            status = None  # creation op may not have materialized yet
        if time.time() > deadline:
            raise exceptions.ProvisionerError(
                f'Timed out waiting for GCE instance {name} '
                f'(status={status}).')
        time.sleep(poll)


def external_ip(instance: Dict[str, Any]) -> Optional[str]:
    for nic in instance.get('networkInterfaces', []):
        for ac in nic.get('accessConfigs', []):
            if ac.get('natIP'):
                return ac['natIP']
    return None


def internal_ip(instance: Dict[str, Any]) -> str:
    nics = instance.get('networkInterfaces', [])
    return nics[0].get('networkIP', '') if nics else ''


# -- persistent disks (volume ops; reference: sky/provision/__init__.py
# apply_volume/delete_volume routed to sky/provision/gcp) ------------------
def create_disk(project: str, zone: str, name: str, size_gb: int,
                disk_type: str = 'pd-balanced',
                labels: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    body = {
        'name': name,
        'sizeGb': str(int(size_gb)),
        'type': f'projects/{project}/zones/{zone}/diskTypes/{disk_type}',
        'labels': dict(labels or {}),
    }
    return _request('POST', f'projects/{project}/zones/{zone}/disks',
                    json_body=body)


def get_disk(project: str, zone: str, name: str) -> Dict[str, Any]:
    return _request('GET', f'projects/{project}/zones/{zone}/disks/{name}')


def delete_disk(project: str, zone: str, name: str) -> Dict[str, Any]:
    return _request('DELETE',
                    f'projects/{project}/zones/{zone}/disks/{name}')


def attach_disk(project: str, zone: str, instance: str, disk_name: str,
                device_name: Optional[str] = None) -> Dict[str, Any]:
    body = {
        'source': f'projects/{project}/zones/{zone}/disks/{disk_name}',
        'deviceName': device_name or disk_name,
        'mode': 'READ_WRITE',
    }
    return _request(
        'POST',
        f'projects/{project}/zones/{zone}/instances/{instance}/attachDisk',
        json_body=body)


def detach_disk(project: str, zone: str, instance: str,
                device_name: str) -> Dict[str, Any]:
    return _request(
        'POST',
        f'projects/{project}/zones/{zone}/instances/{instance}/detachDisk',
        params={'deviceName': device_name})
