"""Provisioner data structures shared across clouds.

Reference: sky/provision/common.py — ProvisionConfig/ProvisionRecord/
ClusterInfo/InstanceInfo.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class ProvisionConfig:
    provider_config: Dict[str, Any]      # deploy variables from the cloud
    authentication_config: Dict[str, Any]
    count: int                            # task num_nodes (slices for TPU)
    tags: Dict[str, str]
    resume_stopped_nodes: bool = True
    ports_to_open: Optional[List[str]] = None


@dataclasses.dataclass
class ProvisionRecord:
    provider_name: str
    cluster_name: str
    region: str
    zone: Optional[str]
    head_instance_id: str
    created_instance_ids: List[str]
    resumed_instance_ids: List[str] = dataclasses.field(default_factory=list)
    # Deploy variables the instance was created with; threaded back into
    # wait/query/terminate/get_cluster_info calls.
    provider_config: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def is_instance_just_booted(self, instance_id: str) -> bool:
        return (instance_id in self.created_instance_ids or
                instance_id in self.resumed_instance_ids)


@dataclasses.dataclass
class InstanceInfo:
    """One host (a TPU-VM worker, a GCE VM, or a local sandbox)."""
    instance_id: str
    internal_ip: str
    external_ip: Optional[str]
    ssh_port: int = 22
    agent_port: int = 0        # where this host's agent listens
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)
    # TPU topology coordinates:
    node_rank: int = 0         # which Task node (slice) this host belongs to
    host_rank: int = 0         # rank within the slice

    def get_feasible_ip(self) -> str:
        return self.external_ip or self.internal_ip

    @property
    def agent_addr(self) -> str:
        """host:port reachable from *within* the cluster network."""
        return f'{self.internal_ip}:{self.agent_port}'


@dataclasses.dataclass
class ClusterInfo:
    instances: List[InstanceInfo]
    head_instance_id: str
    provider_name: str
    provider_config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    ssh_user: str = 'skypilot'
    ssh_private_key: Optional[str] = None
    # For Local clusters: sandbox dirs keyed by instance_id.
    custom: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def get_head_instance(self) -> InstanceInfo:
        for inst in self.instances:
            if inst.instance_id == self.head_instance_id:
                return inst
        raise ValueError(f'head {self.head_instance_id} not in instances')

    def sorted_instances(self) -> List[InstanceInfo]:
        """Deterministic order: (node_rank, host_rank), head first overall."""
        head = self.get_head_instance()
        rest = [i for i in self.instances
                if i.instance_id != self.head_instance_id]
        rest.sort(key=lambda i: (i.node_rank, i.host_rank))
        return [head] + rest

    @property
    def num_instances(self) -> int:
        return len(self.instances)
