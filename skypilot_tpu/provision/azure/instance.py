"""Azure provisioner: ARM VMs via the routed interface.

Reference: sky/provision/azure/instance.py (azure SDK) — same contract
(run/wait/stop/terminate/query/get_cluster_info/open_ports), driven
here by the ARM REST client (`arm_api.py`). All of a cluster's
resources live in one resource group (`sky-<cluster>-<region>`,
region-qualified so failover relaunches never collide with an
async-deleting group); nodes are
named `<cluster>-<i>` and discovered by the `skypilot-cluster` tag.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.azure import arm_api


def _node_names(cluster_name_on_cloud: str, count: int) -> List[str]:
    if count == 1:
        return [cluster_name_on_cloud]
    return [f'{cluster_name_on_cloud}-{i}' for i in range(count)]


def _ssh_pub_key() -> Optional[str]:
    from skypilot_tpu import authentication
    try:
        _, pub = authentication.get_or_generate_keys()
        return pub
    except Exception:  # pylint: disable=broad-except
        return None


def _by_name(rg: str) -> Dict[str, Dict[str, Any]]:
    return {vm.get('name', ''): vm for vm in arm_api.list_vms(rg)}


def _rank_key(name: str):
    """Numeric-aware sort: 'c-2' before 'c-10' (lexicographic order
    would misassign node ranks on 10+-node clusters)."""
    base, _, idx = name.rpartition('-')
    if idx.isdigit():
        return (base, int(idx))
    return (name, -1)


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    pc = config.provider_config
    region = pc.get('region', region)
    zone = pc.get('zone')
    instance_type = pc.get('instance_type')
    if not instance_type:
        raise exceptions.ProvisionerError(
            'Azure path needs an instance_type.',
            category=exceptions.ProvisionerError.CONFIG)
    rg = arm_api.resource_group_name(cluster_name_on_cloud, region)
    arm_api.ensure_resource_group(rg, region, cluster_name_on_cloud)
    subnet_id = arm_api.ensure_network(rg, region)
    names = _node_names(cluster_name_on_cloud, config.count)
    existing = _by_name(rg)
    pub_key = _ssh_pub_key()
    created, resumed = [], []
    for name in names:
        vm = existing.get(name)
        if vm is not None:
            state = arm_api.vm_power_state(vm)
            if state == 'stopping':
                # Launch raced a deallocate: wait for it to settle,
                # then restart — otherwise the node would sit in
                # 'stopped' until the wait timeout.
                deadline = time.time() + 300
                while state == 'stopping' and time.time() < deadline:
                    time.sleep(5)
                    cur = _by_name(rg).get(name)
                    state = (arm_api.vm_power_state(cur)
                             if cur is not None else 'stopped')
            if state == 'stopped':
                arm_api.start_vm(rg, name)
                resumed.append(name)
            continue  # running/pending: reuse
        arm_api.create_vm(
            rg, region, node_name=name,
            cluster_name=cluster_name_on_cloud,
            instance_type=instance_type, subnet_id=subnet_id,
            ssh_pub_key=pub_key, spot=bool(pc.get('use_spot')),
            disk_size_gb=int(pc.get('disk_size') or 256), zone=zone,
            image=pc.get('image_id'))
        created.append(name)
    return common.ProvisionRecord(
        provider_name='azure',
        cluster_name=cluster_name_on_cloud,
        region=region,
        zone=zone,
        head_instance_id=names[0],
        created_instance_ids=created,
        resumed_instance_ids=resumed,
        provider_config=dict(pc),
    )


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None,
                   provider_config: Optional[Dict[str, Any]] = None,
                   timeout: float = 600, poll: float = 5) -> None:
    del state
    pc = provider_config or {}
    region = pc.get('region', region)
    count = int(pc.get('num_nodes', 1))
    rg = arm_api.resource_group_name(cluster_name_on_cloud, region)
    names = set(_node_names(cluster_name_on_cloud, count))
    deadline = time.time() + timeout
    while True:
        running = set()
        by_name = _by_name(rg)
        # A node that vanishes mid-wait was evicted/deleted (spot VMs
        # use evictionPolicy=Delete) — fail fast as CAPACITY so the
        # failover engine moves on instead of burning the timeout.
        missing = names - set(by_name)
        if missing:
            raise exceptions.ProvisionerError(
                f'Azure VM(s) {sorted(missing)} disappeared while '
                f'waiting (evicted or failed to allocate).',
                category=exceptions.ProvisionerError.CAPACITY)
        for name, vm in by_name.items():
            if name in names and arm_api.vm_power_state(vm) == 'running':
                running.add(name)
        if running == names:
            return
        if time.time() > deadline:
            raise exceptions.ProvisionerError(
                f'Timed out waiting for {sorted(names - running)} '
                f'in resource group {rg}.')
        time.sleep(poll)


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    del worker_only
    pc = provider_config or {}
    region = pc.get('region')
    if not region:
        raise exceptions.ProvisionerError(
            f'Azure cluster {cluster_name_on_cloud!r} has no region in '
            'its provider config; cannot stop instances.',
            category=exceptions.ProvisionerError.CONFIG)
    rg = arm_api.resource_group_name(cluster_name_on_cloud, region)
    for name, vm in _by_name(rg).items():
        if arm_api.vm_power_state(vm) in ('running', 'pending'):
            arm_api.deallocate_vm(rg, name)


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    del worker_only
    pc = provider_config or {}
    region = pc.get('region')
    if not region:
        return
    # One async DELETE tears down VMs/NICs/IPs/disks/vnet together
    # (idempotent: a 404 on an already-gone group is success).
    arm_api.delete_resource_group(
        arm_api.resource_group_name(cluster_name_on_cloud, region))


_STATE_MAP = {
    'running': 'running',
    'pending': 'pending',
    'stopping': 'stopping',
    'stopped': 'stopped',
    'unknown': 'pending',
}


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    del non_terminated_only
    pc = provider_config or {}
    region = pc.get('region')
    if not region:
        # Never return {}: status refresh reads an empty result as
        # "terminated externally" and deletes the cluster record while
        # the VMs keep billing.
        raise exceptions.ProvisionerError(
            f'Azure cluster {cluster_name_on_cloud!r} has no region in '
            'its provider config; cannot query instances.',
            category=exceptions.ProvisionerError.CONFIG)
    rg = arm_api.resource_group_name(cluster_name_on_cloud, region)
    out: Dict[str, Optional[str]] = {}
    for name, vm in _by_name(rg).items():
        if arm_api.vm_tags(vm).get('skypilot-cluster') != \
                cluster_name_on_cloud:
            continue
        out[name] = _STATE_MAP.get(arm_api.vm_power_state(vm), 'pending')
    return out


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    from skypilot_tpu import constants
    pc = provider_config or {}
    region = pc.get('region', region)
    rg = arm_api.resource_group_name(cluster_name_on_cloud, region)
    by_name = _by_name(rg)
    if not by_name:
        raise exceptions.FetchClusterInfoError(
            exceptions.FetchClusterInfoError.Reason.HEAD)
    addrs = arm_api.node_addresses(rg)
    instances = []
    for rank, (name, _vm) in enumerate(
            sorted(by_name.items(), key=lambda kv: _rank_key(kv[0]))):
        addr = addrs.get(name, {})
        instances.append(common.InstanceInfo(
            instance_id=name,
            internal_ip=str(addr.get('internal_ip') or ''),
            external_ip=addr.get('external_ip'),
            ssh_port=22,
            agent_port=constants.AGENT_PORT,
            node_rank=rank,
            host_rank=0,
        ))
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=instances[0].instance_id,
        provider_name='azure',
        provider_config=dict(pc),
        ssh_user='skypilot',
        ssh_private_key='~/.ssh/sky-key',
    )


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    pc = provider_config or {}
    region = pc.get('region')
    if not region:
        raise exceptions.ProvisionerError(
            f'Azure cluster {cluster_name_on_cloud!r} has no region in '
            'its provider config; cannot open ports.',
            category=exceptions.ProvisionerError.CONFIG)
    arm_api.authorize_ingress(
        arm_api.resource_group_name(cluster_name_on_cloud, region),
        ports)


def cleanup_ports(cluster_name_on_cloud: str, ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    del cluster_name_on_cloud, ports, provider_config
