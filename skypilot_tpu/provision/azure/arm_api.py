"""Thin Azure Resource Manager REST client (stdlib OAuth2 + JSON).

Third public cloud next to GCP and AWS. Where the reference wraps the
azure SDK (sky/adaptors/azure.py, sky/provision/azure/instance.py),
this build calls ARM REST directly: a client-credentials token from
login.microsoftonline.com, then JSON PUT/GET/POST/DELETE under
management.azure.com — the same zero-dependency stance and the same
`_request()` seam as `aws/ec2_api.py` / `gcp/tpu_api.py`, so fake-API
tests drive the whole provisioner without the network.

Credentials: AZURE_SUBSCRIPTION_ID + AZURE_TENANT_ID + AZURE_CLIENT_ID
+ AZURE_CLIENT_SECRET from env (the standard service-principal
contract), else the same four keys in ~/.azure/skypilot.json.

Resource model: one resource group per cluster+region
(`sky-<cluster>-<region>`)
holding vnet/subnet/NSG/NICs/IPs/VMs — teardown is a single
resource-group DELETE, the canonical Azure cleanup (nothing to leak).
"""
from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

_MGMT = 'https://management.azure.com'
_LOGIN = 'https://login.microsoftonline.com'
_COMPUTE_API = '2024-03-01'
_NETWORK_API = '2023-09-01'
_RG_API = '2022-09-01'
_CREDENTIALS_PATH = '~/.azure/skypilot.json'

_token_cache: Dict[str, Any] = {}
_creds_cache: Optional[Dict[str, str]] = None


def load_credentials() -> Optional[Dict[str, str]]:
    """{subscription_id, tenant_id, client_id, client_secret} or None.

    Cached after the first hit: every ARM call resolves credentials
    (URL + auth), and polling loops would otherwise re-read the
    credentials file several times per second.
    """
    global _creds_cache
    if _creds_cache is not None:
        return _creds_cache
    keys = ('subscription_id', 'tenant_id', 'client_id', 'client_secret')
    env = {k: os.environ.get(f'AZURE_{k.upper()}') for k in keys}
    if all(env.values()):
        _creds_cache = env  # type: ignore
        return _creds_cache
    path = os.path.expanduser(_CREDENTIALS_PATH)
    if not os.path.exists(path):
        return None
    try:
        with open(path, 'r', encoding='utf-8') as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if all(data.get(k) for k in keys):
        _creds_cache = {k: str(data[k]) for k in keys}
        return _creds_cache
    return None


def _get_token(creds: Dict[str, str]) -> str:
    """Client-credentials bearer token, cached until ~5 min pre-expiry."""
    now = time.time()
    cached = _token_cache.get(creds['client_id'])
    if cached and cached['expires'] > now + 300:
        return cached['token']
    body = urllib.parse.urlencode({
        'grant_type': 'client_credentials',
        'client_id': creds['client_id'],
        'client_secret': creds['client_secret'],
        'scope': f'{_MGMT}/.default',
    }).encode()
    url = f'{_LOGIN}/{creds["tenant_id"]}/oauth2/v2.0/token'
    req = urllib.request.Request(url, data=body, method='POST')
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        detail = e.read().decode(errors='replace')[:300]
        raise exceptions.ProvisionerError(
            f'Azure token request failed: {detail}',
            category=exceptions.ProvisionerError.PERMISSION) from e
    except OSError as e:
        raise exceptions.ProvisionerError(
            f'Azure token request: network error {e}',
            category=exceptions.ProvisionerError.TRANSIENT) from e
    _token_cache[creds['client_id']] = {
        'token': out['access_token'],
        'expires': now + float(out.get('expires_in', 3600)),
    }
    return out['access_token']


def _classify_error(code: str, message: str) -> tuple:
    """ARM error code → (category, scope) via the per-cloud pattern
    table (provision/failover_patterns.py; reference:
    FailoverCloudErrorHandlerV2's _azure_handler mapping)."""
    from skypilot_tpu.provision import failover_patterns
    pat = failover_patterns.classify('azure', code, message)
    if pat is not None:
        return pat.category, pat.scope
    lower = code.lower()
    if lower.startswith('invalid'):
        return exceptions.ProvisionerError.CONFIG, None
    return exceptions.ProvisionerError.TRANSIENT, None


def _request(method: str, path: str, body: Optional[Dict[str, Any]] = None,
             api_version: str = _COMPUTE_API) -> Dict[str, Any]:
    """One authenticated ARM call; JSON in/out.

    `path` is subscription-relative or absolute under management.azure.com
    (leading '/subscriptions/...'). This is the fake-API test seam.
    """
    creds = load_credentials()
    if creds is None:
        raise exceptions.NoCloudAccessError(
            'Azure credentials not found (AZURE_* env or '
            '~/.azure/skypilot.json).')
    token = _get_token(creds)
    sep = '&' if '?' in path else '?'
    url = f'{_MGMT}{path}{sep}api-version={api_version}'
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method, headers={
        'Authorization': f'Bearer {token}',
        'Content-Type': 'application/json',
    })
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            text = resp.read().decode()
    except urllib.error.HTTPError as e:
        text = e.read().decode(errors='replace')
        code, message = str(e.code), text[:300]
        try:
            err = json.loads(text).get('error', {})
            code = err.get('code', code)
            message = err.get('message', message)
        except ValueError:
            pass
        if e.code == 404 and method in ('GET', 'DELETE'):
            # GET: caller treats {} as absent. DELETE: already gone is
            # the idempotent success case (teardown retries, failover
            # cleanup before the RG ever existed).
            return {}
        category, scope = _classify_error(code, message)
        raise exceptions.ProvisionerError(
            f'Azure {method} {path.rsplit("/", 1)[-1]} -> {code}: '
            f'{message[:300]}', category=category, scope=scope) from e
    except OSError as e:
        raise exceptions.ProvisionerError(
            f'Azure {method} {path}: network error {e}',
            category=exceptions.ProvisionerError.TRANSIENT) from e
    if not text:
        return {}
    return json.loads(text)


def _subscription() -> str:
    creds = load_credentials()
    if creds is None:
        raise exceptions.NoCloudAccessError('Azure credentials not found.')
    return creds['subscription_id']


def _rg_path(rg: str) -> str:
    return f'/subscriptions/{_subscription()}/resourceGroups/{rg}'


def resource_group_name(cluster_name: str, region: str) -> str:
    # Region-qualified: resource-group deletion is async (202 + minutes
    # of teardown), so a region-failover relaunch must land in a FRESH
    # group — PUTting a name that is mid-deletion is rejected by ARM.
    return f'sky-{cluster_name}-{region}'


# ---------------------------------------------------------------------------
# Resource group + network bootstrap
# ---------------------------------------------------------------------------
def ensure_resource_group(rg: str, region: str,
                          cluster_name: str) -> None:
    _request('PUT', _rg_path(rg),
             {'location': region,
              'tags': {'skypilot-cluster': cluster_name}},
             api_version=_RG_API)


def ensure_network(rg: str, region: str) -> str:
    """VNet + subnet + SSH-open NSG; returns the subnet resource id.

    Create-if-absent, never overwrite: an ARM PUT REPLACES the whole
    resource, so re-PUTting the NSG on a relaunch would wipe any
    port rules `open_ports` added since the first launch.
    """
    base = f'{_rg_path(rg)}/providers/Microsoft.Network'
    nsg_id = f'{base}/networkSecurityGroups/sky-nsg'
    if not _request('GET', nsg_id, api_version=_NETWORK_API):
        _request('PUT', nsg_id, {
            'location': region,
            'properties': {'securityRules': [{
                'name': 'ssh',
                'properties': {
                    'priority': 1000, 'direction': 'Inbound',
                    'access': 'Allow', 'protocol': 'Tcp',
                    'sourceAddressPrefix': '*', 'sourcePortRange': '*',
                    'destinationAddressPrefix': '*',
                    'destinationPortRange': '22',
                },
            }]},
        }, api_version=_NETWORK_API)
    vnet_id = f'{base}/virtualNetworks/sky-vnet'
    subnet_id = f'{vnet_id}/subnets/default'
    if not _request('GET', vnet_id, api_version=_NETWORK_API):
        _request('PUT', vnet_id, {
            'location': region,
            'properties': {
                'addressSpace': {'addressPrefixes': ['10.20.0.0/16']},
                'subnets': [{'name': 'default', 'properties': {
                    'addressPrefix': '10.20.0.0/20',
                    'networkSecurityGroup': {'id': nsg_id},
                }}],
            },
        }, api_version=_NETWORK_API)
    return subnet_id


# ---------------------------------------------------------------------------
# VMs
# ---------------------------------------------------------------------------
def create_vm(rg: str, region: str, *, node_name: str, cluster_name: str,
              instance_type: str, subnet_id: str,
              ssh_pub_key: Optional[str], spot: bool = False,
              disk_size_gb: int = 256, zone: Optional[str] = None,
              image: Optional[Dict[str, str]] = None) -> None:
    """Public IP + NIC + VM for one node. Every attached resource is
    created with deleteOption=Delete so a VM (or resource-group)
    delete leaves nothing behind."""
    net = f'{_rg_path(rg)}/providers/Microsoft.Network'
    pip_id = f'{net}/publicIPAddresses/{node_name}-ip'
    _request('PUT', pip_id, {
        'location': region,
        'sku': {'name': 'Standard'},
        'properties': {'publicIPAllocationMethod': 'Static'},
    }, api_version=_NETWORK_API)
    nic_id = f'{net}/networkInterfaces/{node_name}-nic'
    _request('PUT', nic_id, {
        'location': region,
        'properties': {'ipConfigurations': [{
            'name': 'ipconfig1',
            'properties': {
                'subnet': {'id': subnet_id},
                'publicIPAddress': {
                    'id': pip_id,
                    'properties': {'deleteOption': 'Delete'},
                },
            },
        }]},
    }, api_version=_NETWORK_API)
    if isinstance(image, str):
        # Marketplace URN form: publisher:offer:sku:version.
        parts = image.split(':')
        if len(parts) != 4:
            raise exceptions.ProvisionerError(
                f'Azure image_id must be publisher:offer:sku:version, '
                f'got {image!r}.',
                category=exceptions.ProvisionerError.CONFIG)
        image = dict(zip(('publisher', 'offer', 'sku', 'version'), parts))
    image = image or {
        'publisher': 'Canonical',
        'offer': '0001-com-ubuntu-server-jammy',
        'sku': '22_04-lts-gen2',
        'version': 'latest',
    }
    vm_body: Dict[str, Any] = {
        'location': region,
        'tags': {'skypilot-cluster': cluster_name, 'Name': node_name},
        'properties': {
            'hardwareProfile': {'vmSize': instance_type},
            'storageProfile': {
                'imageReference': image,
                'osDisk': {
                    'createOption': 'FromImage',
                    'deleteOption': 'Delete',
                    'diskSizeGB': int(disk_size_gb),
                    'managedDisk':
                        {'storageAccountType': 'Premium_LRS'},
                },
            },
            'osProfile': {
                'computerName': node_name[:63],
                'adminUsername': 'skypilot',
                'linuxConfiguration': {
                    'disablePasswordAuthentication': True,
                    'ssh': {'publicKeys': [{
                        'path':
                            '/home/skypilot/.ssh/authorized_keys',
                        'keyData': ssh_pub_key or '',
                    }]},
                },
            },
            'networkProfile': {'networkInterfaces': [{
                'id': nic_id,
                'properties': {'deleteOption': 'Delete'},
            }]},
        },
    }
    if spot:
        vm_body['properties']['priority'] = 'Spot'
        vm_body['properties']['evictionPolicy'] = 'Delete'
        vm_body['properties']['billingProfile'] = {'maxPrice': -1}
    if zone:
        vm_body['zones'] = [str(zone)]
    _request(
        'PUT',
        f'{_rg_path(rg)}/providers/Microsoft.Compute'
        f'/virtualMachines/{node_name}', vm_body)


def list_vms(rg: str) -> List[Dict[str, Any]]:
    out = _request(
        'GET',
        f'{_rg_path(rg)}/providers/Microsoft.Compute'
        f'/virtualMachines?$expand=instanceView')
    return list(out.get('value', []))


def vm_power_state(vm: Dict[str, Any]) -> str:
    """'running' | 'pending' | 'stopping' | 'stopped' | 'unknown'."""
    statuses = (vm.get('properties', {}).get('instanceView', {})
                .get('statuses', []))
    for s in statuses:
        code = s.get('code', '')
        if not code.startswith('PowerState/'):
            continue
        state = code.split('/', 1)[1]
        return {
            'running': 'running',
            'starting': 'pending',
            'creating': 'pending',
            'deallocating': 'stopping',
            'stopping': 'stopping',
            'deallocated': 'stopped',
            'stopped': 'stopped',
        }.get(state, 'unknown')
    return 'pending'  # instanceView not populated yet


def vm_tags(vm: Dict[str, Any]) -> Dict[str, str]:
    return dict(vm.get('tags', {}))


def _vm_action(rg: str, vm_name: str, action: str) -> None:
    _request(
        'POST',
        f'{_rg_path(rg)}/providers/Microsoft.Compute'
        f'/virtualMachines/{vm_name}/{action}')


def deallocate_vm(rg: str, vm_name: str) -> None:
    _vm_action(rg, vm_name, 'deallocate')


def start_vm(rg: str, vm_name: str) -> None:
    _vm_action(rg, vm_name, 'start')


def delete_resource_group(rg: str) -> None:
    """Async 202: ARM tears down every resource in the group."""
    _request('DELETE', f'{_rg_path(rg)}?forceDeletionTypes='
                       'Microsoft.Compute%2FvirtualMachines',
             api_version=_RG_API)


# ---------------------------------------------------------------------------
# Networking detail + ports
# ---------------------------------------------------------------------------
def list_nics(rg: str) -> List[Dict[str, Any]]:
    out = _request(
        'GET',
        f'{_rg_path(rg)}/providers/Microsoft.Network/networkInterfaces',
        api_version=_NETWORK_API)
    return list(out.get('value', []))


def list_public_ips(rg: str) -> Dict[str, str]:
    """public-ip resource id -> address."""
    out = _request(
        'GET',
        f'{_rg_path(rg)}/providers/Microsoft.Network/publicIPAddresses',
        api_version=_NETWORK_API)
    return {p.get('id', ''): p.get('properties', {}).get('ipAddress', '')
            for p in out.get('value', [])}


def node_addresses(rg: str) -> Dict[str, Dict[str, Optional[str]]]:
    """node name ('<x>-nic' stripped) -> {internal_ip, external_ip}."""
    pips = list_public_ips(rg)
    out: Dict[str, Dict[str, Optional[str]]] = {}
    for nic in list_nics(rg):
        name = nic.get('name', '')
        node = name[:-4] if name.endswith('-nic') else name
        configs = nic.get('properties', {}).get('ipConfigurations', [])
        internal, external = None, None
        for c in configs:
            p = c.get('properties', {})
            internal = internal or p.get('privateIPAddress')
            pip = p.get('publicIPAddress', {})
            if pip.get('id') in pips:
                external = pips[pip['id']] or None
        out[node] = {'internal_ip': internal, 'external_ip': external}
    return out


def authorize_ingress(rg: str, ports: List[str]) -> None:
    """One NSG rule per port range on the cluster's shared NSG.

    Rule names encode the FULL range (so '100' never replaces
    '100-200') and priorities are allocated from the live rule set
    (ARM rejects duplicate priorities within an NSG).
    """
    base = (f'{_rg_path(rg)}/providers/Microsoft.Network'
            f'/networkSecurityGroups/sky-nsg')
    nsg = _request('GET', base, api_version=_NETWORK_API)
    existing_rules = nsg.get('properties', {}).get('securityRules', [])
    existing_names = {r.get('name') for r in existing_rules}
    next_priority = 1 + max(
        [1099] + [int(r.get('properties', {}).get('priority', 0))
                  for r in existing_rules])
    for port in ports:
        lo, _, hi = str(port).partition('-')
        port_range = f'{lo}-{hi}' if hi else lo
        name = f'sky-port-{port_range.replace("-", "-to-")}'
        if name in existing_names:
            continue  # idempotent: rule already present
        _request('PUT', f'{base}/securityRules/{name}', {
            'properties': {
                'priority': next_priority,
                'direction': 'Inbound', 'access': 'Allow',
                'protocol': 'Tcp',
                'sourceAddressPrefix': '*', 'sourcePortRange': '*',
                'destinationAddressPrefix': '*',
                'destinationPortRange': port_range,
            },
        }, api_version=_NETWORK_API)
        existing_names.add(name)
        next_priority += 1
