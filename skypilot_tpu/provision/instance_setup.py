"""Runtime bootstrap on freshly provisioned cloud hosts.

Reference: sky/provision/instance_setup.py — install deps, start the
runtime (there: ray head/workers + skylet; here: one agent per host).
Used by the GCP/SSH paths; the Local provisioner starts agents itself.
"""
from __future__ import annotations

import os
import tempfile
from typing import List, Optional

from skypilot_tpu import constants
from skypilot_tpu import exceptions
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.utils import command_runner as runner_lib
from skypilot_tpu.utils import subprocess_utils

_PKG_REMOTE_DIR = '~/.sky-tpu-runtime/skypilot_tpu_pkg'


def remote_pkg_dir() -> str:
    """Where the package tree lives on hosts (public: the CLI's
    ssh-node-pool teardown removes it)."""
    return _PKG_REMOTE_DIR
_VENV_PY = 'python3'

_AGENT_START_TEMPLATE = (
    'mkdir -p {home} && cd {pkg_dir} && '
    'pkill -f "skypilot_tpu.agent.agent --port {port}" || true; '
    'PYTHONPATH={pkg_dir} nohup {python} -m skypilot_tpu.agent.agent '
    '--port {port} --home {home} --cluster {cluster} {head_flag} '
    '> {home}/agent.log 2>&1 & '
    'sleep 1 && curl -sf http://localhost:{port}/health > /dev/null')


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def deploy_package(runner) -> None:
    """Rsync this installation's package tree to a host (the runtime-
    matches-server guarantee). Shared by per-launch bootstrap and
    `stpu ssh-node-pool up` pre-warming."""
    src = os.path.join(_repo_root(), 'skypilot_tpu') + '/'
    runner.run(f'mkdir -p {_PKG_REMOTE_DIR}/skypilot_tpu',
               stream_logs=False)
    runner.rsync(src, f'{_PKG_REMOTE_DIR}/skypilot_tpu/', up=True,
                 excludes=['__pycache__'])


def setup_agents(cluster_info: provision_common.ClusterInfo,
                 runners: List[runner_lib.CommandRunner],
                 cluster_name: str,
                 secret: Optional[str] = None) -> None:
    """Upload the package to every host and start its agent.

    The package is rsynced from the server's own installation — the
    reference builds+uploads a wheel so remote runtime matches server
    code (sky/backends/wheel_utils.py); rsync of the package tree is
    the same guarantee with less machinery. The per-cluster `secret`
    is rsynced (not passed via argv, which would leak through `ps`) to
    `<home>/agent_secret` before the agent starts; the agent then
    rejects any request without the matching X-Agent-Token.
    """
    instances = cluster_info.sorted_instances()

    secret_src = None
    if secret is not None:
        fd, secret_src = tempfile.mkstemp(prefix='agent_secret_')
        with os.fdopen(fd, 'w', encoding='utf-8') as f:
            f.write(secret)
        os.chmod(secret_src, 0o600)

    # External log shipping destination (reference: sky/logs): agents
    # ship finished jobs' logs to `logs.store` when configured.
    from skypilot_tpu import sky_config
    log_store = sky_config.get_nested(('logs', 'store'))
    log_store_src = None
    if log_store:
        fd, log_store_src = tempfile.mkstemp(prefix='log_store_')
        with os.fdopen(fd, 'w', encoding='utf-8') as f:
            f.write(str(log_store))

    def bootstrap(pair) -> None:
        inst, runner = pair
        home = constants.SKY_REMOTE_HOME
        runner.run(f'mkdir -p {home} && chmod 700 {home}')
        deploy_package(runner)
        if secret_src is not None:
            runner.rsync(secret_src, f'{home}/agent_secret', up=True)
        if log_store_src is not None:
            runner.rsync(log_store_src, f'{home}/log_store', up=True)
        is_head = inst.instance_id == cluster_info.head_instance_id
        cmd = _AGENT_START_TEMPLATE.format(
            home=home,
            pkg_dir=_PKG_REMOTE_DIR,
            python=_VENV_PY,
            port=inst.agent_port or constants.AGENT_PORT,
            cluster=cluster_name,
            head_flag='--head' if is_head else '')
        rc = runner.run(cmd, stream_logs=False)
        if rc != 0:
            raise exceptions.ClusterSetUpError(
                f'Failed to start agent on {inst.instance_id} (rc={rc}).')
        # Streaming aggregator (logs.store: gcp|aws): fluent-bit tails
        # the job logs on every host (reference: sky/logs). Best-effort
        # — a logging outage must not fail provisioning.
        from skypilot_tpu import logs as logs_lib
        aggregator = logs_lib.get_aggregator()
        if aggregator is not None:
            setup = ' && '.join(
                aggregator.setup_commands(cluster_name))
            rc, _, err = runner.run(setup, require_outputs=True)
            if rc != 0:
                ux_utils.log(
                    f'Log aggregator setup failed on '
                    f'{inst.instance_id} (rc={rc}): {err[-300:]}; '
                    f'continuing without streaming logs there.')

    try:
        subprocess_utils.run_in_parallel(bootstrap,
                                         list(zip(instances, runners)))
    finally:
        for tmp in (secret_src, log_store_src):
            if tmp is not None:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
