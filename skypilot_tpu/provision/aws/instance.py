"""AWS provisioner: EC2 instances via the routed interface.

Reference: sky/provision/aws/instance.py (boto3) — same contract
(run/wait/stop/terminate/query/get_cluster_info/open_ports), driven
here by the SigV4 Query client (`ec2_api.py`). Nodes are named
`<cluster>-<i>` via the Name tag and discovered by the
`skypilot-cluster` tag, so every verb works from the tag filter alone.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.aws import ec2_api

# Canonical (Ubuntu 22.04 LTS amd64 hvm:ebs-ssd) AMIs per region —
# snapshot table, overridable per-request via resources.image_id.
_DEFAULT_AMIS = {
    'us-east-1': 'ami-0e2512bd9da751ea8',
    'us-east-2': 'ami-0862be96e41dcbf74',
    'us-west-2': 'ami-03f65b8614a860c29',
    'eu-west-1': 'ami-0905a3c97561e0b69',
    'ap-northeast-1': 'ami-07c589821f2b353aa',
}

_STATE_MAP = {
    'running': 'running',
    'pending': 'pending',
    'stopping': 'stopping',
    'stopped': 'stopped',
    'shutting-down': None,
    'terminated': None,
}


def _node_names(cluster_name_on_cloud: str, count: int) -> List[str]:
    if count == 1:
        return [cluster_name_on_cloud]
    return [f'{cluster_name_on_cloud}-{i}' for i in range(count)]


def _ssh_pub_key() -> Optional[str]:
    from skypilot_tpu import authentication
    try:
        _, pub = authentication.get_or_generate_keys()
        return pub
    except Exception:  # pylint: disable=broad-except
        return None


def _by_name(region: str, cluster_name_on_cloud: str
             ) -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    for inst in ec2_api.describe_instances(region, cluster_name_on_cloud):
        name = ec2_api.instance_tags(inst).get('Name', '')
        out[name] = inst
    return out


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    pc = config.provider_config
    region = pc.get('region', region)
    zone = pc.get('zone')
    instance_type = pc.get('instance_type')
    if not instance_type:
        raise exceptions.ProvisionerError(
            'AWS path needs an instance_type.',
            category=exceptions.ProvisionerError.CONFIG)
    image_id = pc.get('image_id') or _DEFAULT_AMIS.get(region)
    if not image_id:
        raise exceptions.ProvisionerError(
            f'No default AMI known for {region}; set image_id.',
            category=exceptions.ProvisionerError.CONFIG)
    names = _node_names(cluster_name_on_cloud, config.count)
    existing = _by_name(region, cluster_name_on_cloud)
    pub_key = _ssh_pub_key()
    created, resumed = [], []
    for name in names:
        inst = existing.get(name)
        if inst is not None:
            state = ec2_api.instance_state(inst)
            if state == 'stopped':
                ec2_api.start_instances(region, [inst['instanceId']])
                resumed.append(name)
            continue  # running/pending: reuse
        ec2_api.run_instances(
            region, count=1, instance_type=instance_type,
            image_id=image_id, cluster_name=cluster_name_on_cloud,
            node_name=name, zone=zone, spot=bool(pc.get('use_spot')),
            disk_size_gb=int(pc.get('disk_size') or 256),
            ssh_pub_key=pub_key)
        created.append(name)
    return common.ProvisionRecord(
        provider_name='aws',
        cluster_name=cluster_name_on_cloud,
        region=region,
        zone=zone,
        head_instance_id=names[0],
        created_instance_ids=created,
        resumed_instance_ids=resumed,
        provider_config=dict(pc),
    )


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None,
                   provider_config: Optional[Dict[str, Any]] = None,
                   timeout: float = 600, poll: float = 5) -> None:
    del state
    pc = provider_config or {}
    region = pc.get('region', region)
    count = int(pc.get('num_nodes', 1))
    names = set(_node_names(cluster_name_on_cloud, count))
    deadline = time.time() + timeout
    while True:
        running = set()
        for name, inst in _by_name(region, cluster_name_on_cloud).items():
            st = ec2_api.instance_state(inst)
            if st == 'running' and name in names:
                running.add(name)
            elif st in ('terminated', 'shutting-down') and name in names:
                raise exceptions.ProvisionerError(
                    f'EC2 instance {name} entered {st} while waiting.',
                    category=exceptions.ProvisionerError.CAPACITY)
        if running == names:
            return
        if time.time() > deadline:
            raise exceptions.ProvisionerError(
                f'Timed out waiting for {sorted(names - running)} '
                f'in {region}.')
        time.sleep(poll)


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    del worker_only
    pc = provider_config or {}
    region = pc['region']
    ids = [inst['instanceId']
           for inst in ec2_api.describe_instances(region,
                                                  cluster_name_on_cloud)
           if ec2_api.instance_state(inst) in ('running', 'pending')]
    ec2_api.stop_instances(region, ids)


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    del worker_only
    pc = provider_config or {}
    region = pc.get('region')
    if not region:
        return
    ids = [inst['instanceId']
           for inst in ec2_api.describe_instances(region,
                                                  cluster_name_on_cloud)]
    ec2_api.terminate_instances(region, ids)


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    pc = provider_config or {}
    region = pc['region']
    out: Dict[str, Optional[str]] = {}
    for name, inst in _by_name(region, cluster_name_on_cloud).items():
        status = _STATE_MAP.get(ec2_api.instance_state(inst), 'pending')
        if non_terminated_only and status is None:
            continue
        out[name] = status
    return out


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    from skypilot_tpu import constants
    pc = provider_config or {}
    region = pc.get('region', region)
    by_name = _by_name(region, cluster_name_on_cloud)
    live = {n: i for n, i in by_name.items()
            if ec2_api.instance_state(i) not in ('terminated',
                                                 'shutting-down')}
    if not live:
        raise exceptions.FetchClusterInfoError(
            exceptions.FetchClusterInfoError.Reason.HEAD)
    def _rank_key(name):
        # Numeric-aware: 'c-2' before 'c-10' for stable node ranks.
        base, _, idx = name.rpartition('-')
        return (base, int(idx)) if idx.isdigit() else (name, -1)

    instances = []
    for rank, (name, inst) in enumerate(
            sorted(live.items(), key=lambda kv: _rank_key(kv[0]))):
        instances.append(common.InstanceInfo(
            instance_id=name,
            internal_ip=str(inst.get('privateIpAddress', '')),
            external_ip=(str(inst['ipAddress'])
                         if inst.get('ipAddress') else None),
            ssh_port=22,
            agent_port=constants.AGENT_PORT,
            node_rank=rank,
            host_rank=0,
        ))
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=instances[0].instance_id,
        provider_name='aws',
        provider_config=dict(pc),
        ssh_user='skypilot',
        ssh_private_key='~/.ssh/sky-key',
    )


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    pc = provider_config or {}
    region = pc['region']
    groups = set()
    for inst in ec2_api.describe_instances(region, cluster_name_on_cloud):
        gset = inst.get('groupSet', [])
        if isinstance(gset, dict):
            gset = [gset]
        for g in gset:
            if g.get('groupId'):
                groups.add(g['groupId'])
    for gid in sorted(groups):
        ec2_api.authorize_ingress(region, gid, ports)


def cleanup_ports(cluster_name_on_cloud: str, ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    del cluster_name_on_cloud, ports, provider_config
