"""Thin EC2 Query API client with stdlib SigV4 signing.

The second real public cloud next to GCP. Where the reference wraps
boto3 (sky/adaptors/aws.py, sky/provision/aws/instance.py), this
build signs the EC2 Query API directly — no SDK dependency, the same
zero-dependency stance as the GCP REST client (`tpu_api.py`), and the
same `_request()` seam so fake-API tests drive the whole provisioner
without the network.

Credentials: AWS_ACCESS_KEY_ID / AWS_SECRET_ACCESS_KEY (+ optional
AWS_SESSION_TOKEN) from env, else the `default` profile of
~/.aws/credentials.
"""
from __future__ import annotations

import configparser
import datetime
import hashlib
import hmac
import os
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions

_API_VERSION = '2016-11-15'
_CREDENTIALS_PATH = '~/.aws/credentials'


def load_credentials() -> Optional[Tuple[str, str, Optional[str]]]:
    """(access_key, secret_key, session_token) or None."""
    access = os.environ.get('AWS_ACCESS_KEY_ID')
    secret = os.environ.get('AWS_SECRET_ACCESS_KEY')
    if access and secret:
        return access, secret, os.environ.get('AWS_SESSION_TOKEN')
    path = os.path.expanduser(_CREDENTIALS_PATH)
    if not os.path.exists(path):
        return None
    parser = configparser.ConfigParser()
    try:
        parser.read(path)
    except configparser.Error:
        return None
    profile = os.environ.get('AWS_PROFILE', 'default')
    if profile not in parser:
        return None
    section = parser[profile]
    access = section.get('aws_access_key_id')
    secret = section.get('aws_secret_access_key')
    if not access or not secret:
        return None
    return access, secret, section.get('aws_session_token')


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def _sigv4_headers(region: str, host: str, body: str,
                   creds: Tuple[str, str, Optional[str]]) -> Dict[str, str]:
    """AWS Signature Version 4 for a POST to the EC2 Query endpoint."""
    access, secret, token = creds
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime('%Y%m%dT%H%M%SZ')
    date_stamp = now.strftime('%Y%m%d')
    service = 'ec2'
    payload_hash = hashlib.sha256(body.encode()).hexdigest()

    canonical_headers = (f'content-type:application/x-www-form-urlencoded; '
                         f'charset=utf-8\nhost:{host}\n'
                         f'x-amz-date:{amz_date}\n')
    signed_headers = 'content-type;host;x-amz-date'
    if token:
        canonical_headers += f'x-amz-security-token:{token}\n'
        signed_headers += ';x-amz-security-token'
    canonical_request = '\n'.join([
        'POST', '/', '', canonical_headers, signed_headers, payload_hash])

    scope = f'{date_stamp}/{region}/{service}/aws4_request'
    string_to_sign = '\n'.join([
        'AWS4-HMAC-SHA256', amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest()])

    k = _sign(f'AWS4{secret}'.encode(), date_stamp)
    k = _sign(k, region)
    k = _sign(k, service)
    k = _sign(k, 'aws4_request')
    signature = hmac.new(k, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()

    headers = {
        'Content-Type':
            'application/x-www-form-urlencoded; charset=utf-8',
        'X-Amz-Date': amz_date,
        'Authorization':
            (f'AWS4-HMAC-SHA256 Credential={access}/{scope}, '
             f'SignedHeaders={signed_headers}, Signature={signature}'),
    }
    if token:
        headers['X-Amz-Security-Token'] = token
    return headers


def _classify_error(code: str, message: str) -> tuple:
    """EC2 error code → (category, scope) via the per-cloud pattern
    table (provision/failover_patterns.py; reference:
    FailoverCloudErrorHandlerV1's _aws_handler blocklist mapping)."""
    from skypilot_tpu.provision import failover_patterns
    pat = failover_patterns.classify('aws', code, message)
    if pat is not None:
        return pat.category, pat.scope
    # Status-family fallbacks for codes no pattern knows.
    lower = code.lower()
    if lower.startswith('invalid') or lower.startswith('missing'):
        return exceptions.ProvisionerError.CONFIG, None
    return exceptions.ProvisionerError.TRANSIENT, None


def _strip_ns(tag: str) -> str:
    return tag.rsplit('}', 1)[-1]


def _xml_to_obj(elem: ET.Element) -> Any:
    """EC2 XML → dict/list: <item> children fold into lists."""
    children = list(elem)
    if not children:
        return elem.text or ''
    items = [c for c in children if _strip_ns(c.tag) == 'item']
    if items and len(items) == len(children):
        return [_xml_to_obj(c) for c in items]
    out: Dict[str, Any] = {}
    for c in children:
        out[_strip_ns(c.tag)] = _xml_to_obj(c)
    return out


def _request(region: str, action: str,
             params: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """One signed EC2 Query API call; XML response parsed to dicts.

    This is the seam the fake-API tests monkeypatch.
    """
    creds = load_credentials()
    if creds is None:
        raise exceptions.NoCloudAccessError(
            'AWS credentials not found (env or ~/.aws/credentials).')
    host = f'ec2.{region}.amazonaws.com'
    form = {'Action': action, 'Version': _API_VERSION}
    form.update(params or {})
    body = urllib.parse.urlencode(sorted(form.items()))
    headers = _sigv4_headers(region, host, body, creds)
    req = urllib.request.Request(f'https://{host}/', data=body.encode(),
                                 headers=headers, method='POST')
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            text = resp.read().decode()
    except urllib.error.HTTPError as e:
        text = e.read().decode(errors='replace')
        code, message = 'Unknown', text[:300]
        try:
            root = ET.fromstring(text)
            err = root.find('.//{*}Error')
            if err is None:
                err = root.find('.//Error')
            if err is not None:
                code = (err.findtext('{*}Code') or
                        err.findtext('Code') or 'Unknown')
                message = (err.findtext('{*}Message') or
                           err.findtext('Message') or message)
        except ET.ParseError:
            pass
        if code in ('InvalidInstanceID.NotFound',
                    'InvalidGroup.NotFound'):
            raise exceptions.FetchClusterInfoError(
                exceptions.FetchClusterInfoError.Reason.HEAD) from e
        category, scope = _classify_error(code, message)
        raise exceptions.ProvisionerError(
            f'EC2 {action} in {region} -> {code}: {message[:300]}',
            category=category, scope=scope) from e
    except OSError as e:
        raise exceptions.ProvisionerError(
            f'EC2 {action} in {region}: network error {e}',
            category=exceptions.ProvisionerError.TRANSIENT) from e
    root = ET.fromstring(text)
    obj = _xml_to_obj(root)
    return obj if isinstance(obj, dict) else {'result': obj}


def _flatten(prefix: str, values: List[str]) -> Dict[str, str]:
    return {f'{prefix}.{i + 1}': v for i, v in enumerate(values)}


def _filter_params(filters: Dict[str, List[str]]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for i, (name, values) in enumerate(sorted(filters.items()), start=1):
        out[f'Filter.{i}.Name'] = name
        for j, v in enumerate(values, start=1):
            out[f'Filter.{i}.Value.{j}'] = v
    return out


# ---------------------------------------------------------------------------
# Instances
# ---------------------------------------------------------------------------
def run_instances(region: str, *, count: int, instance_type: str,
                  image_id: str, cluster_name: str, node_name: str,
                  zone: Optional[str] = None, spot: bool = False,
                  disk_size_gb: int = 256,
                  ssh_pub_key: Optional[str] = None,
                  security_group_ids: Optional[List[str]] = None,
                  extra_tags: Optional[Dict[str, str]] = None
                  ) -> List[Dict[str, Any]]:
    """RunInstances; returns the instancesSet items."""
    params: Dict[str, str] = {
        'MinCount': str(count),
        'MaxCount': str(count),
        'InstanceType': instance_type,
        'ImageId': image_id,
        ('BlockDeviceMapping.1.DeviceName'): '/dev/sda1',
        ('BlockDeviceMapping.1.Ebs.VolumeSize'): str(int(disk_size_gb)),
        ('BlockDeviceMapping.1.Ebs.VolumeType'): 'gp3',
        ('BlockDeviceMapping.1.Ebs.DeleteOnTermination'): 'true',
    }
    if zone:
        params['Placement.AvailabilityZone'] = zone
    if spot:
        params['InstanceMarketOptions.MarketType'] = 'spot'
        params[('InstanceMarketOptions.SpotOptions.'
                'InstanceInterruptionBehavior')] = 'terminate'
    if ssh_pub_key:
        # cloud-init user-data injects the key: no KeyPair lifecycle to
        # manage or leak (reference manages named key pairs instead).
        import base64
        user_data = ('#cloud-config\n'
                     'users:\n'
                     '  - name: skypilot\n'
                     '    sudo: ALL=(ALL) NOPASSWD:ALL\n'
                     '    shell: /bin/bash\n'
                     '    ssh_authorized_keys:\n'
                     f'      - {ssh_pub_key}\n')
        params['UserData'] = base64.b64encode(user_data.encode()).decode()
    if security_group_ids:
        params.update(_flatten('SecurityGroupId', security_group_ids))
    tags = {'Name': node_name, 'skypilot-cluster': cluster_name}
    tags.update(extra_tags or {})
    params['TagSpecification.1.ResourceType'] = 'instance'
    for i, (k, v) in enumerate(sorted(tags.items()), start=1):
        params[f'TagSpecification.1.Tag.{i}.Key'] = k
        params[f'TagSpecification.1.Tag.{i}.Value'] = v
    out = _request(region, 'RunInstances', params)
    instances = out.get('instancesSet', [])
    if isinstance(instances, dict):
        instances = [instances]
    return instances


def describe_instances(region: str, cluster_name: str,
                       include_terminated: bool = False
                       ) -> List[Dict[str, Any]]:
    filters = {'tag:skypilot-cluster': [cluster_name]}
    if not include_terminated:
        filters['instance-state-name'] = [
            'pending', 'running', 'stopping', 'stopped', 'shutting-down']
    out = _request(region, 'DescribeInstances', _filter_params(filters))
    reservations = out.get('reservationSet', [])
    if isinstance(reservations, dict):
        reservations = [reservations]
    instances: List[Dict[str, Any]] = []
    for r in reservations:
        items = r.get('instancesSet', [])
        if isinstance(items, dict):
            items = [items]
        instances.extend(items)
    return instances


def terminate_instances(region: str, instance_ids: List[str]) -> None:
    if not instance_ids:
        return
    _request(region, 'TerminateInstances',
             _flatten('InstanceId', instance_ids))


def stop_instances(region: str, instance_ids: List[str]) -> None:
    if not instance_ids:
        return
    _request(region, 'StopInstances', _flatten('InstanceId', instance_ids))


def start_instances(region: str, instance_ids: List[str]) -> None:
    if not instance_ids:
        return
    _request(region, 'StartInstances', _flatten('InstanceId', instance_ids))


def authorize_ingress(region: str, group_id: str, ports: List[str]) -> None:
    """Open TCP ports on a security group, one call per port.

    Per-port (not batched) on purpose: AuthorizeSecurityGroupIngress is
    atomic, so a batch containing one already-authorized rule rejects
    the WHOLE call and new ports would silently never open. Duplicate
    errors on a single port are the idempotent success case.
    """
    for port in ports:
        lo, _, hi = str(port).partition('-')
        params = {
            'GroupId': group_id,
            'IpPermissions.1.IpProtocol': 'tcp',
            'IpPermissions.1.FromPort': lo,
            'IpPermissions.1.ToPort': hi or lo,
            'IpPermissions.1.IpRanges.1.CidrIp': '0.0.0.0/0',
        }
        try:
            _request(region, 'AuthorizeSecurityGroupIngress', params)
        except exceptions.ProvisionerError as e:
            if 'Duplicate' not in str(e):
                raise


# State helpers -------------------------------------------------------------
def instance_state(instance: Dict[str, Any]) -> str:
    state = instance.get('instanceState', {})
    if isinstance(state, dict):
        return str(state.get('name', 'pending'))
    return str(state)


def instance_tags(instance: Dict[str, Any]) -> Dict[str, str]:
    tags = instance.get('tagSet', [])
    if isinstance(tags, dict):
        tags = [tags]
    return {t.get('key', ''): t.get('value', '') for t in tags}
