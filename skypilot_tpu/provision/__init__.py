"""Provisioner router: dispatch function calls to per-cloud modules.

Reference: sky/provision/__init__.py — `_route_to_cloud_impl` looks up
`sky.provision.<cloud>.<fn>`; clouds register by module presence.
Interface (all take (provider_name-dispatched) positional args):

  run_instances(region, cluster_name_on_cloud, config) -> ProvisionRecord
  wait_instances(region, cluster_name_on_cloud, state) -> None
  stop_instances(cluster_name_on_cloud, provider_config) -> None
  terminate_instances(cluster_name_on_cloud, provider_config) -> None
  query_instances(cluster_name_on_cloud, provider_config)
      -> Dict[instance_id, status]
  get_cluster_info(region, cluster_name_on_cloud, provider_config)
      -> ClusterInfo
  open_ports / cleanup_ports(cluster_name_on_cloud, ports, provider_config)
"""
from __future__ import annotations

import functools
import importlib
from typing import Any

from skypilot_tpu.utils import timeline


def _route(provider_name: str, fn_name: str):
    module_name = provider_name.lower()
    module = importlib.import_module(
        f'skypilot_tpu.provision.{module_name}.instance')
    fn = getattr(module, fn_name, None)
    if fn is None:
        raise NotImplementedError(
            f'{module_name} provisioner does not implement {fn_name}')
    return fn


def _make_router(fn_name: str):

    @timeline.event
    def router(provider_name: str, *args: Any, **kwargs: Any) -> Any:
        return _route(provider_name, fn_name)(*args, **kwargs)

    router.__name__ = fn_name
    return router


run_instances = _make_router('run_instances')
# Volume ops (reference: sky/provision/__init__.py:235-310):
apply_volume = _make_router('apply_volume')
delete_volume = _make_router('delete_volume')
attach_volume = _make_router('attach_volume')
wait_instances = _make_router('wait_instances')
stop_instances = _make_router('stop_instances')
terminate_instances = _make_router('terminate_instances')
query_instances = _make_router('query_instances')
get_cluster_info = _make_router('get_cluster_info')
open_ports = _make_router('open_ports')
cleanup_ports = _make_router('cleanup_ports')
