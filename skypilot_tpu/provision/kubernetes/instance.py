"""Kubernetes provisioner: pods-as-hosts, GKE TPU pod slices.

Reference: sky/provision/kubernetes/ (the largest reference cloud).
TPU-first shape: one Task node = one GKE TPU slice = `tpu_num_hosts`
pods scheduled onto that slice's node pool via the GKE TPU selectors
(cloud.google.com/gke-tpu-accelerator + gke-tpu-topology) with
`google.com/tpu` chip limits; a headless Service gives pods stable
DNS for the agent mesh. CPU tasks are plain pods. All HTTP goes
through `_request()` (fake-API-testable, same pattern as
provision/gcp/).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import requests as requests_lib

from skypilot_tpu import constants
from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.utils import kubeconfig

_AGENT_IMAGE_DEFAULT = 'python:3.11-slim'


def _ctx(provider_config: Optional[Dict[str, Any]]) -> kubeconfig.KubeContext:
    pc = provider_config or {}
    ctx = kubeconfig.load_context(pc.get('context'))
    if ctx is None:
        raise exceptions.NoCloudAccessError(
            'No kubeconfig context available for the kubernetes cloud.')
    if pc.get('namespace'):
        ctx.namespace = pc['namespace']
    return ctx


def _request(ctx: kubeconfig.KubeContext, method: str, path: str,
             json_body: Optional[Dict] = None) -> Dict[str, Any]:
    url = f'{ctx.server}{path}'
    resp = requests_lib.request(method, url, json=json_body, timeout=60,
                                **ctx.request_kwargs())
    if resp.status_code == 404:
        raise exceptions.FetchClusterInfoError(
            exceptions.FetchClusterInfoError.Reason.HEAD)
    if resp.status_code >= 400:
        from skypilot_tpu.provision import failover_patterns
        pat = failover_patterns.classify('kubernetes',
                                         str(resp.status_code),
                                         resp.text)
        kwargs = ({'category': pat.category, 'scope': pat.scope}
                  if pat is not None else {})
        raise exceptions.ProvisionerError(
            f'k8s API {method} {path} -> {resp.status_code}: '
            f'{resp.text[:500]}', **kwargs)
    return resp.json() if resp.text else {}


# ---------------------------------------------------------------------------
# Manifests
# ---------------------------------------------------------------------------
def _pod_manifest(cluster: str, pod_name: str, pc: Dict[str, Any],
                  node_rank: int, host_rank: int) -> Dict[str, Any]:
    tpu = bool(pc.get('tpu_vm'))
    container: Dict[str, Any] = {
        'name': 'sky',
        'image': pc.get('image_id') or _AGENT_IMAGE_DEFAULT,
        'command': ['/bin/sh', '-c',
                    'sleep infinity'],  # runtime bootstrapped by setup
        'ports': [{'containerPort': constants.AGENT_PORT}],
        'env': [
            {'name': 'SKYPILOT_CLUSTER', 'value': cluster},
            {'name': 'TPU_WORKER_ID', 'value': str(host_rank)},
        ],
    }
    if tpu:
        chips = int(pc.get('tpu_chips_per_host') or 4)
        container['resources'] = {
            'limits': {'google.com/tpu': chips},
            'requests': {'google.com/tpu': chips},
        }
    else:
        requests_map = {}
        if pc.get('cpus'):
            requests_map['cpu'] = str(pc['cpus'])
        if pc.get('memory'):
            requests_map['memory'] = f"{pc['memory']}Gi"
        if requests_map:
            container['resources'] = {'requests': requests_map}
    spec: Dict[str, Any] = {
        'restartPolicy': 'Never',
        'containers': [container],
        'hostname': pod_name,
        'subdomain': cluster,
    }
    # Named volumes ride the pod spec as PVC mounts (created by
    # apply_volume below; reference: sky/provision/kubernetes volumes).
    # Head pod only: the claims are ReadWriteOnce, so mounting them in
    # every pod of a multi-node cluster would wedge scheduling — this
    # mirrors the GCP path, which attaches the disk to the head host.
    volumes = pc.get('volumes') or {}
    if volumes and node_rank == 0 and host_rank == 0:
        # The agent bootstrap runs as root in the default image, so the
        # job workdir (constants.SKY_REMOTE_WORKDIR, '~/...') is under
        # /root; k8s mountPath must be absolute.
        workdir = constants.SKY_REMOTE_WORKDIR.replace('~', '/root', 1)
        spec['volumes'] = []
        container['volumeMounts'] = []
        for i, (mount_path, claim) in enumerate(sorted(volumes.items())):
            if not mount_path.startswith('/'):
                mount_path = f'{workdir}/{mount_path}'
            spec['volumes'].append({
                'name': f'skyvol-{i}',
                'persistentVolumeClaim': {'claimName': claim},
            })
            container['volumeMounts'].append({
                'name': f'skyvol-{i}', 'mountPath': mount_path})
    if tpu:
        spec['nodeSelector'] = {
            'cloud.google.com/gke-tpu-accelerator':
                pc.get('gke_tpu_accelerator',
                       _gke_accelerator(pc.get('tpu_accelerator_type', ''))),
            'cloud.google.com/gke-tpu-topology':
                pc.get('tpu_topology', ''),
        }
    return {
        'apiVersion': 'v1',
        'kind': 'Pod',
        'metadata': {
            'name': pod_name,
            'labels': {
                'skypilot-cluster': cluster,
                'skypilot-node-rank': str(node_rank),
                'skypilot-host-rank': str(host_rank),
            },
        },
        'spec': spec,
    }


def _gke_accelerator(accelerator_type: str) -> str:
    """'v5litepod-16' -> 'tpu-v5-lite-podslice'; 'v5p-128' -> 'tpu-v5p-slice'."""
    prefix = accelerator_type.split('-')[0]
    return {
        'v4': 'tpu-v4-podslice',
        'v5litepod': 'tpu-v5-lite-podslice',
        'v5p': 'tpu-v5p-slice',
        'v6e': 'tpu-v6e-slice',
    }.get(prefix, 'tpu-v5-lite-podslice')


def _service_manifest(cluster: str) -> Dict[str, Any]:
    return {
        'apiVersion': 'v1',
        'kind': 'Service',
        'metadata': {'name': cluster,
                     'labels': {'skypilot-cluster': cluster}},
        'spec': {
            'clusterIP': 'None',  # headless: per-pod DNS
            'selector': {'skypilot-cluster': cluster},
            'ports': [{'port': constants.AGENT_PORT}],
        },
    }


def _pod_names(cluster: str, num_nodes: int,
               hosts_per_node: int) -> List[Dict[str, Any]]:
    out = []
    for node in range(num_nodes):
        for host in range(hosts_per_node):
            out.append({'name': f'{cluster}-{node}-{host}',
                        'node_rank': node, 'host_rank': host})
    return out


# ---------------------------------------------------------------------------
# Interface
# ---------------------------------------------------------------------------
def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    del region
    pc = dict(config.provider_config)
    ctx = _ctx(pc)
    ns = ctx.namespace
    hosts_per_node = int(pc.get('tpu_num_hosts') or 1)
    names = _pod_names(cluster_name_on_cloud, config.count, hosts_per_node)

    try:
        _request(ctx, 'GET',
                 f'/api/v1/namespaces/{ns}/services/'
                 f'{cluster_name_on_cloud}')
    except exceptions.FetchClusterInfoError:
        _request(ctx, 'POST', f'/api/v1/namespaces/{ns}/services',
                 json_body=_service_manifest(cluster_name_on_cloud))

    created = []
    for entry in names:
        try:
            _request(ctx, 'GET',
                     f'/api/v1/namespaces/{ns}/pods/{entry["name"]}')
            continue  # exists
        except exceptions.FetchClusterInfoError:
            pass
        _request(ctx, 'POST', f'/api/v1/namespaces/{ns}/pods',
                 json_body=_pod_manifest(cluster_name_on_cloud,
                                         entry['name'], pc,
                                         entry['node_rank'],
                                         entry['host_rank']))
        created.append(entry['name'])

    pc['namespace'] = ns
    return common.ProvisionRecord(
        provider_name='kubernetes',
        cluster_name=cluster_name_on_cloud,
        region=ctx.name,
        zone=None,
        head_instance_id=names[0]['name'],
        created_instance_ids=created,
        provider_config=pc,
    )


def _list_pods(ctx: kubeconfig.KubeContext,
               cluster: str) -> List[Dict[str, Any]]:
    out = _request(
        ctx, 'GET',
        f'/api/v1/namespaces/{ctx.namespace}/pods'
        f'?labelSelector=skypilot-cluster%3D{cluster}')
    return out.get('items', [])


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None,
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    del region, state
    ctx = _ctx(provider_config)
    deadline = time.time() + constants.PROVISION_TIMEOUT_SECONDS
    while True:
        pods = _list_pods(ctx, cluster_name_on_cloud)
        phases = [p.get('status', {}).get('phase') for p in pods]
        if pods and all(ph == 'Running' for ph in phases):
            return
        if any(ph == 'Failed' for ph in phases):
            raise exceptions.ProvisionerError(
                f'Pod(s) failed for {cluster_name_on_cloud}: {phases}')
        # Unschedulable pods (stockout / no fitting node) fail over
        # instead of burning the whole provision timeout: classify the
        # scheduler's condition message through the pattern table.
        messages = '; '.join(
            f"{c.get('reason', '')}: {c.get('message', '')}"
            for p in pods
            for c in p.get('status', {}).get('conditions', []) or []
            if c.get('reason'))
        if 'Unschedulable' in messages and \
                time.time() > deadline - constants.\
                PROVISION_TIMEOUT_SECONDS + 60:
            from skypilot_tpu.provision import failover_patterns
            pat = failover_patterns.classify('kubernetes', '',
                                             messages)
            raise exceptions.ProvisionerError(
                f'Pod(s) unschedulable for {cluster_name_on_cloud}: '
                f'{messages[:400]}',
                category=(pat.category if pat else
                          exceptions.ProvisionerError.CAPACITY),
                scope=pat.scope if pat else None)
        if time.time() > deadline:
            from skypilot_tpu.provision import failover_patterns
            pat = failover_patterns.classify('kubernetes', '', messages)
            kwargs = ({'category': pat.category, 'scope': pat.scope}
                      if pat is not None else {})
            raise exceptions.ProvisionerError(
                f'Timed out waiting for pods of {cluster_name_on_cloud} '
                f'({phases}; {messages[:300]}).', **kwargs)
        time.sleep(5)


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   worker_only: bool = False) -> None:
    raise exceptions.NotSupportedError(
        'Kubernetes pods cannot be stopped; use down.')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Optional[Dict[str, Any]] = None,
                        worker_only: bool = False) -> None:
    del worker_only
    try:
        ctx = _ctx(provider_config)
    except exceptions.NoCloudAccessError:
        return
    ns = ctx.namespace
    for pod in _list_pods(ctx, cluster_name_on_cloud):
        name = pod['metadata']['name']
        try:
            _request(ctx, 'DELETE', f'/api/v1/namespaces/{ns}/pods/{name}')
        except exceptions.FetchClusterInfoError:
            pass
    try:
        _request(ctx, 'DELETE',
                 f'/api/v1/namespaces/{ns}/services/{cluster_name_on_cloud}')
    except exceptions.FetchClusterInfoError:
        pass


_PHASE_MAP = {
    'Running': 'running',
    'Pending': 'pending',
    'Succeeded': None,
    'Failed': None,
    'Unknown': 'pending',
}


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Optional[Dict[str, Any]] = None,
                    non_terminated_only: bool = True
                    ) -> Dict[str, Optional[str]]:
    ctx = _ctx(provider_config)
    out: Dict[str, Optional[str]] = {}
    for pod in _list_pods(ctx, cluster_name_on_cloud):
        status = _PHASE_MAP.get(pod.get('status', {}).get('phase'),
                                'pending')
        if non_terminated_only and status is None:
            continue
        out[pod['metadata']['name']] = status
    return out


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    del region
    ctx = _ctx(provider_config)
    pods = sorted(_list_pods(ctx, cluster_name_on_cloud),
                  key=lambda p: p['metadata']['name'])
    if not pods:
        raise exceptions.FetchClusterInfoError(
            exceptions.FetchClusterInfoError.Reason.HEAD)
    instances = []
    for pod in pods:
        meta = pod['metadata']
        labels = meta.get('labels', {})
        instances.append(common.InstanceInfo(
            instance_id=meta['name'],
            internal_ip=pod.get('status', {}).get('podIP', ''),
            external_ip=None,
            ssh_port=-1,
            agent_port=constants.AGENT_PORT,
            node_rank=int(labels.get('skypilot-node-rank', 0)),
            host_rank=int(labels.get('skypilot-host-rank', 0)),
        ))
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=instances[0].instance_id,
        provider_name='kubernetes',
        provider_config=dict(provider_config or {}),
        ssh_user='root',
        custom={'namespace': ctx.namespace, 'context': ctx.name},
    )


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Optional[Dict[str, Any]] = None) -> None:
    pass  # service/ingress exposure lands with the full k8s backend


def cleanup_ports(cluster_name_on_cloud: str, ports: List[str],
                  provider_config: Optional[Dict[str, Any]] = None) -> None:
    pass


# -- volume ops: PersistentVolumeClaims (reference:
# sky/provision/kubernetes volume support) ----------------------------------
def _pvc_manifest(name: str, size_gb: int,
                  storage_class: Optional[str] = None) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        'accessModes': ['ReadWriteOnce'],
        'resources': {'requests': {'storage': f'{int(size_gb)}Gi'}},
    }
    if storage_class:
        spec['storageClassName'] = storage_class
    return {
        'apiVersion': 'v1',
        'kind': 'PersistentVolumeClaim',
        'metadata': {'name': name,
                     'labels': {'skypilot-volume': name}},
        'spec': spec,
    }


def apply_volume(config: Dict[str, Any]) -> Dict[str, Any]:
    ctx = _ctx(config.get('provider_config'))
    name = config['name']
    path = f'/api/v1/namespaces/{ctx.namespace}/persistentvolumeclaims'
    try:
        pvc = _request(ctx, 'GET', f'{path}/{name}')
    except exceptions.FetchClusterInfoError:
        _request(ctx, 'POST', path,
                 json_body=_pvc_manifest(name,
                                         int(config.get('size_gb', 100)),
                                         config.get('storage_class')))
        pvc = _request(ctx, 'GET', f'{path}/{name}')
    return {'name': name, 'namespace': ctx.namespace,
            'status': pvc.get('status', {}).get('phase', 'Pending')}


def delete_volume(config: Dict[str, Any]) -> None:
    ctx = _ctx(config.get('provider_config'))
    path = (f'/api/v1/namespaces/{ctx.namespace}/'
            f'persistentvolumeclaims/{config["name"]}')
    try:
        _request(ctx, 'DELETE', path)
    except exceptions.FetchClusterInfoError:
        pass


def list_skypilot_pods(context: Optional[str] = None,
                       namespace: Optional[str] = None
                       ) -> List[Dict[str, Any]]:
    """All pods this framework manages in a context (any cluster) —
    backs `stpu status --kubernetes` (reference: status_kubernetes in
    sky/client/cli/command.py)."""
    ctx = _ctx({'context': context, 'namespace': namespace})
    try:
        # Cluster-scope list covers pods in every namespace.
        out = _request(ctx, 'GET',
                       '/api/v1/pods?labelSelector=skypilot-cluster')
    except exceptions.ProvisionerError:
        # RBAC may deny cluster-scope listing; fall back to the
        # context's namespace.
        out = _request(
            ctx, 'GET',
            f'/api/v1/namespaces/{ctx.namespace}/pods'
            f'?labelSelector=skypilot-cluster')
    pods = []
    for pod in out.get('items', []):
        meta = pod.get('metadata', {})
        labels = meta.get('labels', {})
        pods.append({
            'name': meta.get('name', ''),
            'cluster': labels.get('skypilot-cluster', ''),
            'node_rank': labels.get('skypilot-node-rank', '0'),
            'phase': pod.get('status', {}).get('phase', 'Unknown'),
            'node': pod.get('spec', {}).get('nodeName', ''),
            'namespace': meta.get('namespace', ctx.namespace),
        })
    return pods
