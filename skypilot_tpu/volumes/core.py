"""Volumes: named persistent disks as first-class objects.

Reference: sky/volumes/ — network/instance volumes (k8s PVC, GCP PD)
with CRUD via the API server. Round-1 scope: registry CRUD + GCP PD
deploy-variable plumbing; actual disk attach lands with the GCE VM
path.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_state


def apply(name: str, size_gb: int, infra: Optional[str] = None,
          volume_type: str = 'pd-balanced') -> Dict[str, Any]:
    config = {
        'name': name,
        'size_gb': int(size_gb),
        'infra': infra or 'gcp',
        'type': volume_type,
        'created_at': time.time(),
    }
    with global_state._db().conn() as conn:  # pylint: disable=protected-access
        conn.execute(
            'INSERT INTO volumes (name, launched_at, config, status) '
            'VALUES (?,?,?,?) ON CONFLICT(name) DO UPDATE SET '
            'config=excluded.config',
            (name, int(time.time()), json.dumps(config), 'READY'))
    return config


def ls() -> List[Dict[str, Any]]:
    rows = global_state._db().query(  # pylint: disable=protected-access
        'SELECT * FROM volumes ORDER BY name')
    out = []
    for r in rows:
        cfg = json.loads(r['config'] or '{}')
        cfg['status'] = r['status']
        out.append(cfg)
    return out


def delete(name: str) -> None:
    row = global_state._db().query_one(  # pylint: disable=protected-access
        'SELECT name FROM volumes WHERE name=?', (name,))
    if row is None:
        raise exceptions.SkyError(f'Volume {name!r} not found.')
    global_state._db().execute(  # pylint: disable=protected-access
        'DELETE FROM volumes WHERE name=?', (name,))
