"""Volumes: named persistent disks as first-class objects.

Reference: sky/volumes/ + the provisioner volume ops
(sky/provision/__init__.py:235-310). `apply` really creates the
backing store (GCP PD / k8s PVC / Local host dir) through the routed
provisioner interface; `delete` destroys it; tasks mount volumes via
the `volumes: {mount_path: name}` YAML field (backend attach+mount at
file-mount time).
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import provision as provision_lib


def apply(name: str, size_gb: int, infra: Optional[str] = None,
          volume_type: str = 'pd-balanced',
          zone: Optional[str] = None) -> Dict[str, Any]:
    """Create (or adopt) the backing volume and register it."""
    provider = (infra or 'gcp').split('/')[0].lower()
    config = {
        'name': name,
        'size_gb': int(size_gb),
        'infra': provider,
        'type': volume_type,
        'created_at': time.time(),
    }
    if zone or (infra and '/' in infra):
        config['zone'] = zone or infra.split('/')[-1]
    result = provision_lib.apply_volume(provider, config)
    config.update({k: v for k, v in result.items() if k != 'status'})
    with global_state._db().conn() as conn:  # pylint: disable=protected-access
        conn.execute(
            'INSERT INTO volumes (name, launched_at, config, status) '
            'VALUES (?,?,?,?) ON CONFLICT(name) DO UPDATE SET '
            'config=excluded.config, status=excluded.status',
            (name, int(time.time()), json.dumps(config),
             result.get('status', 'READY')))
    return {**config, 'status': result.get('status', 'READY')}


def get(name: str) -> Optional[Dict[str, Any]]:
    row = global_state._db().query_one(  # pylint: disable=protected-access
        'SELECT * FROM volumes WHERE name=?', (name,))
    if row is None:
        return None
    cfg = json.loads(row['config'] or '{}')
    cfg['status'] = row['status']
    return cfg


def ls() -> List[Dict[str, Any]]:
    rows = global_state._db().query(  # pylint: disable=protected-access
        'SELECT * FROM volumes ORDER BY name')
    out = []
    for r in rows:
        cfg = json.loads(r['config'] or '{}')
        cfg['status'] = r['status']
        out.append(cfg)
    return out


def delete(name: str) -> None:
    record = get(name)
    if record is None:
        raise exceptions.SkyError(f'Volume {name!r} not found.')
    provider = record.get('infra', 'gcp')
    provision_lib.delete_volume(provider, record)
    global_state._db().execute(  # pylint: disable=protected-access
        'DELETE FROM volumes WHERE name=?', (name,))
