"""Explicit pipeline schedules: the op stream GPipe/1F1B/interleaved
runners execute.

`make_schedule(stages, microbatches, style, virtual_stages)` is a PURE
function of its four arguments: it emits the complete per-tick op
stream `[(tick, stage, microbatch, fwd|bwd)]` (plus the virtual-stage
index under interleaving) and every derived artifact the shard_map
runner in parallel/pipeline.py needs — dense [ticks, stages] lookup
tables, activation/cotangent buffer slot assignments, and receive-ring
geometry for the stage-to-stage ppermute links. No jax imports: the
schedule is host-side numpy, testable without devices, and the same
accounting (`bubble_fraction`, `peak_live_activations`) feeds the
step-metrics gauge, `bench.py --sweep-pipeline`, and the invariant
test battery.

Styles (S stages, M microbatches, v virtual stages per device; one op
— a chunk forward or a chunk backward — per device per tick):

  gpipe        fill/drain with a full flush between the phases: all
               forwards, then all backwards. Span 2(M + S - 1) ticks,
               per-device bubble 2(S - 1), but every stage holds all
               M in-flight activations at the flush.
  1f1b         PipeDream-flush one-forward-one-backward: backwards
               get priority and forward admission is capped at S
               in-flight microbatches, so peak live activations per
               stage drop from M to <= S. Same span and bubble count
               as gpipe — the schedule does not run faster at equal
               M, it runs at HIGHER M in the same memory, and that is
               what shrinks the bubble fraction (S-1)/(M+S-1).
  interleaved  1f1b over v virtual stages (layer chunks) per device:
               device s hosts chunks s, S+s, ..., (v-1)S+s (the
               Megatron interleaved-1F1B program; microbatches must
               divide into groups of S). Each device performs 2Mv
               (v-times smaller) ops, the span grows to
               2(Mv + S - 1) ticks but the bubble stays 2(S - 1)
               per device — the fraction (S-1)/(Mv+S-1) is the
               Megatron "bubble / v" — at the cost of holding up to
               2(S-1) + (v-1)S + 1 chunk inputs per device.

The closed forms asserted by tests/unit_tests/test_pipeline_schedule:
every style spans exactly 2(M*v + S - 1) ticks with exactly 2(S - 1)
bubble slots per device (so bubble fraction = (S-1)/(Mv+S-1), and the
styles differ in WHERE the slack goes: gpipe holds all M activations
at the flush, 1f1b caps them at min(M, S), interleaved divides the
fraction by v); peak live activations are exactly M (gpipe) and
min(M, S) (1f1b, stage 0).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

FWD = 1
BWD = 2

STYLES = ('gpipe', '1f1b', 'interleaved')


@dataclasses.dataclass(frozen=True)
class PipelineOp:
    """One scheduled op: device `stage` runs the forward or backward
    of `virtual` (the global virtual-stage index; == stage when
    virtual_stages == 1) for `microbatch` at `tick`."""
    tick: int
    stage: int
    microbatch: int
    virtual: int
    kind: int  # FWD | BWD

    @property
    def direction(self) -> str:
        return 'fwd' if self.kind == FWD else 'bwd'


@dataclasses.dataclass(frozen=True)
class PipelineSchedule:
    """The op stream plus the accounting and runner tables derived
    from it. Immutable; build with `make_schedule`."""
    stages: int
    microbatches: int
    style: str
    virtual_stages: int
    ops: Tuple[PipelineOp, ...]
    num_ticks: int
    # Dense runner tables, all [num_ticks, stages] int32 unless noted.
    tables: Dict[str, np.ndarray]
    # Peak concurrently-stored chunk inputs, per device.
    live_peak_per_stage: Tuple[int, ...]
    # Receive-ring depths for the fwd/bwd ppermute links.
    rx_fwd_depth: int
    rx_bwd_depth: int
    # Cotangent buffer depth (last-virtual-stage loss grads).
    gy_depth: int

    # -- accounting --------------------------------------------------
    @property
    def total_slots(self) -> int:
        return self.num_ticks * self.stages

    @property
    def busy_slots(self) -> int:
        return len(self.ops)

    @property
    def bubble_slots(self) -> int:
        """Idle (tick, stage) slots over the whole schedule."""
        return self.total_slots - self.busy_slots

    @property
    def bubble_fraction(self) -> float:
        return self.bubble_slots / self.total_slots

    @property
    def peak_live_activations(self) -> int:
        """Max chunk inputs any device stores at once — the schedule's
        activation-memory height in units of one [mb, seq, embed]
        buffer (chunk inputs are full residual width regardless of
        how many layers the chunk holds)."""
        return max(self.live_peak_per_stage)

    def activation_bytes(self, microbatch_tokens: int, embed_dim: int,
                         bytes_per_el: int = 2) -> int:
        """Activation-buffer memory proxy for one device: stored chunk
        inputs only (layer-internal activations are rematerialized by
        the runner's backward)."""
        return (self.peak_live_activations * microbatch_tokens *
                embed_dim * bytes_per_el)

    def describe(self) -> str:
        return (f'{self.style}(S={self.stages}, M={self.microbatches}'
                f', v={self.virtual_stages}): {self.num_ticks} ticks, '
                f'bubble {self.bubble_slots}/{self.total_slots} '
                f'({self.bubble_fraction:.1%}), peak live acts '
                f'{self.peak_live_activations}')


def _device_sequence(rank: int, stages: int, microbatches: int,
                     style: str, virtual_stages: int
                     ) -> List[Tuple[int, int, int]]:
    """Device `rank`'s op program as an ordered list of
    (kind, virtual, microbatch) — the per-rank recipe, before timing.

      gpipe        all forwards (microbatch order), then all
                   backwards: the fill/drain flush.
      1f1b         PipeDream-flush: S-rank-1 warmup forwards, then
                   strict fwd/bwd alternation, then the backward
                   drain.
      interleaved  the Megatron interleaved-1F1B program: microbatch
                   groups of size S cycle through the device's v
                   chunks (forwards deepest-last, backwards
                   deepest-first), warmup 2(S-rank-1) + (v-1)S
                   chunk-forwards deep.
    """
    S, M, v = stages, microbatches, virtual_stages
    total_f = M * v

    if v == 1:
        def fwd_of(i):
            return rank, i

        def bwd_of(j):
            return rank, j
        warmup = total_f if style == 'gpipe' else min(S - rank - 1,
                                                      total_f)
    else:
        def fwd_of(i):
            group, w = divmod(i, S * v)
            return (w // S) * S + rank, group * S + w % S

        def bwd_of(j):
            group, w = divmod(j, S * v)
            return (v - 1 - w // S) * S + rank, group * S + w % S
        warmup = min(2 * (S - rank - 1) + (v - 1) * S, total_f)

    seq: List[Tuple[int, int, int]] = []
    fi = bi = 0
    for _ in range(warmup):
        seq.append((FWD,) + fwd_of(fi))
        fi += 1
    while fi < total_f:
        seq.append((FWD,) + fwd_of(fi))
        fi += 1
        seq.append((BWD,) + bwd_of(bi))
        bi += 1
    while bi < total_f:
        seq.append((BWD,) + bwd_of(bi))
        bi += 1
    return seq


def _schedule_ops(stages: int, microbatches: int, style: str,
                  virtual_stages: int) -> List[PipelineOp]:
    """Lockstep timing for the per-device programs: each tick, every
    device attempts the NEXT op of its sequence and stalls (a bubble
    tick) until the op's inputs exist.

    Dependency rules — completions land at END of tick, so a
    dependency satisfied at tick t unblocks from t+1 (the ppermute
    hand-off takes the tick boundary): fwd(vs, m) needs
    fwd(vs-1, m); bwd(vs, m) needs fwd(vs, m) (whose tick also
    produced the loss cotangent when vs is last) and bwd(vs+1, m).
    """
    S, M, v = stages, microbatches, virtual_stages
    V = S * v
    seqs = [_device_sequence(r, S, M, style, v) for r in range(S)]
    ptr = [0] * S
    fwd_done: Dict[Tuple[int, int], int] = {}
    bwd_done: Dict[Tuple[int, int], int] = {}
    ops: List[PipelineOp] = []
    total = 2 * V * M
    t = 0
    # The per-rank programs are deadlock-free by construction; a bug
    # must fail loudly, not spin.
    max_ticks = 4 * (V * M + V + M + 8)
    while len(ops) < total:
        if t > max_ticks:
            raise RuntimeError(
                f'schedule generation did not converge: {style} S={S} '
                f'M={M} v={v} stuck at tick {t}')
        fired = []
        for r in range(S):
            if ptr[r] >= len(seqs[r]):
                continue
            kind, vs, m = seqs[r][ptr[r]]
            if kind == FWD:
                ready = vs == 0 or fwd_done.get((vs - 1, m), t) < t
            else:
                ready = fwd_done.get((vs, m), t) < t and (
                    vs == V - 1 or bwd_done.get((vs + 1, m), t) < t)
            if ready:
                fired.append((r, kind, vs, m))
                ptr[r] += 1
        for r, kind, vs, m in fired:
            (fwd_done if kind == FWD else bwd_done)[(vs, m)] = t
            ops.append(PipelineOp(t, r, m, vs, kind))
        t += 1
    return ops


def _assign_slots(events: List[Tuple[int, int, str, int]],
                  label: str) -> Tuple[Dict[Tuple[int, int], int], int]:
    """Free-list slot assignment for (write tick, read tick) pairs.

    events: (write_tick, read_tick, key...) sorted by write tick; a
    slot is busy from its write until its read (inclusive). Returns
    ({key: slot}, depth)."""
    free: List[int] = []
    next_slot = 0
    release_at: Dict[int, List[int]] = {}
    slots: Dict[Tuple[int, int], int] = {}
    for wt, rt, *key in sorted(events):
        for old in sorted(release_at.pop(wt, []) + []):
            free.append(old)
        # Also release anything whose read tick passed before wt.
        for rel_t in [k for k in release_at if k < wt]:
            free.extend(release_at.pop(rel_t))
        slot = free.pop(0) if free else next_slot
        if slot == next_slot:
            next_slot += 1
        slots[tuple(key)] = slot
        release_at.setdefault(rt + 1, []).append(slot)
    if next_slot == 0:
        next_slot = 1  # runners always carry a non-empty buffer
    return slots, next_slot


def make_schedule(stages: int, microbatches: int, style: str = 'gpipe',
                  virtual_stages: int = 1) -> PipelineSchedule:
    """Build the explicit schedule. Pure: same args, same stream."""
    if style not in STYLES:
        raise ValueError(f'style must be one of {STYLES}; got {style!r}')
    if stages < 2:
        raise ValueError(f'pipeline schedules need >= 2 stages; got '
                         f'{stages}')
    if microbatches < 1:
        raise ValueError('microbatches must be >= 1')
    if style == 'interleaved':
        if virtual_stages < 2:
            raise ValueError('interleaved needs virtual_stages >= 2')
        if microbatches % stages:
            raise ValueError(
                f'interleaved cycles microbatch groups of size '
                f'stages={stages} through the virtual chunks; '
                f'microbatches={microbatches} must be a multiple')
    elif virtual_stages != 1:
        raise ValueError(f'{style} runs with virtual_stages == 1 '
                         f'(got {virtual_stages}); pick interleaved '
                         f'for virtual-stage chunking')
    S, M, v = stages, microbatches, virtual_stages
    V = S * v
    ops = _schedule_ops(S, M, style, v)
    T = max(op.tick for op in ops) + 1

    # Index ops for table construction + validation.
    fwd_tick = {}
    bwd_tick = {}
    by_slot: Dict[Tuple[int, int], PipelineOp] = {}
    for op in ops:
        key = (op.tick, op.stage)
        if key in by_slot:
            raise AssertionError(
                f'two ops on stage {op.stage} at tick {op.tick}')
        by_slot[key] = op
        if op.kind == FWD:
            fwd_tick[(op.virtual, op.microbatch)] = op.tick
        else:
            bwd_tick[(op.virtual, op.microbatch)] = op.tick

    # -- activation slots (per device): a chunk input is stored at its
    # fwd tick and read back at its bwd tick.
    act_slots: Dict[int, Dict[Tuple[int, int], int]] = {}
    live_peak = []
    act_depth = 1
    for s in range(S):
        events = []
        for k in range(v):
            vs = k * S + s
            for m in range(M):
                events.append((fwd_tick[(vs, m)], bwd_tick[(vs, m)],
                               vs, m))
        slots, depth = _assign_slots(events, f'act[stage {s}]')
        act_slots[s] = slots
        act_depth = max(act_depth, depth)
        live_peak.append(depth)

    # -- loss-cotangent slots: gy for (V-1, m) is produced at the fwd
    # tick of the last virtual stage and consumed at its bwd tick.
    gy_events = [(fwd_tick[(V - 1, m)], bwd_tick[(V - 1, m)], m)
                 for m in range(M)]
    gy_slots, gy_depth = _assign_slots(gy_events, 'gy')

    # -- receive rings. A fwd message for (vs, m), vs in [1, V), is
    # produced at fwd_tick[vs-1, m] on device (vs-1) % S and consumed
    # at fwd_tick[vs, m] on device vs % S; bwd messages mirror it.
    rxf_events = [(fwd_tick[(vs - 1, m)], fwd_tick[(vs, m)], vs, m)
                  for vs in range(1, V) for m in range(M)]
    rxb_events = [(bwd_tick[(vs + 1, m)], bwd_tick[(vs, m)], vs, m)
                  for vs in range(V - 1) for m in range(M)]
    # Ring depth must be uniform across devices (SPMD buffer), so
    # assign per consuming device but take the max depth.
    rxf_slots: Dict[Tuple[int, int], int] = {}
    rxf_depth = 1
    for s in range(S):
        ev = [e for e in rxf_events if e[2] % S == s]
        slots, depth = _assign_slots(ev, f'rxf[{s}]')
        rxf_slots.update(slots)
        rxf_depth = max(rxf_depth, depth)
    rxb_slots: Dict[Tuple[int, int], int] = {}
    rxb_depth = 1
    for s in range(S):
        ev = [e for e in rxb_events if e[2] % S == s]
        slots, depth = _assign_slots(ev, f'rxb[{s}]')
        rxb_slots.update(slots)
        rxb_depth = max(rxb_depth, depth)

    # -- dense runner tables ----------------------------------------
    z = lambda: np.full((T, S), -1, dtype=np.int32)  # noqa: E731
    tables = {
        'op_kind': np.zeros((T, S), dtype=np.int32),
        'op_mb': z(), 'op_chunk': z(), 'op_virtual': z(),
        'act_slot': z(),
        # fwd-message routing: slot the PRODUCER's output is written
        # to on the consumer (indexed by producer tick/stage), and the
        # slot a consuming fwd op reads (indexed by consumer).
        'rxf_wslot': z(), 'rxf_rslot': z(),
        'rxb_wslot': z(), 'rxb_rslot': z(),
    }
    # Per-tick scalars (int32 [T]).
    embed_mb = np.full((T,), -1, dtype=np.int32)   # fwd of virtual 0
    gy_mb = np.full((T,), -1, dtype=np.int32)      # fwd of virtual V-1
    gy_wslot = np.full((T,), -1, dtype=np.int32)
    gy_rslot = np.full((T,), -1, dtype=np.int32)
    embv_mb = np.full((T,), -1, dtype=np.int32)    # bwd of virtual 0

    for op in ops:
        t, s, m, vs = op.tick, op.stage, op.microbatch, op.virtual
        tables['op_kind'][t, s] = op.kind
        tables['op_mb'][t, s] = m
        tables['op_chunk'][t, s] = vs // S
        tables['op_virtual'][t, s] = vs
        tables['act_slot'][t, s] = act_slots[s][(vs, m)]
        if op.kind == FWD:
            if vs == 0:
                embed_mb[t] = m
            if vs == V - 1:
                gy_mb[t] = m
                gy_wslot[t] = gy_slots[(m,)]
            else:
                # This output travels the fwd ring to device (s+1)%S.
                tables['rxf_wslot'][t, s] = rxf_slots[(vs + 1, m)]
            if vs > 0:
                tables['rxf_rslot'][t, s] = rxf_slots[(vs, m)]
        else:
            if vs == V - 1:
                gy_rslot[t] = gy_slots[(m,)]
            else:
                tables['rxb_rslot'][t, s] = rxb_slots[(vs, m)]
            if vs == 0:
                embv_mb[t] = m
            else:
                tables['rxb_wslot'][t, s] = rxb_slots[(vs - 1, m)]
    tables['embed_mb'] = embed_mb
    tables['gy_mb'] = gy_mb
    tables['gy_wslot'] = gy_wslot
    tables['gy_rslot'] = gy_rslot
    tables['embv_mb'] = embv_mb

    sched = PipelineSchedule(
        stages=S, microbatches=M, style=style, virtual_stages=v,
        ops=tuple(ops), num_ticks=T, tables=tables,
        live_peak_per_stage=tuple(live_peak),
        rx_fwd_depth=rxf_depth, rx_bwd_depth=rxb_depth,
        gy_depth=gy_depth)
    _validate(sched, fwd_tick, bwd_tick)
    return sched


def _validate(sched: PipelineSchedule, fwd_tick: Dict, bwd_tick: Dict
              ) -> None:
    """Structural invariants every emitted schedule must satisfy (the
    test battery re-asserts these from the public op stream)."""
    S, M, V = (sched.stages, sched.microbatches,
               sched.stages * sched.virtual_stages)
    assert len(sched.ops) == 2 * V * M, 'op count'
    for vs in range(V):
        for m in range(M):
            f, b = fwd_tick[(vs, m)], bwd_tick[(vs, m)]
            assert f >= 0 and b >= 0, (vs, m)
            assert f < b or (vs == V - 1 and f < b), \
                f'bwd before fwd at vs={vs} m={m}'
            if vs > 0:
                assert fwd_tick[(vs - 1, m)] < f, 'fwd chain order'
            if vs < V - 1:
                assert bwd_tick[(vs + 1, m)] < b, 'bwd chain order'


# -- Inference (serving) schedules: forward-only (PR 19) --------------------
@dataclasses.dataclass(frozen=True)
class InferenceSchedule:
    """The forward-only op stream of staged serving: no backwards, no
    flush, no activation stash — a microbatch (one prefill chunk in
    the serving engine; the chunked-prefill fixed-shape chunk IS the
    natural microbatch) enters stage 0 and ripples through the S
    stages, one stage per tick. Span is M + S - 1 ticks and the only
    idle slots are the fill/drain triangles: bubble fraction
    (S - 1)·S / ((M + S - 1)·S) = (S - 1)/(M + S - 1) — half the
    training closed form because there is no backward wave. Build
    with `make_inference_schedule`; the engine's prefill-bubble gauge
    and serve_bench's `--pp-ab` report read `bubble_fraction` from
    here rather than re-deriving it."""
    stages: int
    microbatches: int
    ops: Tuple[PipelineOp, ...]
    num_ticks: int

    @property
    def total_slots(self) -> int:
        return self.num_ticks * self.stages

    @property
    def busy_slots(self) -> int:
        return len(self.ops)

    @property
    def bubble_slots(self) -> int:
        return self.total_slots - self.busy_slots

    @property
    def bubble_fraction(self) -> float:
        return self.bubble_slots / self.total_slots

    def describe(self) -> str:
        return (f'inference(S={self.stages}, M={self.microbatches}): '
                f'{self.num_ticks} ticks, bubble '
                f'{self.bubble_slots}/{self.total_slots} '
                f'({self.bubble_fraction:.1%})')


def make_inference_schedule(stages: int,
                            microbatches: int) -> InferenceSchedule:
    """Forward-only schedule: microbatch m runs on stage s at tick
    m + s. Pure; allows stages == 1 (span M, zero bubble) so the
    engine's accounting degenerates cleanly for unstaged serving."""
    if stages < 1:
        raise ValueError(f'stages must be >= 1; got {stages}')
    if microbatches < 1:
        raise ValueError('microbatches must be >= 1')
    ops = tuple(PipelineOp(m + s, s, m, s, FWD)
                for m in range(microbatches) for s in range(stages))
    sched = InferenceSchedule(stages=stages, microbatches=microbatches,
                              ops=ops,
                              num_ticks=microbatches + stages - 1)
    assert sched.num_ticks == closed_form_inference_span(
        stages, microbatches), 'inference span'
    return sched


def closed_form_inference_span(stages: int, microbatches: int) -> int:
    """Analytic tick count of the forward-only stream: M + S - 1
    (bubble fraction (S - 1)/(M + S - 1))."""
    return microbatches + stages - 1


def closed_form_span(stages: int, microbatches: int, style: str,
                     virtual_stages: int = 1) -> int:
    """Analytic tick count: every style spans exactly
    2(M * v + S - 1) — M*v ops per device plus the 2(S-1)-tick
    fill/drain skew. Per-device bubble is always 2(S - 1) ticks; the
    styles trade WHERE the memory goes, and interleaving divides the
    bubble FRACTION by v by making each tick v-times smaller."""
    del style  # same span for gpipe / 1f1b / interleaved
    return 2 * (microbatches * virtual_stages + stages - 1)
