"""Pipeline parallelism over a `stage` mesh axis via shard_map.

The TPU-native formulation (scaling-book recipe, not a port of the
reference's NCCL send/recv schedules): layer parameters are STACKED
([L, ...] leaves) and sharded over the mesh's `stage` axis, the
schedule runs inside ONE `shard_map`, and stage-to-stage transfer is
`lax.ppermute` (XLA collective-permute on ICI).

Two execution engines share that frame, selected by `schedule=`:

  gpipe (default)   the fused fill/drain scan: microbatch ingestion,
      per-stage layer application and activation hand-off are ONE
      `lax.scan`, and backward needs nothing hand-written — jax.grad
      differentiates through the scan and the ppermutes (a ppermute's
      transpose is the reverse ppermute), so the drain schedule falls
      out of AD. Every stage holds all M microbatch activations at
      the flush: memory O(M).

  1f1b / interleaved   the explicit-schedule runner: the op stream
      from parallel/pipeline_schedule.py (one chunk-forward or
      chunk-backward per stage per tick) executes under a
      `lax.switch` inside the tick scan, with hand-rolled backward —
      each backward op re-runs its chunk forward under `jax.vjp`
      from the stored chunk INPUT (per-chunk rematerialization) and
      accumulates parameter grads as it goes. 1F1B caps stored chunk
      inputs at S (vs GPipe's M): that memory headroom is what buys
      the larger microbatch counts that actually shrink the bubble
      fraction (S-1)/(M+S-1), and interleaved virtual stages divide
      the fraction by v on top. Collectives (vocab-parallel embed,
      head psum, the two ppermute rings) run UNCONDITIONALLY every
      tick — only the local chunk compute sits under the switch, so
      no device can diverge at a collective.

All schedules span 2(M*v + S - 1) ticks with 2(S - 1) bubble ticks
per device (see pipeline_schedule.py for the accounting the
step-metrics gauge and `bench.py --sweep-pipeline` report).

v2 (closes the v1 composition gaps):
  - tensor/fsdp/expert COMPOSE WITHIN STAGES: only `stage` and `data`
    are manual shard_map axes (`axis_names`); the rest stay under
    GSPMD, so stacked block leaves carry their usual logical-rule
    shardings (heads/mlp→tensor, embed→fsdp, expert→expert) on their
    inner dims and XLA inserts the within-stage collectives.
  - the embedding table and LM head are STAGE-SHARDED over the vocab
    dim (no longer replicated on every stage — the HBM that matters
    at 70B scale): embedding is a masked local gather + psum;
    the head is a vocab-parallel matmul with a psum/pmax logsumexp
    cross-entropy, which also spreads the head FLOPs across all
    stages instead of serializing them on the last one.
  - `num_layers % stages != 0` is allowed: the stack is zero-padded
    and padded slots are masked to identity in the per-stage scan.

Families: GPT, Llama, Mixtral (Mixtral's router aux loss is
accumulated across stages with live-tick masking; its batch-mean
products make the faithful reference the mean of per-microbatch
losses). Dropout is rejected (blocks run deterministically).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.parallel import pipeline_schedule as psched
from skypilot_tpu.parallel.train import TrainState


def stack_layer_params(params: Dict[str, Any], prefix: str,
                       num_layers: int,
                       pad_to: int = 0) -> Tuple[Any, Dict[str, Any]]:
    """Split a model's params into (stacked block leaves [L, ...],
    everything else). The stacked tree's structure is ONE block's.
    `pad_to > num_layers` zero-pads the stack (padded slots are
    masked to identity in the pipeline's per-stage scan)."""
    layers = [params[f'{prefix}{i}'] for i in range(num_layers)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    if pad_to > num_layers:
        pad = pad_to - num_layers
        stacked = jax.tree.map(
            lambda x: jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1)),
            stacked)
    rest = {k: v for k, v in params.items()
            if not (k.startswith(prefix) and
                    k[len(prefix):].isdigit())}
    return stacked, rest


def unstack_layer_params(stacked: Any, rest: Dict[str, Any],
                         prefix: str, num_layers: int) -> Dict[str, Any]:
    """Inverse of stack_layer_params (checkpoint interop); ignores
    padded tail slots."""
    out = dict(rest)
    for i in range(num_layers):
        out[f'{prefix}{i}'] = jax.tree.map(lambda x, i=i: x[i], stacked)
    return out


def _vp_next_token_loss(local_logits: jax.Array, tokens: jax.Array,
                        stage: jax.Array, vshard: int,
                        vocab: int) -> jax.Array:
    """Vocab-parallel causal LM loss over the `stage` axis.

    local_logits: [B, S, vshard] — this stage's vocab shard (global
    column range [stage*vshard, (stage+1)*vshard), columns >= vocab
    are padding). Mirrors train.next_token_loss numerics: f32
    logsumexp with global-max subtraction (pmax), target logit via
    masked local gather + psum."""
    logits = local_logits[:, :-1].astype(jnp.float32)
    targets = tokens[:, 1:]
    off = stage * vshard
    # Padded vocab columns must not contribute mass.
    valid = off + jnp.arange(vshard) < vocab
    logits = jnp.where(valid[None, None, :], logits, -jnp.inf)
    lid = targets - off
    ok = jnp.logical_and(lid >= 0, lid < vshard)
    tl = jnp.take_along_axis(
        logits, jnp.clip(lid, 0, vshard - 1)[..., None], axis=-1)[..., 0]
    target_logit = jax.lax.psum(jnp.where(ok, tl, 0.0), 'stage')
    # Global max: any m makes lse exact; stop_gradient keeps AD on the
    # softmax path (d lse/d logits = softmax regardless of m).
    # all_gather + max, not pmax: pmax has no differentiation rule
    # (even a zero tangent must flow through the primitive).
    m = jax.lax.stop_gradient(jnp.max(
        jax.lax.all_gather(jnp.max(logits, axis=-1), 'stage'), axis=0))
    se = jax.lax.psum(
        jnp.sum(jnp.exp(logits - m[..., None]), axis=-1), 'stage')
    lse = m + jnp.log(se)
    return jnp.mean(lse - target_logit)


class _Family(NamedTuple):
    """Per-model-family pipeline adapter.

    vocab_dims maps rest-leaf name -> the dim carrying the vocab
    (stage-sharded + padded to stages * vshard). embed_vp returns the
    (psum-combined) input embedding from the LOCAL vocab shard;
    head_local returns this stage's [B, S, vshard] logits slice."""
    prefix: str
    block: Any
    takes_positions: bool
    returns_aux: bool
    vocab_dims: Dict[str, int]
    embed_vp: Callable
    head_local: Callable


def _stage_psum(x: jax.Array) -> jax.Array:
    """psum over `stage`, carried in f32. Every caller has exactly ONE
    nonzero contributor (masked gather / masked broadcast), so the
    f32 round-trip is exact for bf16 inputs. Uniform f32 also keeps
    XLA's all-reduce combiner away from mixed bf16/f32 tuple
    all-reduces, whose dtype-rewrite pass crashes on CPU."""
    return jax.lax.psum(x.astype(jnp.float32), 'stage').astype(x.dtype)


def _vp_gather(table: jax.Array, tokens: jax.Array, stage: jax.Array,
               vshard: int) -> jax.Array:
    """Embedding lookup against this stage's vocab shard: gather the
    locally-owned rows (others masked to 0) and psum — exactly one
    stage owns each id, so the sum reassembles the global gather."""
    lid = tokens - stage * vshard
    ok = jnp.logical_and(lid >= 0, lid < vshard)
    x = table[jnp.clip(lid, 0, vshard - 1)]
    return _stage_psum(jnp.where(ok[..., None], x, 0))


def _gpt_embed_vp(rest, tokens, cfg, stage, vshard):
    x = _vp_gather(rest['wte'].astype(cfg.dtype), tokens, stage, vshard)
    return x + rest['wpe'].astype(cfg.dtype)[:tokens.shape[1]]


def _llama_embed_vp(rest, tokens, cfg, stage, vshard):
    return _vp_gather(rest['tok_embed'].astype(cfg.dtype), tokens,
                      stage, vshard)


def _family_of(model) -> _Family:
    # head_local reuses the models' own final_norm_logits helpers
    # unchanged: the vocab dim is only the einsum OUTPUT dim, so they
    # work on a local vocab shard as-is — and head/norm changes in the
    # model files cannot silently diverge from the pipelined path.
    from skypilot_tpu.models import gpt as gpt_lib
    from skypilot_tpu.models import llama as llama_lib
    from skypilot_tpu.models import mixtral as mixtral_lib
    if isinstance(model, gpt_lib.GPT):
        return _Family('h_', gpt_lib.Block(model.config), False, False,
                       {'wte': 0}, _gpt_embed_vp,
                       gpt_lib.final_norm_logits)
    if isinstance(model, llama_lib.Llama):
        return _Family('layer_', llama_lib.Block(model.config), True,
                       False, {'tok_embed': 0, 'lm_head': 1},
                       _llama_embed_vp, llama_lib.final_norm_logits)
    if isinstance(model, mixtral_lib.Mixtral):
        return _Family('layer_', mixtral_lib.Block(model.config), True,
                       True, {'tok_embed': 0, 'lm_head': 1},
                       _llama_embed_vp, llama_lib.final_norm_logits)
    from skypilot_tpu.models import deepseek as deepseek_lib
    if isinstance(model, deepseek_lib.Deepseek):
        # MLA blocks are llama-shaped at the pipeline seam (same
        # (x, positions) signature, same tok_embed/final_norm/lm_head
        # param layout, RMSNorm shared with llama) — the latent-KV
        # machinery is internal to the block.
        return _Family('layer_', deepseek_lib.Block(model.config), True,
                       False, {'tok_embed': 0, 'lm_head': 1},
                       _llama_embed_vp, llama_lib.final_norm_logits)
    raise ValueError(
        f'Pipeline parallelism supports the GPT, Llama, Mixtral, and '
        f'DeepSeek families; got {type(model).__name__}')


class PipelinedLM:
    """Pipeline-parallel training step (GPT/Llama/Mixtral/DeepSeek).

    Usage:
        pp = PipelinedLM(model, mesh, num_microbatches=8,
                         schedule='1f1b')
        stacked, rest = pp.split_params(params)
        loss = pp.loss(stacked, rest, tokens)          # jittable
        step = pp.make_train_step(tx)                  # optimizer step

    `schedule` picks the engine (module docstring): 'gpipe' is the
    fused scan + AD backward; '1f1b'/'interleaved' execute the
    explicit op stream from pipeline_schedule.make_schedule with
    hand-rolled backward. `virtual_stages` (interleaved only) is the
    number of layer chunks each device hosts.
    """

    def __init__(self, model, mesh: Mesh,
                 num_microbatches: int = 8,
                 remat_ticks: bool = True,
                 schedule: str = 'gpipe',
                 virtual_stages: int = 1) -> None:
        self.model = model
        self.cfg = model.config
        self.mesh = mesh
        self.num_stages = mesh.shape['stage']
        self.num_microbatches = num_microbatches
        # Rematerialize each schedule tick: backward recomputes the
        # tick's layer forwards instead of keeping every tick's
        # intermediate activations live — the memory profile pipeline
        # training needs (activations scale with ticks = M + S - 1
        # otherwise). Equality-tested on, off in test_pipeline.py.
        # (gpipe engine only: the explicit runner's backward ops
        # rematerialize per chunk by construction.)
        self.remat_ticks = remat_ticks
        self.family = _family_of(model)
        self._prefix = self.family.prefix
        if getattr(self.cfg, 'dropout_rate', 0.0):
            raise ValueError(
                'PipelinedLM runs blocks deterministically; '
                'dropout_rate > 0 would be silently ignored — train '
                'without dropout or use ShardedTrainer.')
        if getattr(self.cfg, 'remat', False):
            raise ValueError(
                'PipelinedLM does not rematerialize blocks; set '
                'remat=False (per-tick remat already bounds live '
                'activations — see remat_ticks).')
        S = self.num_stages
        # The schedule object validates style/virtual_stages/M and
        # carries the bubble/memory accounting even for gpipe (where
        # the fused scan executes the same logical stream).
        self.schedule_style = schedule
        self.virtual_stages = virtual_stages
        self.schedule = psched.make_schedule(
            S, num_microbatches, style=schedule,
            virtual_stages=virtual_stages)
        # Uneven layer counts pad the stack with masked identity slots
        # (the padded blocks' zero params stay zero: grads are masked,
        # so adamw never moves them). Chunking is per VIRTUAL stage:
        # each device hosts v chunks of layers_per_chunk layers.
        V = S * virtual_stages
        self.layers_per_chunk = -(-self.cfg.num_layers // V)
        self.layers_per_stage = self.layers_per_chunk * virtual_stages
        self.padded_layers = self.layers_per_chunk * V
        # Vocab is stage-sharded for the embedding/head; pad to S.
        self.vshard = -(-self.cfg.vocab_size // S)
        self.padded_vocab = self.vshard * S
        # Interleaving changes which layers live on which device:
        # device s hosts virtual stages s, S+s, ... — the stacked
        # array (contiguously stage-sharded) is PERMUTED so row
        # s*layers_per_stage + k*layers_per_chunk + l holds global
        # layer (k*S + s)*layers_per_chunk + l. Identity when v == 1.
        perm = np.empty(self.padded_layers, dtype=np.int64)
        pos = 0
        for s in range(S):
            for k in range(virtual_stages):
                vs = k * S + s
                for layer in range(self.layers_per_chunk):
                    perm[pos] = vs * self.layers_per_chunk + layer
                    pos += 1
        self._layer_perm = perm
        self._layer_perm_inv = np.argsort(perm)
        # Compiled explicit-schedule runners, keyed by seq_len.
        self._runner_cache: Dict[int, Callable] = {}

    # -- params -------------------------------------------------------------
    def _pad_vocab(self, rest: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(rest)
        for name, dim in self.family.vocab_dims.items():
            leaf = out[name]
            pad = self.padded_vocab - leaf.shape[dim]
            if pad:
                widths = [(0, 0)] * leaf.ndim
                widths[dim] = (0, pad)
                out[name] = jnp.pad(leaf, widths)
        return out

    def _unpad_vocab(self, rest: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(rest)
        for name, dim in self.family.vocab_dims.items():
            out[name] = jax.lax.slice_in_dim(
                out[name], 0, self.cfg.vocab_size, axis=dim)
        return out

    def split_params(self, params: Dict[str, Any]) -> Tuple[Any, Any]:
        stacked, rest = stack_layer_params(params, self._prefix,
                                           self.cfg.num_layers,
                                           pad_to=self.padded_layers)
        if self.virtual_stages > 1:
            perm = self._layer_perm
            stacked = jax.tree.map(lambda x: x[perm], stacked)
        return stacked, self._pad_vocab(rest)

    def merge_params(self, stacked: Any, rest: Any) -> Dict[str, Any]:
        if self.virtual_stages > 1:
            inv = self._layer_perm_inv
            stacked = jax.tree.map(lambda x: x[inv], stacked)
        return unstack_layer_params(stacked, self._unpad_vocab(rest),
                                    self._prefix, self.cfg.num_layers)

    def _rest_specs(self, rest: Dict[str, Any]) -> Dict[str, Any]:
        """Per-leaf PartitionSpecs for `rest`: vocab-dim leaves shard
        over `stage`; everything else (norm scales, wpe) replicates."""
        def spec_for(path, leaf):
            name = path[0].key if path else None
            if name in self.family.vocab_dims:
                dim = self.family.vocab_dims[name]
                entries = [None] * leaf.ndim
                entries[dim] = 'stage'
                return P(*entries)
            return P()

        return jax.tree_util.tree_map_with_path(spec_for, rest)

    def _block_mesh_specs(self, stacked: Any) -> Any:
        """Mesh-axis specs for stacked block leaves: 'stage' on the
        stack dim + the model's own logical rules (heads/mlp→tensor,
        embed→fsdp, expert→expert) on the inner dims — the
        within-stage sharding GSPMD executes under the auto axes."""
        import flax.linen as nn
        from flax import traverse_util
        from skypilot_tpu.parallel import mesh as mesh_lib
        rules = dict(mesh_lib.DEFAULT_RULES)

        abstract = jax.eval_shape(
            lambda: self.model.init(
                jax.random.PRNGKey(0),
                jnp.ones((1, 8), jnp.int32))['params'])
        logical = nn.get_partition_spec(abstract)
        block0 = traverse_util.flatten_dict(
            logical[f'{self._prefix}0'], sep='/')

        def map_axes(spec):
            entries = []
            for name in (spec or ()):
                ax = rules.get(name)
                axes = ax if isinstance(ax, tuple) else \
                    (ax,) if ax else ()
                axes = tuple(a for a in axes
                             if a in self.mesh.shape and a != 'stage')
                entries.append(axes if len(axes) > 1 else
                               (axes[0] if axes else None))
            return entries

        flat = traverse_util.flatten_dict(stacked, sep='/')
        out = {k: P('stage', *map_axes(block0.get(k)))
               for k in flat}
        return traverse_util.unflatten_dict(out, sep='/')

    def param_shardings(self, stacked: Any, rest: Any):
        """(stacked, rest) NamedShardings: layer dim over `stage` plus
        logical-rule inner-dim axes; rest vocab leaves over `stage`."""
        s_stage = jax.tree.map(
            lambda spec: NamedSharding(self.mesh, spec),
            self._block_mesh_specs(stacked),
            is_leaf=lambda x: isinstance(x, P))
        s_rest = jax.tree.map(
            lambda spec: NamedSharding(self.mesh, spec),
            self._rest_specs(rest),
            is_leaf=lambda x: isinstance(x, P))
        return s_stage, s_rest

    # -- forward ------------------------------------------------------------
    def loss(self, stacked: Any, rest: Any,
             tokens: jax.Array) -> jax.Array:
        """Mean LM loss over the global batch, pipeline-parallel.

        tokens: [global_batch, seq]; global_batch must divide into
        num_microbatches x data-axis size.

        With virtual_stages == 1 this runs the fused scan (schedule-
        independent math, differentiable with jax.grad — the gpipe
        engine and the oracle the explicit runner is tested against).
        Interleaved layouts delegate to the runner and return its
        loss (grads come from loss_and_grad, not jax.grad).
        """
        if self.virtual_stages > 1:
            return self.loss_and_grad(stacked, rest, tokens)[0]
        S = self.num_stages
        M = self.num_microbatches
        d = self.mesh.shape['data']
        B, seq_len = tokens.shape
        if B % (M * d):
            raise ValueError(f'batch {B} must divide into '
                             f'{M} microbatches x data={d}')
        mb = B // (M * d)
        tokens_mb = tokens.reshape(M, d * mb, seq_len)

        cfg = self.cfg
        fam = self.family
        block_apply = fam.block.apply
        lps = self.layers_per_stage
        true_layers = cfg.num_layers
        vshard = self.vshard
        remat_ticks = self.remat_ticks
        aux_scale = (cfg.router_aux_loss_weight /
                     cfg.num_layers) if fam.returns_aux else 0.0

        def pipeline(stacked_local, rest_local, tokens_local):
            # stacked_local: [layers_per_stage, ...] (stage shard);
            # rest_local: vocab leaves are this stage's shard;
            # tokens_local: [M, mb, seq] (data shard).
            stage = jax.lax.axis_index('stage')

            def apply_stage(x):
                aux0 = jnp.zeros((), jnp.float32)
                gidx = stage * lps + jnp.arange(lps)
                if fam.takes_positions:
                    positions = jnp.broadcast_to(
                        jnp.arange(x.shape[1]), x.shape[:2])

                def one_layer(carry, xs):
                    layer_params, li = xs
                    h, aux = carry
                    if fam.takes_positions:
                        out = block_apply({'params': layer_params}, h,
                                          positions)
                    else:
                        out = block_apply({'params': layer_params}, h,
                                          True)
                    if fam.returns_aux:
                        h2, a = out
                    else:
                        h2, a = out, jnp.zeros((), jnp.float32)
                    # Padded slots are identity (their zero params
                    # would not be, e.g. biased blocks) and aux-free.
                    real = li < true_layers
                    h2 = jnp.where(real, h2, h)
                    a = jnp.where(real, a, 0.0)
                    return (h2, aux + a), None

                (x, aux), _ = jax.lax.scan(one_layer, (x, aux0),
                                           (stacked_local, gidx))
                return x, aux

            def tick(carry, t):
                buf = carry
                in_idx = jnp.clip(t, 0, M - 1)
                # Stage-sharded embedding: every stage gathers its
                # vocab shard and a psum assembles the row (exact —
                # one shard owns each id). Only stage 0 consumes it.
                emb = fam.embed_vp(rest_local, tokens_local[in_idx],
                                   cfg, stage, vshard)
                x = jnp.where(stage == 0, emb.astype(buf.dtype), buf)
                y, aux = apply_stage(x)
                # A stage's tick is LIVE when it holds microbatch
                # t - stage in [0, M): bubble ticks process garbage
                # whose aux must not count.
                mb_idx = t - stage
                live = jnp.logical_and(mb_idx >= 0, mb_idx < M)
                aux = jnp.where(live, aux, 0.0)
                out_idx = t - (S - 1)
                live_out = jnp.logical_and(out_idx >= 0, out_idx < M)
                # Stage-sharded head: broadcast the last stage's
                # output (one psum), then every stage computes its
                # [.., vshard] logits slice — the head matmul runs
                # S-way parallel instead of serializing on the last
                # stage. Collectives run every tick (they cannot sit
                # under a per-stage cond); masking is via `where`.
                y_last = _stage_psum(
                    jnp.where(stage == S - 1, y, jnp.zeros_like(y)))
                local_logits = fam.head_local(rest_local, y_last, cfg)
                ce = _vp_next_token_loss(
                    local_logits,
                    tokens_local[jnp.clip(out_idx, 0, M - 1)],
                    stage, vshard, cfg.vocab_size)
                loss_mb = jnp.where(live_out, ce, 0.0)
                nxt = jax.lax.ppermute(
                    y, 'stage', [(i, (i + 1) % S) for i in range(S)])
                return nxt, (loss_mb, aux)

            buf0 = jnp.zeros((tokens_local.shape[1], seq_len,
                              cfg.embed_dim), cfg.dtype)
            body = (jax.checkpoint(tick, prevent_cse=False)
                    if remat_ticks else tick)
            _, (losses, auxes) = jax.lax.scan(body, buf0,
                                              jnp.arange(M + S - 1))
            # The CE terms are already psum-combined (identical on
            # every stage); aux is per-stage and must be summed.
            # Aux scaling matches the sequential model exactly
            # (weight * total_layers_aux / num_layers, averaged over
            # the M microbatches).
            total = jnp.sum(losses)
            total = total + aux_scale * jax.lax.psum(jnp.sum(auxes),
                                                     'stage')
            return jax.lax.pmean(total / M, 'data')

        from skypilot_tpu.utils.jax_compat import shard_map
        fn = shard_map(
            pipeline, mesh=self.mesh,
            in_specs=(jax.tree.map(lambda _: P('stage'), stacked),
                      self._rest_specs(rest),
                      P(None, 'data', None)),
            out_specs=P(),
            axis_names={'stage', 'data'},
            check_vma=False)
        # jit (inlined when already inside a jit): jax.checkpoint in
        # the tick body cannot be evaluated under an EAGER shard_map.
        return jax.jit(fn)(stacked, rest, tokens_mb)

    # -- explicit-schedule engine -------------------------------------------
    def loss_and_grad(self, stacked: Any, rest: Any, tokens: jax.Array,
                      scale: Any = None
                      ) -> Tuple[jax.Array, Tuple[Any, Any]]:
        """Loss AND (g_stacked, g_rest) in ONE pass of the explicit
        schedule: forwards and backwards interleave tick-by-tick per
        pipeline_schedule.make_schedule, so activation residency
        follows the schedule's accounting (1F1B: <= S chunk inputs
        per device) instead of GPipe's full-flush M.

        `scale` (default 1.0) multiplies every cotangent seed and the
        returned loss — the guard's loss_scale path: NaN here poisons
        loss and grads through the same arithmetic the isfinite
        predicate watches.
        """
        M = self.num_microbatches
        d = self.mesh.shape['data']
        B, seq_len = tokens.shape
        if B % (M * d):
            raise ValueError(f'batch {B} must divide into '
                             f'{M} microbatches x data={d}')
        mb = B // (M * d)
        tokens_mb = tokens.reshape(M, d * mb, seq_len)
        if scale is None:
            scale = 1.0
        fn = self._runner(seq_len)
        return fn(stacked, rest, tokens_mb,
                  jnp.asarray(scale, jnp.float32))

    def _runner(self, seq_len: int) -> Callable:
        if seq_len in self._runner_cache:
            return self._runner_cache[seq_len]
        S = self.num_stages
        M = self.num_microbatches
        v = self.virtual_stages
        V = S * v
        sched = self.schedule
        cfg = self.cfg
        fam = self.family
        block_apply = fam.block.apply
        Lc = self.layers_per_chunk
        true_layers = cfg.num_layers
        vshard = self.vshard
        aux_scale = (cfg.router_aux_loss_weight /
                     cfg.num_layers) if fam.returns_aux else 0.0
        T = sched.num_ticks
        tb = {k: jnp.asarray(t) for k, t in sched.tables.items()}
        act_depth = max(sched.live_peak_per_stage)
        FWD = psched.FWD
        stacked_specs, rest_specs = self._stack_rest_specs()
        # Replicated rest leaves (norm scales, wpe) get per-stage
        # local grad contributions that must be psum-combined; vocab-
        # sharded leaves already hold their shard's grad.
        rest_psum = jax.tree.map(
            lambda spec: not any(
                'stage' in (e if isinstance(e, tuple) else (e,))
                for e in spec),
            rest_specs, is_leaf=lambda x: isinstance(x, P))

        def apply_chunk(chunk_params, x, virt):
            """One chunk forward: Lc stacked layers starting at global
            layer virt*Lc; padded slots are masked to identity."""
            aux0 = jnp.zeros((), jnp.float32)
            gidx = virt * Lc + jnp.arange(Lc)
            if fam.takes_positions:
                positions = jnp.broadcast_to(
                    jnp.arange(x.shape[1]), x.shape[:2])

            def one_layer(carry, xs):
                layer_params, li = xs
                h, aux = carry
                if fam.takes_positions:
                    out = block_apply({'params': layer_params}, h,
                                      positions)
                else:
                    out = block_apply({'params': layer_params}, h,
                                      True)
                if fam.returns_aux:
                    h2, a = out
                else:
                    h2, a = out, jnp.zeros((), jnp.float32)
                real = li < true_layers
                h2 = jnp.where(real, h2, h)
                a = jnp.where(real, a, 0.0)
                return (h2, aux + a), None

            (y, aux), _ = jax.lax.scan(one_layer, (x, aux0),
                                       (chunk_params, gidx))
            return y, aux

        def pipeline(stacked_local, rest_local, tokens_local, scale):
            stage = jax.lax.axis_index('stage')
            mbsz = tokens_local.shape[1]
            # On jax 0.4.x shard_map, the transpose of psum is psum:
            # an inner jax.grad through the vocab-parallel loss hands
            # every psum path an S-times-replicated cotangent. The
            # probe measures the factor AT TRACE TIME (S under that
            # rule, 1 if a future jax transposes psum to identity) so
            # the explicit cotangent seeds stay calibrated either way.
            psum_t = jax.grad(
                lambda z: jax.lax.psum(z * z, 'stage') / 2.0)(
                    jnp.float32(1.0))
            chunked = jax.tree.map(
                lambda x: x.reshape(v, Lc, *x.shape[1:]), stacked_local)
            zeros_act = jnp.zeros((mbsz, seq_len, cfg.embed_dim),
                                  cfg.dtype)
            zero_chunk_grads = jax.tree.map(
                lambda x: jnp.zeros(x.shape[1:], jnp.float32), chunked)
            gacc_s0 = jax.tree.map(
                lambda x: jnp.zeros(x.shape, jnp.float32), chunked)
            gacc_r0 = jax.tree.map(
                lambda x: jnp.zeros(x.shape, jnp.float32), rest_local)

            def head_ce(y_last, r, tok):
                return _vp_next_token_loss(
                    fam.head_local(r, y_last, cfg), tok, stage,
                    vshard, cfg.vocab_size)

            def tick(carry, t):
                (act_buf, gy_buf, rxf, rxb, gacc_s, gacc_r, ce_sum,
                 aux_sum) = carry
                kind = tb['op_kind'][t, stage]
                chunk = jnp.clip(tb['op_chunk'][t, stage], 0, v - 1)
                virt = tb['op_virtual'][t, stage]
                aslot = jnp.clip(tb['act_slot'][t, stage], 0,
                                 act_depth - 1)
                # Vocab-parallel embedding for this tick's admission
                # (a collective: every stage gathers its shard and
                # psums; only a virtual-0 forward consumes it).
                emb_m = tb['embed_mb'][t]
                emb = fam.embed_vp(
                    rest_local,
                    tokens_local[jnp.clip(emb_m, 0, M - 1)], cfg,
                    stage, vshard)
                # Chunk inputs/cotangents for this tick's op.
                rxf_r = jnp.clip(tb['rxf_rslot'][t, stage], 0,
                                 sched.rx_fwd_depth - 1)
                rxb_r = jnp.clip(tb['rxb_rslot'][t, stage], 0,
                                 sched.rx_bwd_depth - 1)
                x_fwd = jnp.where(virt == 0, emb.astype(cfg.dtype),
                                  rxf[rxf_r])
                gy_r = jnp.clip(tb['gy_rslot'][t], 0,
                                sched.gy_depth - 1)
                g_in = jnp.where(virt == V - 1, gy_buf[gy_r],
                                 rxb[rxb_r])
                x_saved = act_buf[aslot]
                chunk_params = jax.tree.map(
                    lambda p: jax.lax.dynamic_index_in_dim(
                        p, chunk, 0, keepdims=False), chunked)
                aux_ct = (aux_scale * scale).astype(jnp.float32)

                def idle_fn(ops):
                    del ops
                    return zeros_act, zeros_act, zero_chunk_grads, \
                        jnp.zeros((), jnp.float32)

                def fwd_fn(ops):
                    cp, x_in, _, _ = ops
                    y, aux = apply_chunk(cp, x_in, virt)
                    return y, zeros_act, zero_chunk_grads, aux

                def bwd_fn(ops):
                    cp, _, x_stored, g = ops
                    _, vjp = jax.vjp(
                        lambda p, x: apply_chunk(p, x, virt), cp,
                        x_stored)
                    dp, dx = vjp((g, aux_ct))
                    dp = jax.tree.map(
                        lambda x: x.astype(jnp.float32), dp)
                    return zeros_act, dx.astype(cfg.dtype), dp, \
                        jnp.zeros((), jnp.float32)

                y_out, dx_out, dchunk, aux_term = jax.lax.switch(
                    kind, [idle_fn, fwd_fn, bwd_fn],
                    (chunk_params, x_fwd, x_saved, g_in))
                aux_sum = aux_sum + aux_term
                # Store this forward's chunk input for its backward
                # (bwd/idle rewrite the slot's current value: no-op).
                act_buf = jax.lax.dynamic_update_index_in_dim(
                    act_buf, jnp.where(kind == FWD, x_fwd, x_saved),
                    aslot, 0)
                gacc_s = jax.tree.map(
                    lambda acc, dg: acc.at[chunk].add(dg), gacc_s,
                    dchunk)
                # Vocab-parallel head + loss (collective, every tick):
                # broadcast the last virtual stage's fresh output, every
                # stage computes its logits shard, and the SUM of the
                # per-stage d(ce)/d(y_last) local grads is the true
                # cotangent for the one producer (psum transpose).
                is_last_fwd = jnp.logical_and(kind == FWD,
                                              virt == V - 1)
                y_last = _stage_psum(jnp.where(is_last_fwd, y_out,
                                               jnp.zeros_like(y_out)))
                gm = tb['gy_mb'][t]
                tok_m = tokens_local[jnp.clip(gm, 0, M - 1)]
                ce_m, (gy, d_rest_head) = jax.value_and_grad(
                    head_ce, argnums=(0, 1))(y_last, rest_local,
                                             tok_m)
                live = gm >= 0
                ce_sum = ce_sum + jnp.where(live, ce_m, 0.0)
                # Every head_ce path crosses exactly one psum, so the
                # per-device grads are psum_t-times their true partial
                # contribution; the true producer cotangent is the
                # cross-stage SUM of the partials.
                gy_full = jax.lax.psum(
                    gy.astype(jnp.float32), 'stage') * (scale /
                                                        psum_t)
                gy_w = jnp.clip(tb['gy_wslot'][t], 0,
                                sched.gy_depth - 1)
                gy_buf = jax.lax.dynamic_update_index_in_dim(
                    gy_buf,
                    jnp.where(live, gy_full.astype(cfg.dtype),
                              gy_buf[gy_w]), gy_w, 0)
                gacc_r = jax.tree.map(
                    lambda acc, dg: acc + jnp.where(
                        live,
                        dg.astype(jnp.float32) * (scale / psum_t),
                        0.0),
                    gacc_r, d_rest_head)
                # Embedding backward: a virtual-0 backward's dx is the
                # cotangent of the tick that embedded its microbatch.
                # The psum INSIDE embed_vp transposes to the broadcast,
                # so the unbroadcast per-device candidate is the right
                # seed (replicated leaves like wpe only charge stage 0).
                em = tb['embv_mb'][t]
                is_bwd_v0 = jnp.logical_and(kind == psched.BWD,
                                            virt == 0)
                dx_cand = jnp.where(is_bwd_v0, dx_out,
                                    jnp.zeros_like(dx_out))

                def embed_fn(r):
                    return fam.embed_vp(
                        r, tokens_local[jnp.clip(em, 0, M - 1)], cfg,
                        stage, vshard)

                _, evjp = jax.vjp(embed_fn, rest_local)
                d_rest_emb, = evjp(dx_cand.astype(emb.dtype))
                gacc_r = jax.tree.map(
                    lambda acc, dg: acc + jnp.where(
                        em >= 0, dg.astype(jnp.float32), 0.0),
                    gacc_r, d_rest_emb)
                # Ring hand-offs (every tick; receive-slot tables are
                # indexed by the PRODUCER so the consumer knows where
                # to park the message; -1 = nothing real arrived).
                msg_f = jax.lax.ppermute(
                    y_out, 'stage',
                    [(i, (i + 1) % S) for i in range(S)])
                wsf = tb['rxf_wslot'][t, (stage - 1) % S]
                wsf_c = jnp.clip(wsf, 0, sched.rx_fwd_depth - 1)
                rxf = jax.lax.dynamic_update_index_in_dim(
                    rxf, jnp.where(wsf >= 0, msg_f, rxf[wsf_c]),
                    wsf_c, 0)
                msg_b = jax.lax.ppermute(
                    dx_out, 'stage',
                    [(i, (i - 1) % S) for i in range(S)])
                wsb = tb['rxb_wslot'][t, (stage + 1) % S]
                wsb_c = jnp.clip(wsb, 0, sched.rx_bwd_depth - 1)
                rxb = jax.lax.dynamic_update_index_in_dim(
                    rxb, jnp.where(wsb >= 0, msg_b, rxb[wsb_c]),
                    wsb_c, 0)
                return (act_buf, gy_buf, rxf, rxb, gacc_s, gacc_r,
                        ce_sum, aux_sum), None

            carry0 = (
                jnp.zeros((act_depth, mbsz, seq_len, cfg.embed_dim),
                          cfg.dtype),
                jnp.zeros((sched.gy_depth, mbsz, seq_len,
                           cfg.embed_dim), cfg.dtype),
                jnp.zeros((sched.rx_fwd_depth, mbsz, seq_len,
                           cfg.embed_dim), cfg.dtype),
                jnp.zeros((sched.rx_bwd_depth, mbsz, seq_len,
                           cfg.embed_dim), cfg.dtype),
                gacc_s0, gacc_r0,
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.float32))
            (_, _, _, _, gacc_s, gacc_r, ce_sum, aux_sum), _ = \
                jax.lax.scan(tick, carry0, jnp.arange(T))
            total = ce_sum + aux_scale * jax.lax.psum(aux_sum,
                                                      'stage')
            loss = jax.lax.pmean(total / M, 'data') * scale
            g_stacked = jax.tree.map(
                lambda g, p: (jax.lax.pmean(g, 'data') / M)
                .reshape(p.shape).astype(p.dtype),
                gacc_s, stacked_local)
            g_rest = jax.tree.map(
                lambda g, p, needs: (
                    jax.lax.psum(g, 'stage') if needs else g)
                .astype(p.dtype),
                jax.tree.map(lambda g: jax.lax.pmean(g, 'data') / M,
                             gacc_r),
                rest_local, rest_psum)
            return loss, (g_stacked, g_rest)

        from skypilot_tpu.utils.jax_compat import shard_map
        fn = shard_map(
            pipeline, mesh=self.mesh,
            in_specs=(stacked_specs, rest_specs,
                      P(None, 'data', None), P()),
            out_specs=(P(), (stacked_specs, rest_specs)),
            axis_names={'stage', 'data'},
            check_vma=False)
        jitted = jax.jit(fn)
        self._runner_cache[seq_len] = jitted
        return jitted

    def _abstract_params(self) -> Dict[str, Any]:
        return self.model.init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32)
        )['params']

    def _stack_rest_specs(self) -> Tuple[Any, Any]:
        """(stacked, rest) manual-axis PartitionSpecs for shard_map."""
        import flax.linen as nn
        abstract = jax.eval_shape(
            lambda: self.split_params(
                nn.meta.unbox(self._abstract_params())))
        return (jax.tree.map(lambda _: P('stage'), abstract[0]),
                self._rest_specs(abstract[1]))

    # -- training -----------------------------------------------------------
    def init(self, rng: jax.Array, example: jax.Array,
             tx: optax.GradientTransformation) -> TrainState:
        """TrainState whose params are the (stacked, rest) pair, laid
        out with stage-sharded block leaves (+ logical-rule inner-dim
        shardings) and stage-sharded vocab tables."""
        import flax.linen as nn

        def _init():
            params = nn.meta.unbox(
                self.model.init(rng, example[:1])['params'])
            return self.split_params(params)

        # Born-sharded (the ShardedTrainer pattern): a model big
        # enough to NEED pipeline stages must never materialize whole
        # on one device.
        shapes = jax.eval_shape(_init)
        shardings = self.param_shardings(*shapes)
        stacked, rest = jax.jit(_init, out_shardings=shardings)()
        state = TrainState.create((stacked, rest), tx)
        # The scalar step (and any opt-state scalar, e.g. the schedule
        # count) must be MESH-replicated, not single-device: a
        # checkpoint restore follows this template's shardings, and
        # jit rejects mixed device sets.
        rep = NamedSharding(self.mesh, P())
        return state.replace(
            step=jax.device_put(state.step, rep),
            opt_state=jax.tree.map(
                lambda x: jax.device_put(x, rep)
                if getattr(x, 'ndim', None) == 0 else x,
                state.opt_state))

    def make_train_step(self, tx: optax.GradientTransformation,
                        guard: bool = False,
                        collect_grad_norm: bool = False):
        """The per-step train fn for the configured schedule.

        Unguarded: `(state, tokens) -> (state, loss)` — or
        `(state, (loss, grad_norm))` with `collect_grad_norm` (the
        --metrics-file twin of ShardedTrainer's). With `guard=True`:
        `(state, tokens, max_grad_norm, loss_scale) ->
        (state, (loss, grad_norm, bad))` — the NaN/spike verdict is
        computed on device from the GLOBAL loss and grad norm (GSPMD
        folds the per-stage shard contributions: the psum-of-
        per-stage-flags the schedule refactor exists to enable), and
        a bad step where-selects the old params/opt_state exactly
        like robustness/train_guard.py's sharded-trainer path.
        """
        collect = collect_grad_norm or guard
        use_runner = self.schedule_style != 'gpipe'

        def _loss_and_grads(stacked, rest, tokens, scale):
            if use_runner:
                return self.loss_and_grad(stacked, rest, tokens,
                                          scale=scale)
            return jax.value_and_grad(
                lambda s, r: self.loss(s, r, tokens) * scale,
                argnums=(0, 1))(stacked, rest)

        # Donating the state halves peak HBM (params + Adam moments
        # would otherwise be live twice per step).
        def _body(state: TrainState, tokens: jax.Array,
                  ctl: Optional[jax.Array] = None
                  ) -> Tuple[TrainState, Any]:
            stacked, rest = state.params
            scale = jnp.float32(1.0) if ctl is None else ctl[1]
            loss, grads = _loss_and_grads(stacked, rest, tokens,
                                          scale)
            gnorm = optax.global_norm(grads) if collect else None
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            if ctl is None:
                aux = loss if gnorm is None else (loss, gnorm)
                return state.replace(step=state.step + 1,
                                     params=params,
                                     opt_state=opt_state), aux
            bad = jnp.logical_or(
                jnp.logical_or(~jnp.isfinite(loss),
                               ~jnp.isfinite(gnorm)),
                gnorm > ctl[0])
            params = jax.tree.map(
                lambda new, old: jnp.where(bad, old, new),
                params, state.params)
            opt_state = jax.tree.map(
                lambda new, old: jnp.where(bad, old, new),
                opt_state, state.opt_state)
            return state.replace(step=state.step + 1, params=params,
                                 opt_state=opt_state), (loss, gnorm,
                                                        bad)

        step = jax.jit(_body, donate_argnums=(0,))
        if not guard:
            return step

        def guarded(state, tokens, max_grad_norm=float('inf'),
                    loss_scale=1.0):
            ctl = jnp.asarray([max_grad_norm, loss_scale],
                              dtype=jnp.float32)
            return step(state, tokens, ctl)

        return guarded


# Back-compat alias (the class predates Llama support).
PipelinedGPT = PipelinedLM
