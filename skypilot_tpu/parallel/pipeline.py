"""Pipeline parallelism: GPipe over a `stage` mesh axis via shard_map.

The TPU-native formulation (scaling-book recipe, not a port of the
reference's NCCL send/recv schedules): layer parameters are STACKED
([L, ...] leaves) and sharded over the mesh's `stage` axis, the whole
GPipe schedule — microbatch ingestion, per-stage layer application,
activation hand-off — is ONE `lax.scan` inside ONE `shard_map`, and
stage-to-stage transfer is `lax.ppermute` (XLA collective-permute on
ICI). Backward needs nothing hand-written: `jax.grad` differentiates
through the scan and the ppermutes (a ppermute's transpose is the
reverse ppermute), so the 1F1B-ish backward schedule falls out of AD.

Schedule: M microbatches over S stages take M + S - 1 ticks; each
tick every stage applies its layers to the microbatch it currently
holds (bubble ticks process garbage that is masked out of the loss).
Utilization is M / (M + S - 1) — pick num_microbatches >= 4 * stages.

v1 scope: the GPT, Llama, and Mixtral families (Mixtral's router
aux loss is accumulated across stages with live-tick masking; its
batch-mean products make the faithful reference the mean of
per-microbatch losses), composing with data parallelism (`data`
axis; batch microbatches are sharded over it).
tensor/fsdp compose in principle (they shard WITHIN a stage) but are
not exercised here.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.parallel.train import TrainState, next_token_loss


def stack_layer_params(params: Dict[str, Any], prefix: str,
                       num_layers: int) -> Tuple[Any, Dict[str, Any]]:
    """Split a model's params into (stacked block leaves [L, ...],
    everything else). The stacked tree's structure is ONE block's."""
    layers = [params[f'{prefix}{i}'] for i in range(num_layers)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    rest = {k: v for k, v in params.items()
            if not (k.startswith(prefix) and
                    k[len(prefix):].isdigit())}
    return stacked, rest


def unstack_layer_params(stacked: Any, rest: Dict[str, Any],
                         prefix: str, num_layers: int) -> Dict[str, Any]:
    """Inverse of stack_layer_params (checkpoint interop)."""
    out = dict(rest)
    for i in range(num_layers):
        out[f'{prefix}{i}'] = jax.tree.map(lambda x, i=i: x[i], stacked)
    return out


def _family_of(model):
    """(layer prefix, Block module, embed fn, head-logits fn,
    block-wants-positions, block-returns-aux) for a supported family.

    Mixtral reuses the Llama embed/head helpers (identical param
    names/shapes: tok_embed, final_norm, untied lm_head); its blocks
    additionally return a router aux loss, accumulated across stages
    with live-tick masking and scaled exactly as the sequential model
    does (weight * total / num_layers)."""
    from skypilot_tpu.models import gpt as gpt_lib
    from skypilot_tpu.models import llama as llama_lib
    from skypilot_tpu.models import mixtral as mixtral_lib
    if isinstance(model, gpt_lib.GPT):
        return ('h_', gpt_lib.Block(model.config),
                gpt_lib.embed_tokens, gpt_lib.final_norm_logits,
                False, False)
    if isinstance(model, llama_lib.Llama):
        return ('layer_', llama_lib.Block(model.config),
                llama_lib.embed_tokens, llama_lib.final_norm_logits,
                True, False)
    if isinstance(model, mixtral_lib.Mixtral):
        return ('layer_', mixtral_lib.Block(model.config),
                llama_lib.embed_tokens, llama_lib.final_norm_logits,
                True, True)
    raise ValueError(
        f'Pipeline parallelism supports the GPT, Llama, and Mixtral '
        f'families; got {type(model).__name__}')


class PipelinedLM:
    """GPipe-parallel training step (GPT/Llama/Mixtral).

    Usage:
        pp = PipelinedLM(model, mesh, num_microbatches=8)
        stacked, rest = pp.split_params(params)
        loss = pp.loss(stacked, rest, tokens)          # jittable
        step = pp.make_train_step(tx)                  # optimizer step
    """

    def __init__(self, model, mesh: Mesh,
                 num_microbatches: int = 8,
                 remat_ticks: bool = True) -> None:
        self.model = model
        self.cfg = model.config
        self.mesh = mesh
        self.num_stages = mesh.shape['stage']
        self.num_microbatches = num_microbatches
        # Rematerialize each schedule tick: backward recomputes the
        # tick's layer forwards instead of keeping every tick's
        # intermediate activations live — the memory profile pipeline
        # training needs (activations scale with ticks = M + S - 1
        # otherwise). Equality-tested on, off in test_pipeline.py.
        self.remat_ticks = remat_ticks
        (self._prefix, self._block, self._embed_fn, self._head_fn,
         self._block_takes_positions,
         self._block_returns_aux) = _family_of(model)
        if self.cfg.num_layers % self.num_stages:
            raise ValueError(
                f'num_layers={self.cfg.num_layers} must divide evenly '
                f'into {self.num_stages} pipeline stages')
        if getattr(self.cfg, 'dropout_rate', 0.0):
            raise ValueError(
                'PipelinedLM v1 runs blocks deterministically; '
                'dropout_rate > 0 would be silently ignored — train '
                'without dropout or use ShardedTrainer.')
        if getattr(self.cfg, 'remat', False):
            raise ValueError(
                'PipelinedLM v1 does not rematerialize blocks; set '
                'remat=False (pipeline microbatching already bounds '
                'live activations to one microbatch per stage).')
        self.layers_per_stage = self.cfg.num_layers // self.num_stages

    # -- params -------------------------------------------------------------
    def split_params(self, params: Dict[str, Any]) -> Tuple[Any, Any]:
        return stack_layer_params(params, self._prefix,
                                  self.cfg.num_layers)

    def merge_params(self, stacked: Any, rest: Any) -> Dict[str, Any]:
        return unstack_layer_params(stacked, rest, self._prefix,
                                    self.cfg.num_layers)

    def param_shardings(self, stacked: Any, rest: Any):
        """(stacked, rest) NamedShardings: layer dim over `stage`."""
        s_stage = jax.tree.map(
            lambda x: NamedSharding(self.mesh,
                                    P('stage', *([None] * (x.ndim - 1)))),
            stacked)
        s_rest = jax.tree.map(
            lambda x: NamedSharding(self.mesh, P()), rest)
        return s_stage, s_rest

    # -- forward ------------------------------------------------------------
    def _embed(self, rest: Dict[str, Any], tokens: jax.Array) -> jax.Array:
        return self._embed_fn(rest, tokens, self.cfg)

    def _head_loss(self, rest: Dict[str, Any], x: jax.Array,
                   tokens: jax.Array) -> jax.Array:
        return next_token_loss(self._head_fn(rest, x, self.cfg), tokens)

    def loss(self, stacked: Any, rest: Any,
             tokens: jax.Array) -> jax.Array:
        """Mean LM loss over the global batch, pipeline-parallel.

        tokens: [global_batch, seq]; global_batch must divide into
        num_microbatches x data-axis size.
        """
        S = self.num_stages
        M = self.num_microbatches
        d = self.mesh.shape['data']
        B, seq_len = tokens.shape
        if B % (M * d):
            raise ValueError(f'batch {B} must divide into '
                             f'{M} microbatches x data={d}')
        mb = B // (M * d)
        tokens_mb = tokens.reshape(M, d * mb, seq_len)

        block_apply = self._block.apply
        takes_positions = self._block_takes_positions
        returns_aux = self._block_returns_aux
        embed = self._embed
        head_loss = self._head_loss
        remat_ticks = self.remat_ticks
        aux_scale = (self.cfg.router_aux_loss_weight /
                     self.cfg.num_layers) if returns_aux else 0.0

        def pipeline(stacked_local, rest_rep, tokens_local):
            # stacked_local: [layers_per_stage, ...] (stage shard);
            # tokens_local: [M, mb, seq] (data shard).
            stage = jax.lax.axis_index('stage')

            def apply_stage(x):
                aux0 = jnp.zeros((), jnp.float32)
                if takes_positions:
                    # Llama/Mixtral blocks take (x, positions); the
                    # Mixtral block also returns a router aux term.
                    positions = jnp.broadcast_to(
                        jnp.arange(x.shape[1]), x.shape[:2])

                    def one_layer(carry, layer_params):
                        h, aux = carry
                        out = block_apply({'params': layer_params}, h,
                                          positions)
                        if returns_aux:
                            h, a = out
                            return (h, aux + a), None
                        return (out, aux), None
                else:
                    # GPT-family blocks take (x, deterministic).
                    def one_layer(carry, layer_params):
                        h, aux = carry
                        return (block_apply({'params': layer_params}, h,
                                            True), aux), None
                (x, aux), _ = jax.lax.scan(one_layer, (x, aux0),
                                           stacked_local)
                return x, aux

            def tick(carry, t):
                buf = carry
                in_idx = jnp.clip(t, 0, M - 1)
                # cond, not where: only stage 0 pays for the embedding
                # gather (mirrors the last-stage head cond below).
                x = jax.lax.cond(
                    stage == 0,
                    lambda: embed(rest_rep,
                                  tokens_local[in_idx]).astype(buf.dtype),
                    lambda: buf)
                y, aux = apply_stage(x)
                # A stage's tick is LIVE when it holds microbatch
                # t - stage in [0, M): bubble ticks process garbage
                # whose aux must not count.
                mb_idx = t - stage
                live = jnp.logical_and(mb_idx >= 0, mb_idx < M)
                aux = jnp.where(live, aux, 0.0)
                out_idx = t - (S - 1)
                is_out = jnp.logical_and(stage == S - 1,
                                         jnp.logical_and(out_idx >= 0,
                                                         out_idx < M))
                # Head+loss only on the LAST stage's live ticks (cond
                # skips the vocab matmul on every other stage/tick).
                loss_mb = jax.lax.cond(
                    is_out,
                    lambda: head_loss(
                        rest_rep, y,
                        tokens_local[jnp.clip(out_idx, 0, M - 1)]),
                    lambda: jnp.zeros((), jnp.float32))
                nxt = jax.lax.ppermute(
                    y, 'stage', [(i, (i + 1) % S) for i in range(S)])
                return nxt, (loss_mb, aux)

            buf0 = jnp.zeros((tokens_local.shape[1], seq_len,
                              self.cfg.embed_dim), self.cfg.dtype)
            body = (jax.checkpoint(tick, prevent_cse=False)
                    if remat_ticks else tick)
            _, (losses, auxes) = jax.lax.scan(body, buf0,
                                              jnp.arange(M + S - 1))
            # Only the last stage produced nonzero CE terms; every
            # stage contributed aux for its own layers' live ticks.
            # psum broadcasts the sums, pmean averages data shards.
            # Aux scaling matches the sequential model exactly
            # (weight * total_layers_aux / num_layers, averaged over
            # the M microbatches).
            total = jax.lax.psum(jnp.sum(losses), 'stage')
            total = total + aux_scale * jax.lax.psum(jnp.sum(auxes),
                                                     'stage')
            return jax.lax.pmean(total / M, 'data')

        fn = shard_map(
            pipeline, mesh=self.mesh,
            in_specs=(P('stage'), P(), P(None, 'data', None)),
            out_specs=P(),
            check_rep=False)
        # jit (inlined when already inside a jit): jax.checkpoint in
        # the tick body cannot be evaluated under an EAGER shard_map.
        return jax.jit(fn)(stacked, rest, tokens_mb)

    # -- training -----------------------------------------------------------
    def init(self, rng: jax.Array, example: jax.Array,
             tx: optax.GradientTransformation) -> TrainState:
        """TrainState whose params are the (stacked, rest) pair, laid
        out with stage-sharded block leaves."""
        import flax.linen as nn

        def _init():
            params = nn.meta.unbox(
                self.model.init(rng, example[:1])['params'])
            return self.split_params(params)

        # Born-sharded (the ShardedTrainer pattern): a model big
        # enough to NEED pipeline stages must never materialize whole
        # on one device.
        shapes = jax.eval_shape(_init)
        shardings = self.param_shardings(*shapes)
        stacked, rest = jax.jit(_init, out_shardings=shardings)()
        state = TrainState.create((stacked, rest), tx)
        # The scalar step (and any opt-state scalar, e.g. the schedule
        # count) must be MESH-replicated, not single-device: a
        # checkpoint restore follows this template's shardings, and
        # jit rejects mixed device sets.
        rep = NamedSharding(self.mesh, P())
        return state.replace(
            step=jax.device_put(state.step, rep),
            opt_state=jax.tree.map(
                lambda x: jax.device_put(x, rep)
                if getattr(x, 'ndim', None) == 0 else x,
                state.opt_state))

    def make_train_step(self, tx: optax.GradientTransformation):

        # Donating the state halves peak HBM (params + Adam moments
        # would otherwise be live twice per step).
        @functools.partial(jax.jit, donate_argnums=(0,))
        def train_step(state: TrainState, tokens: jax.Array
                       ) -> Tuple[TrainState, jax.Array]:
            stacked, rest = state.params

            def loss_fn(s, r):
                return self.loss(s, r, tokens)

            loss, grads = jax.value_and_grad(loss_fn,
                                             argnums=(0, 1))(stacked,
                                                             rest)
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            return state.replace(step=state.step + 1, params=params,
                                 opt_state=opt_state), loss

        return train_step


# Back-compat alias (the class predates Llama support).
PipelinedGPT = PipelinedLM
