"""Pipeline parallelism: GPipe over a `stage` mesh axis via shard_map.

The TPU-native formulation (scaling-book recipe, not a port of the
reference's NCCL send/recv schedules): layer parameters are STACKED
([L, ...] leaves) and sharded over the mesh's `stage` axis, the whole
GPipe schedule — microbatch ingestion, per-stage layer application,
activation hand-off — is ONE `lax.scan` inside ONE `shard_map`, and
stage-to-stage transfer is `lax.ppermute` (XLA collective-permute on
ICI). Backward needs nothing hand-written: `jax.grad` differentiates
through the scan and the ppermutes (a ppermute's transpose is the
reverse ppermute), so the 1F1B-ish backward schedule falls out of AD.

Schedule: M microbatches over S stages take M + S - 1 ticks; each
tick every stage applies its layers to the microbatch it currently
holds (bubble ticks process garbage that is masked out of the loss).
Utilization is M / (M + S - 1) — pick num_microbatches >= 4 * stages.

v2 (closes the v1 composition gaps):
  - tensor/fsdp/expert COMPOSE WITHIN STAGES: only `stage` and `data`
    are manual shard_map axes (`axis_names`); the rest stay under
    GSPMD, so stacked block leaves carry their usual logical-rule
    shardings (heads/mlp→tensor, embed→fsdp, expert→expert) on their
    inner dims and XLA inserts the within-stage collectives.
  - the embedding table and LM head are STAGE-SHARDED over the vocab
    dim (no longer replicated on every stage — the HBM that matters
    at 70B scale): embedding is a masked local gather + psum;
    the head is a vocab-parallel matmul with a psum/pmax logsumexp
    cross-entropy, which also spreads the head FLOPs across all
    stages instead of serializing them on the last one.
  - `num_layers % stages != 0` is allowed: the stack is zero-padded
    and padded slots are masked to identity in the per-stage scan.

Families: GPT, Llama, Mixtral (Mixtral's router aux loss is
accumulated across stages with live-tick masking; its batch-mean
products make the faithful reference the mean of per-microbatch
losses). Dropout is rejected (blocks run deterministically).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.parallel.train import TrainState


def stack_layer_params(params: Dict[str, Any], prefix: str,
                       num_layers: int,
                       pad_to: int = 0) -> Tuple[Any, Dict[str, Any]]:
    """Split a model's params into (stacked block leaves [L, ...],
    everything else). The stacked tree's structure is ONE block's.
    `pad_to > num_layers` zero-pads the stack (padded slots are
    masked to identity in the pipeline's per-stage scan)."""
    layers = [params[f'{prefix}{i}'] for i in range(num_layers)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    if pad_to > num_layers:
        pad = pad_to - num_layers
        stacked = jax.tree.map(
            lambda x: jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1)),
            stacked)
    rest = {k: v for k, v in params.items()
            if not (k.startswith(prefix) and
                    k[len(prefix):].isdigit())}
    return stacked, rest


def unstack_layer_params(stacked: Any, rest: Dict[str, Any],
                         prefix: str, num_layers: int) -> Dict[str, Any]:
    """Inverse of stack_layer_params (checkpoint interop); ignores
    padded tail slots."""
    out = dict(rest)
    for i in range(num_layers):
        out[f'{prefix}{i}'] = jax.tree.map(lambda x, i=i: x[i], stacked)
    return out


def _vp_next_token_loss(local_logits: jax.Array, tokens: jax.Array,
                        stage: jax.Array, vshard: int,
                        vocab: int) -> jax.Array:
    """Vocab-parallel causal LM loss over the `stage` axis.

    local_logits: [B, S, vshard] — this stage's vocab shard (global
    column range [stage*vshard, (stage+1)*vshard), columns >= vocab
    are padding). Mirrors train.next_token_loss numerics: f32
    logsumexp with global-max subtraction (pmax), target logit via
    masked local gather + psum."""
    logits = local_logits[:, :-1].astype(jnp.float32)
    targets = tokens[:, 1:]
    off = stage * vshard
    # Padded vocab columns must not contribute mass.
    valid = off + jnp.arange(vshard) < vocab
    logits = jnp.where(valid[None, None, :], logits, -jnp.inf)
    lid = targets - off
    ok = jnp.logical_and(lid >= 0, lid < vshard)
    tl = jnp.take_along_axis(
        logits, jnp.clip(lid, 0, vshard - 1)[..., None], axis=-1)[..., 0]
    target_logit = jax.lax.psum(jnp.where(ok, tl, 0.0), 'stage')
    # Global max: any m makes lse exact; stop_gradient keeps AD on the
    # softmax path (d lse/d logits = softmax regardless of m).
    # all_gather + max, not pmax: pmax has no differentiation rule
    # (even a zero tangent must flow through the primitive).
    m = jax.lax.stop_gradient(jnp.max(
        jax.lax.all_gather(jnp.max(logits, axis=-1), 'stage'), axis=0))
    se = jax.lax.psum(
        jnp.sum(jnp.exp(logits - m[..., None]), axis=-1), 'stage')
    lse = m + jnp.log(se)
    return jnp.mean(lse - target_logit)


class _Family(NamedTuple):
    """Per-model-family pipeline adapter.

    vocab_dims maps rest-leaf name -> the dim carrying the vocab
    (stage-sharded + padded to stages * vshard). embed_vp returns the
    (psum-combined) input embedding from the LOCAL vocab shard;
    head_local returns this stage's [B, S, vshard] logits slice."""
    prefix: str
    block: Any
    takes_positions: bool
    returns_aux: bool
    vocab_dims: Dict[str, int]
    embed_vp: Callable
    head_local: Callable


def _stage_psum(x: jax.Array) -> jax.Array:
    """psum over `stage`, carried in f32. Every caller has exactly ONE
    nonzero contributor (masked gather / masked broadcast), so the
    f32 round-trip is exact for bf16 inputs. Uniform f32 also keeps
    XLA's all-reduce combiner away from mixed bf16/f32 tuple
    all-reduces, whose dtype-rewrite pass crashes on CPU."""
    return jax.lax.psum(x.astype(jnp.float32), 'stage').astype(x.dtype)


def _vp_gather(table: jax.Array, tokens: jax.Array, stage: jax.Array,
               vshard: int) -> jax.Array:
    """Embedding lookup against this stage's vocab shard: gather the
    locally-owned rows (others masked to 0) and psum — exactly one
    stage owns each id, so the sum reassembles the global gather."""
    lid = tokens - stage * vshard
    ok = jnp.logical_and(lid >= 0, lid < vshard)
    x = table[jnp.clip(lid, 0, vshard - 1)]
    return _stage_psum(jnp.where(ok[..., None], x, 0))


def _gpt_embed_vp(rest, tokens, cfg, stage, vshard):
    x = _vp_gather(rest['wte'].astype(cfg.dtype), tokens, stage, vshard)
    return x + rest['wpe'].astype(cfg.dtype)[:tokens.shape[1]]


def _llama_embed_vp(rest, tokens, cfg, stage, vshard):
    return _vp_gather(rest['tok_embed'].astype(cfg.dtype), tokens,
                      stage, vshard)


def _family_of(model) -> _Family:
    # head_local reuses the models' own final_norm_logits helpers
    # unchanged: the vocab dim is only the einsum OUTPUT dim, so they
    # work on a local vocab shard as-is — and head/norm changes in the
    # model files cannot silently diverge from the pipelined path.
    from skypilot_tpu.models import gpt as gpt_lib
    from skypilot_tpu.models import llama as llama_lib
    from skypilot_tpu.models import mixtral as mixtral_lib
    if isinstance(model, gpt_lib.GPT):
        return _Family('h_', gpt_lib.Block(model.config), False, False,
                       {'wte': 0}, _gpt_embed_vp,
                       gpt_lib.final_norm_logits)
    if isinstance(model, llama_lib.Llama):
        return _Family('layer_', llama_lib.Block(model.config), True,
                       False, {'tok_embed': 0, 'lm_head': 1},
                       _llama_embed_vp, llama_lib.final_norm_logits)
    if isinstance(model, mixtral_lib.Mixtral):
        return _Family('layer_', mixtral_lib.Block(model.config), True,
                       True, {'tok_embed': 0, 'lm_head': 1},
                       _llama_embed_vp, llama_lib.final_norm_logits)
    from skypilot_tpu.models import deepseek as deepseek_lib
    if isinstance(model, deepseek_lib.Deepseek):
        # MLA blocks are llama-shaped at the pipeline seam (same
        # (x, positions) signature, same tok_embed/final_norm/lm_head
        # param layout, RMSNorm shared with llama) — the latent-KV
        # machinery is internal to the block.
        return _Family('layer_', deepseek_lib.Block(model.config), True,
                       False, {'tok_embed': 0, 'lm_head': 1},
                       _llama_embed_vp, llama_lib.final_norm_logits)
    raise ValueError(
        f'Pipeline parallelism supports the GPT, Llama, Mixtral, and '
        f'DeepSeek families; got {type(model).__name__}')


class PipelinedLM:
    """GPipe-parallel training step (GPT/Llama/Mixtral).

    Usage:
        pp = PipelinedLM(model, mesh, num_microbatches=8)
        stacked, rest = pp.split_params(params)
        loss = pp.loss(stacked, rest, tokens)          # jittable
        step = pp.make_train_step(tx)                  # optimizer step
    """

    def __init__(self, model, mesh: Mesh,
                 num_microbatches: int = 8,
                 remat_ticks: bool = True) -> None:
        self.model = model
        self.cfg = model.config
        self.mesh = mesh
        self.num_stages = mesh.shape['stage']
        self.num_microbatches = num_microbatches
        # Rematerialize each schedule tick: backward recomputes the
        # tick's layer forwards instead of keeping every tick's
        # intermediate activations live — the memory profile pipeline
        # training needs (activations scale with ticks = M + S - 1
        # otherwise). Equality-tested on, off in test_pipeline.py.
        self.remat_ticks = remat_ticks
        self.family = _family_of(model)
        self._prefix = self.family.prefix
        if getattr(self.cfg, 'dropout_rate', 0.0):
            raise ValueError(
                'PipelinedLM runs blocks deterministically; '
                'dropout_rate > 0 would be silently ignored — train '
                'without dropout or use ShardedTrainer.')
        if getattr(self.cfg, 'remat', False):
            raise ValueError(
                'PipelinedLM does not rematerialize blocks; set '
                'remat=False (per-tick remat already bounds live '
                'activations — see remat_ticks).')
        S = self.num_stages
        # Uneven layer counts pad the stack with masked identity slots
        # (the padded blocks' zero params stay zero: grads are masked,
        # so adamw never moves them).
        self.layers_per_stage = -(-self.cfg.num_layers // S)
        self.padded_layers = self.layers_per_stage * S
        # Vocab is stage-sharded for the embedding/head; pad to S.
        self.vshard = -(-self.cfg.vocab_size // S)
        self.padded_vocab = self.vshard * S

    # -- params -------------------------------------------------------------
    def _pad_vocab(self, rest: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(rest)
        for name, dim in self.family.vocab_dims.items():
            leaf = out[name]
            pad = self.padded_vocab - leaf.shape[dim]
            if pad:
                widths = [(0, 0)] * leaf.ndim
                widths[dim] = (0, pad)
                out[name] = jnp.pad(leaf, widths)
        return out

    def _unpad_vocab(self, rest: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(rest)
        for name, dim in self.family.vocab_dims.items():
            out[name] = jax.lax.slice_in_dim(
                out[name], 0, self.cfg.vocab_size, axis=dim)
        return out

    def split_params(self, params: Dict[str, Any]) -> Tuple[Any, Any]:
        stacked, rest = stack_layer_params(params, self._prefix,
                                           self.cfg.num_layers,
                                           pad_to=self.padded_layers)
        return stacked, self._pad_vocab(rest)

    def merge_params(self, stacked: Any, rest: Any) -> Dict[str, Any]:
        return unstack_layer_params(stacked, self._unpad_vocab(rest),
                                    self._prefix, self.cfg.num_layers)

    def _rest_specs(self, rest: Dict[str, Any]) -> Dict[str, Any]:
        """Per-leaf PartitionSpecs for `rest`: vocab-dim leaves shard
        over `stage`; everything else (norm scales, wpe) replicates."""
        def spec_for(path, leaf):
            name = path[0].key if path else None
            if name in self.family.vocab_dims:
                dim = self.family.vocab_dims[name]
                entries = [None] * leaf.ndim
                entries[dim] = 'stage'
                return P(*entries)
            return P()

        return jax.tree_util.tree_map_with_path(spec_for, rest)

    def _block_mesh_specs(self, stacked: Any) -> Any:
        """Mesh-axis specs for stacked block leaves: 'stage' on the
        stack dim + the model's own logical rules (heads/mlp→tensor,
        embed→fsdp, expert→expert) on the inner dims — the
        within-stage sharding GSPMD executes under the auto axes."""
        import flax.linen as nn
        from flax import traverse_util
        from skypilot_tpu.parallel import mesh as mesh_lib
        rules = dict(mesh_lib.DEFAULT_RULES)

        abstract = jax.eval_shape(
            lambda: self.model.init(
                jax.random.PRNGKey(0),
                jnp.ones((1, 8), jnp.int32))['params'])
        logical = nn.get_partition_spec(abstract)
        block0 = traverse_util.flatten_dict(
            logical[f'{self._prefix}0'], sep='/')

        def map_axes(spec):
            entries = []
            for name in (spec or ()):
                ax = rules.get(name)
                axes = ax if isinstance(ax, tuple) else \
                    (ax,) if ax else ()
                axes = tuple(a for a in axes
                             if a in self.mesh.shape and a != 'stage')
                entries.append(axes if len(axes) > 1 else
                               (axes[0] if axes else None))
            return entries

        flat = traverse_util.flatten_dict(stacked, sep='/')
        out = {k: P('stage', *map_axes(block0.get(k)))
               for k in flat}
        return traverse_util.unflatten_dict(out, sep='/')

    def param_shardings(self, stacked: Any, rest: Any):
        """(stacked, rest) NamedShardings: layer dim over `stage` plus
        logical-rule inner-dim axes; rest vocab leaves over `stage`."""
        s_stage = jax.tree.map(
            lambda spec: NamedSharding(self.mesh, spec),
            self._block_mesh_specs(stacked),
            is_leaf=lambda x: isinstance(x, P))
        s_rest = jax.tree.map(
            lambda spec: NamedSharding(self.mesh, spec),
            self._rest_specs(rest),
            is_leaf=lambda x: isinstance(x, P))
        return s_stage, s_rest

    # -- forward ------------------------------------------------------------
    def loss(self, stacked: Any, rest: Any,
             tokens: jax.Array) -> jax.Array:
        """Mean LM loss over the global batch, pipeline-parallel.

        tokens: [global_batch, seq]; global_batch must divide into
        num_microbatches x data-axis size.
        """
        S = self.num_stages
        M = self.num_microbatches
        d = self.mesh.shape['data']
        B, seq_len = tokens.shape
        if B % (M * d):
            raise ValueError(f'batch {B} must divide into '
                             f'{M} microbatches x data={d}')
        mb = B // (M * d)
        tokens_mb = tokens.reshape(M, d * mb, seq_len)

        cfg = self.cfg
        fam = self.family
        block_apply = fam.block.apply
        lps = self.layers_per_stage
        true_layers = cfg.num_layers
        vshard = self.vshard
        remat_ticks = self.remat_ticks
        aux_scale = (cfg.router_aux_loss_weight /
                     cfg.num_layers) if fam.returns_aux else 0.0

        def pipeline(stacked_local, rest_local, tokens_local):
            # stacked_local: [layers_per_stage, ...] (stage shard);
            # rest_local: vocab leaves are this stage's shard;
            # tokens_local: [M, mb, seq] (data shard).
            stage = jax.lax.axis_index('stage')

            def apply_stage(x):
                aux0 = jnp.zeros((), jnp.float32)
                gidx = stage * lps + jnp.arange(lps)
                if fam.takes_positions:
                    positions = jnp.broadcast_to(
                        jnp.arange(x.shape[1]), x.shape[:2])

                def one_layer(carry, xs):
                    layer_params, li = xs
                    h, aux = carry
                    if fam.takes_positions:
                        out = block_apply({'params': layer_params}, h,
                                          positions)
                    else:
                        out = block_apply({'params': layer_params}, h,
                                          True)
                    if fam.returns_aux:
                        h2, a = out
                    else:
                        h2, a = out, jnp.zeros((), jnp.float32)
                    # Padded slots are identity (their zero params
                    # would not be, e.g. biased blocks) and aux-free.
                    real = li < true_layers
                    h2 = jnp.where(real, h2, h)
                    a = jnp.where(real, a, 0.0)
                    return (h2, aux + a), None

                (x, aux), _ = jax.lax.scan(one_layer, (x, aux0),
                                           (stacked_local, gidx))
                return x, aux

            def tick(carry, t):
                buf = carry
                in_idx = jnp.clip(t, 0, M - 1)
                # Stage-sharded embedding: every stage gathers its
                # vocab shard and a psum assembles the row (exact —
                # one shard owns each id). Only stage 0 consumes it.
                emb = fam.embed_vp(rest_local, tokens_local[in_idx],
                                   cfg, stage, vshard)
                x = jnp.where(stage == 0, emb.astype(buf.dtype), buf)
                y, aux = apply_stage(x)
                # A stage's tick is LIVE when it holds microbatch
                # t - stage in [0, M): bubble ticks process garbage
                # whose aux must not count.
                mb_idx = t - stage
                live = jnp.logical_and(mb_idx >= 0, mb_idx < M)
                aux = jnp.where(live, aux, 0.0)
                out_idx = t - (S - 1)
                live_out = jnp.logical_and(out_idx >= 0, out_idx < M)
                # Stage-sharded head: broadcast the last stage's
                # output (one psum), then every stage computes its
                # [.., vshard] logits slice — the head matmul runs
                # S-way parallel instead of serializing on the last
                # stage. Collectives run every tick (they cannot sit
                # under a per-stage cond); masking is via `where`.
                y_last = _stage_psum(
                    jnp.where(stage == S - 1, y, jnp.zeros_like(y)))
                local_logits = fam.head_local(rest_local, y_last, cfg)
                ce = _vp_next_token_loss(
                    local_logits,
                    tokens_local[jnp.clip(out_idx, 0, M - 1)],
                    stage, vshard, cfg.vocab_size)
                loss_mb = jnp.where(live_out, ce, 0.0)
                nxt = jax.lax.ppermute(
                    y, 'stage', [(i, (i + 1) % S) for i in range(S)])
                return nxt, (loss_mb, aux)

            buf0 = jnp.zeros((tokens_local.shape[1], seq_len,
                              cfg.embed_dim), cfg.dtype)
            body = (jax.checkpoint(tick, prevent_cse=False)
                    if remat_ticks else tick)
            _, (losses, auxes) = jax.lax.scan(body, buf0,
                                              jnp.arange(M + S - 1))
            # The CE terms are already psum-combined (identical on
            # every stage); aux is per-stage and must be summed.
            # Aux scaling matches the sequential model exactly
            # (weight * total_layers_aux / num_layers, averaged over
            # the M microbatches).
            total = jnp.sum(losses)
            total = total + aux_scale * jax.lax.psum(jnp.sum(auxes),
                                                     'stage')
            return jax.lax.pmean(total / M, 'data')

        from skypilot_tpu.utils.jax_compat import shard_map
        fn = shard_map(
            pipeline, mesh=self.mesh,
            in_specs=(jax.tree.map(lambda _: P('stage'), stacked),
                      self._rest_specs(rest),
                      P(None, 'data', None)),
            out_specs=P(),
            axis_names={'stage', 'data'},
            check_vma=False)
        # jit (inlined when already inside a jit): jax.checkpoint in
        # the tick body cannot be evaluated under an EAGER shard_map.
        return jax.jit(fn)(stacked, rest, tokens_mb)

    # -- training -----------------------------------------------------------
    def init(self, rng: jax.Array, example: jax.Array,
             tx: optax.GradientTransformation) -> TrainState:
        """TrainState whose params are the (stacked, rest) pair, laid
        out with stage-sharded block leaves (+ logical-rule inner-dim
        shardings) and stage-sharded vocab tables."""
        import flax.linen as nn

        def _init():
            params = nn.meta.unbox(
                self.model.init(rng, example[:1])['params'])
            return self.split_params(params)

        # Born-sharded (the ShardedTrainer pattern): a model big
        # enough to NEED pipeline stages must never materialize whole
        # on one device.
        shapes = jax.eval_shape(_init)
        shardings = self.param_shardings(*shapes)
        stacked, rest = jax.jit(_init, out_shardings=shardings)()
        state = TrainState.create((stacked, rest), tx)
        # The scalar step (and any opt-state scalar, e.g. the schedule
        # count) must be MESH-replicated, not single-device: a
        # checkpoint restore follows this template's shardings, and
        # jit rejects mixed device sets.
        rep = NamedSharding(self.mesh, P())
        return state.replace(
            step=jax.device_put(state.step, rep),
            opt_state=jax.tree.map(
                lambda x: jax.device_put(x, rep)
                if getattr(x, 'ndim', None) == 0 else x,
                state.opt_state))

    def make_train_step(self, tx: optax.GradientTransformation):

        # Donating the state halves peak HBM (params + Adam moments
        # would otherwise be live twice per step).
        @functools.partial(jax.jit, donate_argnums=(0,))
        def train_step(state: TrainState, tokens: jax.Array
                       ) -> Tuple[TrainState, jax.Array]:
            stacked, rest = state.params

            def loss_fn(s, r):
                return self.loss(s, r, tokens)

            loss, grads = jax.value_and_grad(loss_fn,
                                             argnums=(0, 1))(stacked,
                                                             rest)
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            return state.replace(step=state.step + 1, params=params,
                                 opt_state=opt_state), loss

        return train_step


# Back-compat alias (the class predates Llama support).
PipelinedGPT = PipelinedLM
