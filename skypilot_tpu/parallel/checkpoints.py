"""Checkpoint manager: async orbax save/restore of sharded TrainState.

The training half of the managed-jobs recovery contract (SURVEY §2.6):
the job writes checkpoints to a GCS bucket mounted/addressed at
`ckpt_dir` (orbax/tensorstore writes gs:// URIs directly); after a
preemption the controller re-launches the cluster and the recipe
resumes from `latest_step()`. Async saves overlap the device→storage
copy with the next training steps (HBM is snapshotted synchronously,
upload happens in the background).
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:

    def __init__(self, ckpt_dir: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1) -> None:
        if not ckpt_dir.startswith(('gs://', 's3://')):
            ckpt_dir = os.path.abspath(os.path.expanduser(ckpt_dir))
            os.makedirs(ckpt_dir, exist_ok=True)
        self.ckpt_dir = ckpt_dir
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=True)
        self._manager = ocp.CheckpointManager(ckpt_dir, options=options)

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        """Async save; returns whether a save was started. Saving a step
        that already exists is a no-op (resume-safe)."""
        from skypilot_tpu.robustness import faults
        faults.point('checkpoint.save')  # chaos: lost/failed saves
        try:
            return self._manager.save(
                step, args=ocp.args.StandardSave(state), force=force)
        except ocp.checkpoint_manager.StepAlreadyExistsError:
            return False

    def restore(self, state_template: Any,
                step: Optional[int] = None) -> Any:
        """Restore into the template's shardings (abstract or concrete).

        Sharding-agnostic: orbax reshards on read, so a checkpoint
        written with one optimizer-state layout restores into another
        (e.g. a replicated-moments checkpoint into a ZeRO-1 trainer's
        data-sharded template after flipping `--zero1`, or vice
        versa). If the direct sharded read still fails — layout
        metadata mismatches across orbax versions — fall back to an
        unconstrained read followed by a device_put onto the
        template's shardings.
        """
        if step is None:
            step = self.latest_step()
        assert step is not None, 'no checkpoint to restore'
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(
                x, 'sharding', None)) if hasattr(x, 'shape') else x,
            state_template)
        try:
            return self._manager.restore(
                step, args=ocp.args.StandardRestore(abstract))
        except Exception:  # pylint: disable=broad-except
            plain = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
                if hasattr(x, 'shape') else x,
                state_template)
            restored = self._manager.restore(
                step, args=ocp.args.StandardRestore(plain))
            return jax.tree.map(
                lambda tpl, val: jax.device_put(val, tpl.sharding)
                if getattr(tpl, 'sharding', None) is not None else val,
                state_template, restored)

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def wait_until_finished(self) -> None:
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.close()
