"""Checkpoint manager: async orbax save/restore of sharded TrainState.

The training half of the managed-jobs recovery contract (SURVEY §2.6):
the job writes checkpoints to a GCS bucket mounted/addressed at
`ckpt_dir` (orbax/tensorstore writes gs:// URIs directly); after a
preemption the controller re-launches the cluster and the recipe
resumes from the newest checkpoint. Async saves overlap the
device→storage copy with the next training steps (HBM is snapshotted
synchronously, upload happens in the background).

Integrity: local checkpoint dirs get a sha256 manifest per finalized
step (`parallel/ckpt_integrity.py`, written next to the step dir the
first save/wait after the step finalizes). `restore()` verifies the
candidate step against its manifest and automatically falls back to
the newest step that verifies — a torn or corrupt checkpoint write
costs one checkpoint interval of progress, never the job. Failures
are typed (`CheckpointNotFoundError` / `CheckpointCorruptionError`
from `robustness/errors.py`) and counted
(`skypilot_checkpoint_integrity_failures_total`).
"""
from __future__ import annotations

import os
from typing import Any, List, Optional

import jax
import orbax.checkpoint as ocp

from skypilot_tpu.parallel import ckpt_integrity
from skypilot_tpu.robustness.errors import (CheckpointCorruptionError,
                                            CheckpointNotFoundError)
from skypilot_tpu.utils import ux_utils


class CheckpointManager:

    def __init__(self, ckpt_dir: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1) -> None:
        # Manifests hash local files; remote URIs are left to the
        # object store's own integrity (GCS/S3 checksum uploads).
        self._local = not ckpt_dir.startswith(('gs://', 's3://'))
        if self._local:
            ckpt_dir = os.path.abspath(os.path.expanduser(ckpt_dir))
            os.makedirs(ckpt_dir, exist_ok=True)
        self.ckpt_dir = ckpt_dir
        #: Step the last `restore()` actually read (after any
        #: integrity fallback) — callers report resume progress
        #: from this, not from the step they asked for.
        self.last_restored_step: Optional[int] = None
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=True)
        self._manager = ocp.CheckpointManager(ckpt_dir, options=options)

    # -- integrity manifests ---------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.ckpt_dir, str(step))

    def _finalize_manifests(self) -> None:
        """Write manifests for finalized steps that lack one, and
        prune manifests whose step was GC'd (max_to_keep). Async
        saves finalize in the background; `all_steps()` lists only
        finalized steps, so hashing here never races a write."""
        if not self._local:
            return
        steps = set(self._manager.all_steps())
        for step in sorted(steps):
            step_dir = self._step_dir(step)
            # isdir guard: an unexpected orbax step-dir layout must
            # degrade to "unverified legacy" (no manifest), never to
            # an empty manifest that would verify anything.
            if os.path.isdir(step_dir) and not os.path.exists(
                    ckpt_integrity.manifest_path(self.ckpt_dir, step)):
                ckpt_integrity.write_manifest(
                    self.ckpt_dir, step, step_dir)
        ckpt_integrity.prune_manifests(self.ckpt_dir, steps)

    def verify_step(self, step: int) -> bool:
        """True = manifest verified; False = no manifest (legacy
        checkpoint); raises CheckpointCorruptionError on mismatch."""
        if not self._local:
            return False
        return ckpt_integrity.verify_step(self.ckpt_dir, step,
                                          self._step_dir(step))

    # -- save/restore ----------------------------------------------------
    def save(self, step: int, state: Any, force: bool = False) -> bool:
        """Async save; returns whether a save was started. Saving a step
        that already exists is a no-op (resume-safe)."""
        from skypilot_tpu.robustness import faults
        faults.point('checkpoint.save')  # chaos: lost/failed saves
        # Previous steps have finalized by now (or will by the next
        # call): give them manifests before starting new work.
        self._finalize_manifests()
        try:
            return self._manager.save(
                step, args=ocp.args.StandardSave(state), force=force)
        except ocp.checkpoint_manager.StepAlreadyExistsError:
            return False

    def restore(self, state_template: Any,
                step: Optional[int] = None) -> Any:
        """Restore into the template's shardings, with integrity
        fallback: the requested step (default: newest) is verified
        against its sha256 manifest first; a corrupt step is logged,
        counted, and skipped in favor of the next-newest step that
        verifies. Raises `CheckpointNotFoundError` when there is
        nothing to restore and `CheckpointCorruptionError` when
        every candidate is corrupt."""
        from skypilot_tpu.observability import catalog as obs_catalog
        from skypilot_tpu.robustness import faults
        faults.point('checkpoint.restore')  # chaos: unreadable store
        steps = sorted(self._manager.all_steps(), reverse=True)
        if step is None:
            candidates = steps
        else:
            candidates = [step] + [s for s in steps if s < step]
        if not candidates:
            raise CheckpointNotFoundError(
                f'no checkpoint to restore in {self.ckpt_dir}')
        corrupt: List[int] = []
        for candidate in candidates:
            try:
                verified = self.verify_step(candidate)
            except CheckpointCorruptionError as e:
                obs_catalog.counter(
                    'skypilot_checkpoint_integrity_failures_total'
                ).inc()
                ux_utils.error(
                    f'checkpoint step {candidate} failed integrity '
                    f'verification ({e}); falling back to the '
                    f'previous step.')
                corrupt.append(candidate)
                continue
            if self._local and not verified:
                ux_utils.log(f'checkpoint step {candidate} has no '
                             f'integrity manifest (pre-manifest '
                             f'checkpoint); restoring unverified.')
            if corrupt:
                ux_utils.log(f'checkpoint restore: fell back from '
                             f'corrupt step(s) {corrupt} to step '
                             f'{candidate}.')
            self.last_restored_step = candidate
            return self._restore_step(state_template, candidate)
        raise CheckpointCorruptionError(
            f'every restore candidate failed integrity '
            f'verification (steps {corrupt}) in {self.ckpt_dir} — '
            f'no uncorrupted checkpoint left to fall back to')

    def _restore_step(self, state_template: Any, step: int) -> Any:
        """Sharding-agnostic single-step restore: orbax reshards on
        read, so a checkpoint written with one optimizer-state
        layout restores into another (e.g. a replicated-moments
        checkpoint into a ZeRO-1 trainer's data-sharded template
        after flipping `--zero1`, or vice versa). If the direct
        sharded read still fails — layout metadata mismatches across
        orbax versions — fall back to an unconstrained read followed
        by a device_put onto the template's shardings."""
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(
                x, 'sharding', None)) if hasattr(x, 'shape') else x,
            state_template)
        try:
            return self._manager.restore(
                step, args=ocp.args.StandardRestore(abstract))
        except Exception as e:  # pylint: disable=broad-except
            ux_utils.log(
                f'checkpoint step {step}: direct sharded restore '
                f'failed ({type(e).__name__}: {e}); retrying with '
                f'an unconstrained read + device_put resharding.')
            plain = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
                if hasattr(x, 'shape') else x,
                state_template)
            restored = self._manager.restore(
                step, args=ocp.args.StandardRestore(plain))
            return jax.tree.map(
                lambda tpl, val: jax.device_put(val, tpl.sharding)
                if getattr(tpl, 'sharding', None) is not None else val,
                state_template, restored)

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def all_steps(self) -> List[int]:
        return list(self._manager.all_steps())

    def wait_until_finished(self) -> None:
        self._manager.wait_until_finished()
        self._finalize_manifests()

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._finalize_manifests()
        self._manager.close()
