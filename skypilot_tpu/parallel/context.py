"""Context-parallel (sequence-sharded) execution context.

When the active mesh has a `seq` axis > 1, the attention dispatch
(ops/attention.py) switches to ring attention so k/v never
materialize globally — long-context training where sequence length
scales with the number of devices on the `seq` axis.
"""
from __future__ import annotations

import contextlib
from contextvars import ContextVar
from typing import Iterator, Optional

from jax.sharding import Mesh

_ACTIVE_MESH: ContextVar[Optional[Mesh]] = ContextVar(
    'skypilot_tpu_context_parallel_mesh', default=None)


@contextlib.contextmanager
def context_parallel(mesh: Mesh) -> Iterator[None]:
    token = _ACTIVE_MESH.set(mesh)
    try:
        yield
    finally:
        _ACTIVE_MESH.reset(token)


def active_seq_mesh() -> Optional[Mesh]:
    """The mesh to ring-attend over, if sequence parallelism is on."""
    mesh = _ACTIVE_MESH.get()
    if mesh is None:
        return None
    if 'seq' not in mesh.axis_names:
        return None
    size = mesh.shape['seq']
    return mesh if size > 1 else None
