"""Multi-device serving: tensor-parallel parameter placement.

Serving was single-device (ADVICE r3: an 8B checkpoint needs a
v5p-class chip). This lifts that: place the model's params with the
same logical→mesh rules training uses (wq/wk/wv/mlp sharded over the
`tensor` axis), and XLA GSPMD *propagates* the sharding through every
jitted serving function — prefill, decode, the continuous-batching
engine's fns — inserting the TP collectives (all-reduce after wo /
w_down) automatically. No serving code changes and no thread-local
mesh/rules contexts are needed: propagation from the input params is
sufficient (the models' `with_logical_constraint` hints are no-ops
without an active rules context, which is fine — constraints are
hints, placement comes from the params).

    mesh = make_mesh(MeshConfig(tensor=8))
    params = shard_params_for_serving(model, params, mesh)
    engine = ContinuousBatchingEngine(model, params, ...)

The KV cache is placed EXPLICITLY (PR 15): `serving_cache_shardings`
pins the paged pool's kv-heads axis over `tensor` (GQA remainder
rule: shard only when the head count divides evenly, else replicate)
and the engine declares those shardings on every jitted dispatch's
donated cache output — zero per-step resharding of the pool, which
`pool_collective_lines` lets tests assert from the compiled HLO.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from skypilot_tpu.parallel import mesh as mesh_lib


def serving_param_shardings(model, mesh: Mesh,
                            rules=mesh_lib.DEFAULT_RULES) -> Any:
    """NamedShardings for the model's params from its logical axis
    annotations (the training rules table — TP shards heads/mlp/vocab
    over `tensor`)."""
    import flax.linen as nn
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.ones((1, 8), jnp.int32)))['params']
    specs = nn.get_partition_spec(abstract)
    return nn.logical_to_mesh_sharding(specs, mesh, rules)


def shard_params_for_serving(model, params: Any, mesh: Mesh,
                             rules=mesh_lib.DEFAULT_RULES,
                             dtype=None) -> Any:
    """Place `params` (host numpy or device arrays) onto the mesh with
    the model's logical shardings; returns the sharded tree.

    `device_put` is called on the HOST array directly — with a
    NamedSharding it transfers only each device's shard, never a full
    single-device copy (the whole point for bigger-than-one-chip
    models). `dtype` casts per leaf immediately before placement, so
    the host-side transient is one leaf, not a second full tree."""
    import numpy as np
    shardings = serving_param_shardings(model, mesh, rules)

    def _place(w, s):
        if dtype is not None:
            w = np.asarray(w).astype(dtype)
        return jax.device_put(w, s)

    return jax.tree.map(_place, params, shardings)


# -- KV cache placement (PR 15) ---------------------------------------------
#: Cache-collection leaf names with a kv-heads axis. Paged pool
#: values are [num_kv_heads, total_pages, page_size, head_dim]
#: (ops/paged_attention.py); dense per-slot rows are
#: [slots, max_seq, num_kv_heads, head_dim] (models/llama.py).
_PAGED_VALUE_LEAVES = ('k_pages', 'v_pages')
#: Parallel int8 scale pages [total_pages, page_size]: ONE f32 scale
#: per token slot, shared by every kv head — always replicated (a
#: head-sharded device still needs the whole scale row to
#: quantize/dequantize its own heads).
_PAGED_SCALE_LEAVES = ('k_scales', 'v_scales')
_DENSE_LEAVES = ('cached_key', 'cached_value')


def kv_shard_ways(num_kv_heads: int, tensor_size: int) -> int:
    """How many ways the KV-heads axis shards over a `tensor` axis of
    `tensor_size` devices. The GQA remainder rule: a NamedSharding
    splits an axis all-or-nothing, so the pool shards as far as heads
    allow — `tensor_size` ways when the head count divides evenly,
    else it REPLICATES (e.g. 8 kv heads over tensor=2 shard 2-way;
    2 kv heads over tensor=4 replicate; attention q-heads still shard
    because the weights do — only the KV pool pays the remainder)."""
    if tensor_size > 1 and num_kv_heads > 0 and \
            num_kv_heads % tensor_size == 0:
        return int(tensor_size)
    return 1


def _leaf_name(path) -> str:
    for entry in reversed(path):
        key = getattr(entry, 'key', None)
        if isinstance(key, str):
            return key
    return ''


def serving_cache_shardings(cache: Any, mesh: Mesh) -> Any:
    """NamedShardings for an engine's cache collection: paged pool
    values shard their kv-heads axis (axis 0) over `tensor`, dense
    rows shard theirs (axis 2), scale pages and every other leaf
    (MLA latents, bookkeeping scalars) replicate. The engine pins
    these on the donated cache of every jitted dispatch, so an
    N-chip mesh stores 1/N of each value page per chip and never
    reshards the pool between steps."""
    tensor = int(mesh.shape.get('tensor', 1))
    replicated = NamedSharding(mesh, PartitionSpec())

    def spec_for(path, leaf):
        name = _leaf_name(path)
        if name in _PAGED_VALUE_LEAVES and leaf.ndim == 4 and \
                kv_shard_ways(leaf.shape[0], tensor) > 1:
            return NamedSharding(mesh, PartitionSpec('tensor'))
        if name in _DENSE_LEAVES and leaf.ndim == 4 and \
                kv_shard_ways(leaf.shape[2], tensor) > 1:
            return NamedSharding(mesh,
                                 PartitionSpec(None, None, 'tensor'))
        return replicated

    return jax.tree_util.tree_map_with_path(spec_for, cache)


# -- Pipeline stages (PR 19) ------------------------------------------------
def stage_layer_ranges(num_layers: int,
                       stages: int) -> List[Tuple[int, int]]:
    """Contiguous [lo, hi) layer ranges per stage. Earlier stages take
    the remainder layers (stage 0 also owns the embedding table, but
    the KV pool only materializes transformer layers, so front-loading
    keeps the per-stage POOL split as even as the layer count
    allows)."""
    if stages < 1:
        raise ValueError(f'stages must be >= 1, got {stages}')
    if stages > num_layers:
        raise ValueError(
            f'cannot split {num_layers} layers over {stages} stages '
            f'(at least one layer per stage)')
    base, rem = divmod(num_layers, stages)
    ranges: List[Tuple[int, int]] = []
    lo = 0
    for s in range(stages):
        hi = lo + base + (1 if s < rem else 0)
        ranges.append((lo, hi))
        lo = hi
    return ranges


def stage_submeshes(mesh: Mesh) -> List[Mesh]:
    """One tensor-only `Mesh` per stage row of a `(stage, tensor)`
    mesh. Every existing TP machine — `serving_param_shardings`,
    `serving_cache_shardings`, `kv_shard_ways`,
    `pool_collective_lines` — applies per stage on its submesh
    unchanged: within a stage the layout IS the PR 15 tensor-parallel
    layout, and the only cross-stage traffic is the activation
    handoff between stages (host-driven `device_put`, never a pool
    collective)."""
    stages = int(mesh.shape.get('stage', 1))
    tensor = int(mesh.shape.get('tensor', 1))
    devices = np.asarray(mesh.devices).reshape(stages, tensor)
    # Full six-axis meshes (size-1 everywhere but tensor) so the
    # training rules table resolves every logical axis on a submesh
    # exactly like it does on a plain --tensor mesh.
    return [mesh_lib.make_mesh(mesh_lib.MeshConfig(tensor=tensor),
                               devices=list(devices[s]))
            for s in range(stages)]


def build_staged_serving(model, params: Any, mesh: Mesh,
                         rules=mesh_lib.DEFAULT_RULES,
                         dtype=None) -> Tuple[List[Any], List[Any],
                                              List[Mesh],
                                              List[Tuple[int, int]]]:
    """Split a full Llama param tree into per-stage trees and place
    each on its stage's tensor submesh.

    Stage modules use ABSOLUTE layer names (`models/llama.py
    LlamaStage`), so the split is a top-level dict partition:
    `layer_i` goes to the stage whose [lo, hi) holds i, `tok_embed`
    to stage 0, `final_norm`/`lm_head` to the last stage. Shardings
    come from the FULL model's logical annotations evaluated on each
    submesh — per-stage placement is therefore leaf-for-leaf
    identical to what single-stage TP serving would pin, which is
    what keeps staged outputs bit-identical.

    Returns (stage_models, stage_params, submeshes, layer_ranges).
    """
    from skypilot_tpu.models import llama as llama_lib
    base = getattr(model, 'base_model', model)
    if not isinstance(base, llama_lib.Llama):
        raise ValueError(
            f'staged serving supports the Llama family; '
            f'{type(base).__name__} has no stage split')
    cfg = model.config
    stages = int(mesh.shape.get('stage', 1))
    ranges = stage_layer_ranges(cfg.num_layers, stages)
    submeshes = stage_submeshes(mesh)
    stage_models: List[Any] = []
    stage_params: List[Any] = []
    for s, (lo, hi) in enumerate(ranges):
        first, last = s == 0, s == stages - 1
        stage_model = llama_lib.LlamaStage(
            cfg, lo=lo, hi=hi, first=first, last=last)
        keys = {f'layer_{i}' for i in range(lo, hi)}
        if first:
            keys.add('tok_embed')
        if last:
            keys |= {'final_norm', 'lm_head'}
        missing = keys - set(params)
        if missing:
            raise ValueError(
                f'stage {s} needs params {sorted(missing)} not in '
                f'the provided tree (keys: {sorted(params)[:8]}...)')
        shardings = serving_param_shardings(model, submeshes[s],
                                            rules)
        sub = {k: params[k] for k in keys}
        sub_shardings = {k: shardings[k] for k in keys}

        def _place(w, sh):
            if dtype is not None:
                w = np.asarray(w).astype(dtype)
            return jax.device_put(w, sh)

        stage_models.append(stage_model)
        stage_params.append(jax.tree.map(_place, sub, sub_shardings))
    return stage_models, stage_params, submeshes, ranges


def pool_collective_lines(compiled: Any, cache: Any,
                          mesh: Mesh) -> List[str]:
    """HLO lines of a compiled serving module where a resharding
    collective (all-gather / all-to-all) touches a POOL-SHAPED
    operand — the zero-resharding guard for the sharded KV cache.

    `cache` supplies the KV leaves' global shapes; the match set
    holds their element counts at every way the mesh could split
    them, so both a gather OF a shard and a gather INTO the full
    pool trip it. TP's legitimate collectives (the all-reduce after
    wo/w_down, logit gathers) have activation-sized operands and
    pass. Returns the offending lines (empty = guard green)."""
    # Candidate split factors: 1 (full pool), one mesh axis (a
    # shard — what an all-gather consumes), and products of two
    # (the chunk an all-to-all splits a shard into).
    axes = [int(v) for v in mesh.shape.values()]
    ways = {1}
    for a in axes + axes:
        ways |= {w * a for w in ways}
    kv_names = (_PAGED_VALUE_LEAVES + _PAGED_SCALE_LEAVES +
                _DENSE_LEAVES)
    sizes = set()
    flat, _ = jax.tree_util.tree_flatten_with_path(cache)
    for path, leaf in flat:
        if _leaf_name(path) not in kv_names:
            continue
        size = int(leaf.size)
        for w in ways:
            if size % w == 0:
                sizes.add(size // w)
    sizes.discard(0)
    text = compiled.as_text() if hasattr(compiled, 'as_text') \
        else str(compiled)
    hits = []
    for line in text.splitlines():
        if 'all-gather' not in line and 'all-to-all' not in line:
            continue
        for m in re.finditer(r'\[([0-9,]+)\]', line):
            n = 1
            for d in m.group(1).split(','):
                n *= int(d)
            if n in sizes:
                hits.append(line.strip())
                break
    return hits
