"""Multi-device serving: tensor-parallel parameter placement.

Serving was single-device (ADVICE r3: an 8B checkpoint needs a
v5p-class chip). This lifts that: place the model's params with the
same logical→mesh rules training uses (wq/wk/wv/mlp sharded over the
`tensor` axis), and XLA GSPMD *propagates* the sharding through every
jitted serving function — prefill, decode, the continuous-batching
engine's fns — inserting the TP collectives (all-reduce after wo /
w_down) automatically. No serving code changes and no thread-local
mesh/rules contexts are needed: propagation from the input params is
sufficient (the models' `with_logical_constraint` hints are no-ops
without an active rules context, which is fine — constraints are
hints, placement comes from the params).

    mesh = make_mesh(MeshConfig(tensor=8))
    params = shard_params_for_serving(model, params, mesh)
    engine = ContinuousBatchingEngine(model, params, ...)

The KV cache is placed EXPLICITLY (PR 15): `serving_cache_shardings`
pins the paged pool's kv-heads axis over `tensor` (GQA remainder
rule: shard only when the head count divides evenly, else replicate)
and the engine declares those shardings on every jitted dispatch's
donated cache output — zero per-step resharding of the pool, which
`pool_collective_lines` lets tests assert from the compiled HLO.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from skypilot_tpu.parallel import mesh as mesh_lib


def serving_param_shardings(model, mesh: Mesh,
                            rules=mesh_lib.DEFAULT_RULES) -> Any:
    """NamedShardings for the model's params from its logical axis
    annotations (the training rules table — TP shards heads/mlp/vocab
    over `tensor`)."""
    import flax.linen as nn
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.ones((1, 8), jnp.int32)))['params']
    specs = nn.get_partition_spec(abstract)
    return nn.logical_to_mesh_sharding(specs, mesh, rules)


def shard_params_for_serving(model, params: Any, mesh: Mesh,
                             rules=mesh_lib.DEFAULT_RULES,
                             dtype=None) -> Any:
    """Place `params` (host numpy or device arrays) onto the mesh with
    the model's logical shardings; returns the sharded tree.

    `device_put` is called on the HOST array directly — with a
    NamedSharding it transfers only each device's shard, never a full
    single-device copy (the whole point for bigger-than-one-chip
    models). `dtype` casts per leaf immediately before placement, so
    the host-side transient is one leaf, not a second full tree."""
    import numpy as np
    shardings = serving_param_shardings(model, mesh, rules)

    def _place(w, s):
        if dtype is not None:
            w = np.asarray(w).astype(dtype)
        return jax.device_put(w, s)

    return jax.tree.map(_place, params, shardings)


# -- KV cache placement (PR 15) ---------------------------------------------
#: Cache-collection leaf names with a kv-heads axis. Paged pool
#: values are [num_kv_heads, total_pages, page_size, head_dim]
#: (ops/paged_attention.py); dense per-slot rows are
#: [slots, max_seq, num_kv_heads, head_dim] (models/llama.py).
_PAGED_VALUE_LEAVES = ('k_pages', 'v_pages')
#: Parallel int8 scale pages [total_pages, page_size]: ONE f32 scale
#: per token slot, shared by every kv head — always replicated (a
#: head-sharded device still needs the whole scale row to
#: quantize/dequantize its own heads).
_PAGED_SCALE_LEAVES = ('k_scales', 'v_scales')
_DENSE_LEAVES = ('cached_key', 'cached_value')


def kv_shard_ways(num_kv_heads: int, tensor_size: int) -> int:
    """How many ways the KV-heads axis shards over a `tensor` axis of
    `tensor_size` devices. The GQA remainder rule: a NamedSharding
    splits an axis all-or-nothing, so the pool shards as far as heads
    allow — `tensor_size` ways when the head count divides evenly,
    else it REPLICATES (e.g. 8 kv heads over tensor=2 shard 2-way;
    2 kv heads over tensor=4 replicate; attention q-heads still shard
    because the weights do — only the KV pool pays the remainder)."""
    if tensor_size > 1 and num_kv_heads > 0 and \
            num_kv_heads % tensor_size == 0:
        return int(tensor_size)
    return 1


def _leaf_name(path) -> str:
    for entry in reversed(path):
        key = getattr(entry, 'key', None)
        if isinstance(key, str):
            return key
    return ''


def serving_cache_shardings(cache: Any, mesh: Mesh) -> Any:
    """NamedShardings for an engine's cache collection: paged pool
    values shard their kv-heads axis (axis 0) over `tensor`, dense
    rows shard theirs (axis 2), scale pages and every other leaf
    (MLA latents, bookkeeping scalars) replicate. The engine pins
    these on the donated cache of every jitted dispatch, so an
    N-chip mesh stores 1/N of each value page per chip and never
    reshards the pool between steps."""
    tensor = int(mesh.shape.get('tensor', 1))
    replicated = NamedSharding(mesh, PartitionSpec())

    def spec_for(path, leaf):
        name = _leaf_name(path)
        if name in _PAGED_VALUE_LEAVES and leaf.ndim == 4 and \
                kv_shard_ways(leaf.shape[0], tensor) > 1:
            return NamedSharding(mesh, PartitionSpec('tensor'))
        if name in _DENSE_LEAVES and leaf.ndim == 4 and \
                kv_shard_ways(leaf.shape[2], tensor) > 1:
            return NamedSharding(mesh,
                                 PartitionSpec(None, None, 'tensor'))
        return replicated

    return jax.tree_util.tree_map_with_path(spec_for, cache)


def pool_collective_lines(compiled: Any, cache: Any,
                          mesh: Mesh) -> List[str]:
    """HLO lines of a compiled serving module where a resharding
    collective (all-gather / all-to-all) touches a POOL-SHAPED
    operand — the zero-resharding guard for the sharded KV cache.

    `cache` supplies the KV leaves' global shapes; the match set
    holds their element counts at every way the mesh could split
    them, so both a gather OF a shard and a gather INTO the full
    pool trip it. TP's legitimate collectives (the all-reduce after
    wo/w_down, logit gathers) have activation-sized operands and
    pass. Returns the offending lines (empty = guard green)."""
    # Candidate split factors: 1 (full pool), one mesh axis (a
    # shard — what an all-gather consumes), and products of two
    # (the chunk an all-to-all splits a shard into).
    axes = [int(v) for v in mesh.shape.values()]
    ways = {1}
    for a in axes + axes:
        ways |= {w * a for w in ways}
    kv_names = (_PAGED_VALUE_LEAVES + _PAGED_SCALE_LEAVES +
                _DENSE_LEAVES)
    sizes = set()
    flat, _ = jax.tree_util.tree_flatten_with_path(cache)
    for path, leaf in flat:
        if _leaf_name(path) not in kv_names:
            continue
        size = int(leaf.size)
        for w in ways:
            if size % w == 0:
                sizes.add(size // w)
    sizes.discard(0)
    text = compiled.as_text() if hasattr(compiled, 'as_text') \
        else str(compiled)
    hits = []
    for line in text.splitlines():
        if 'all-gather' not in line and 'all-to-all' not in line:
            continue
        for m in re.finditer(r'\[([0-9,]+)\]', line):
            n = 1
            for d in m.group(1).split(','):
                n *= int(d)
            if n in sizes:
                hits.append(line.strip())
                break
    return hits
