"""Multi-device serving: tensor-parallel parameter placement.

Serving was single-device (ADVICE r3: an 8B checkpoint needs a
v5p-class chip). This lifts that: place the model's params with the
same logical→mesh rules training uses (wq/wk/wv/mlp sharded over the
`tensor` axis), and XLA GSPMD *propagates* the sharding through every
jitted serving function — prefill, decode, the continuous-batching
engine's fns — inserting the TP collectives (all-reduce after wo /
w_down) automatically. No serving code changes and no thread-local
mesh/rules contexts are needed: propagation from the input params is
sufficient (the models' `with_logical_constraint` hints are no-ops
without an active rules context, which is fine — constraints are
hints, placement comes from the params).

    mesh = make_mesh(MeshConfig(tensor=8))
    params = shard_params_for_serving(model, params, mesh)
    engine = ContinuousBatchingEngine(model, params, ...)

The KV cache is created eagerly by the engine (small next to the
params) and adopts a propagated sharding after the first jitted step.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from skypilot_tpu.parallel import mesh as mesh_lib


def serving_param_shardings(model, mesh: Mesh,
                            rules=mesh_lib.DEFAULT_RULES) -> Any:
    """NamedShardings for the model's params from its logical axis
    annotations (the training rules table — TP shards heads/mlp/vocab
    over `tensor`)."""
    import flax.linen as nn
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.ones((1, 8), jnp.int32)))['params']
    specs = nn.get_partition_spec(abstract)
    return nn.logical_to_mesh_sharding(specs, mesh, rules)


def shard_params_for_serving(model, params: Any, mesh: Mesh,
                             rules=mesh_lib.DEFAULT_RULES,
                             dtype=None) -> Any:
    """Place `params` (host numpy or device arrays) onto the mesh with
    the model's logical shardings; returns the sharded tree.

    `device_put` is called on the HOST array directly — with a
    NamedSharding it transfers only each device's shard, never a full
    single-device copy (the whole point for bigger-than-one-chip
    models). `dtype` casts per leaf immediately before placement, so
    the host-side transient is one leaf, not a second full tree."""
    import numpy as np
    shardings = serving_param_shardings(model, mesh, rules)

    def _place(w, s):
        if dtype is not None:
            w = np.asarray(w).astype(dtype)
        return jax.device_put(w, s)

    return jax.tree.map(_place, params, shardings)
