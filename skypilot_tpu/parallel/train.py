"""Sharded training harness: init + train step compiled over a mesh.

The pattern ("How to Scale Your Model" recipe): annotate arrays with
logical axes in the model, map logical→mesh with a rules table, give
jit the in/out shardings, and let XLA GSPMD insert the ICI/DCN
collectives. No hand-written collectives in the train loop.
"""
from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.models import lora as lora_lib
from skypilot_tpu.ops import fused_xent
from skypilot_tpu.parallel import mesh as mesh_lib


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any

    @classmethod
    def create(cls, params: Any, tx: optax.GradientTransformation
               ) -> 'TrainState':
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=tx.init(params))


def next_token_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Causal LM loss: predict tokens[:, 1:] from logits[:, :-1].

    logsumexp form: only the [B,S] target logits and the [B,S]
    normalizer survive — no second [B,S,V] log-prob array in HBM
    (the [B,S,V] logits are already the memory high-water mark).
    """
    # Upcast once: bf16 logits (the memory-lean LM-head option) get an
    # f32 logsumexp; XLA fuses the convert into the reduction, so no
    # f32 [B,S,V] array ever lands in HBM.
    logits = logits[:, :-1].astype(jnp.float32)
    targets = tokens[:, 1:]
    target_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0]
    lse = jax.nn.logsumexp(logits, axis=-1)
    return jnp.mean(lse - target_logit)


def default_optimizer(learning_rate: float = 3e-4,
                      weight_decay: float = 0.1,
                      grad_clip: float = 1.0,
                      warmup_steps: int = 0,
                      total_steps: Optional[int] = None
                      ) -> optax.GradientTransformation:
    if warmup_steps or total_steps:
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, learning_rate, warmup_steps or 1,
            total_steps or (warmup_steps or 1) * 10)
    else:
        schedule = learning_rate
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


# XLA's async-collective / latency-hiding knobs (TPU compiler): with
# these on, the per-leaf grad "buckets" the overlap path emits become
# independently schedulable async reduce-scatters that the latency-
# hiding scheduler hoists into the backward, instead of one fused
# blocking all-reduce after it. They must be in XLA_FLAGS before
# backend init (train_lm --overlap sets them; bench/profile runs show
# the collective gaps closing). Harmless to list; only applied on TPU
# — the CPU build rejects unknown --xla_tpu_* flags.
OVERLAP_XLA_FLAGS: Tuple[str, ...] = (
    '--xla_tpu_enable_async_collective_fusion=true',
    '--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true',
    '--xla_tpu_enable_async_collective_fusion_multiple_steps=true',
    '--xla_tpu_overlap_compute_collective_tc=true',
    '--xla_enable_async_all_gather=true',
    '--xla_enable_async_collective_permute=true',
)


def overlap_xla_flags(platform: Optional[str] = None) -> Tuple[str, ...]:
    """The XLA_FLAGS `--overlap` adds for `platform` ('tpu'/'cpu'/
    None=probe-free default 'tpu'). CPU gets none: the CPU XLA build
    aborts on unknown --xla_tpu_* flags, and its collectives are
    thread-copies with nothing to hide."""
    if platform == 'cpu':
        return ()
    return OVERLAP_XLA_FLAGS


def _supports_fused(model: nn.Module, loss_fn: Callable) -> bool:
    """Can this (model, loss) pair ride the fused blockwise xent path?

    The model must expose `return_hidden` in its apply signature and
    the loss must be the stock next-token CE (or flagged `fused_ok`,
    e.g. mixtral's CE + aux-loss wrapper) — a custom logits-space loss
    needs the logits and stays on the naive path.
    """
    try:
        sig = inspect.signature(type(model).__call__)
    except (TypeError, ValueError):  # builtins / exotic callables
        return False
    if 'return_hidden' not in sig.parameters:
        return False
    return loss_fn is next_token_loss or bool(
        getattr(loss_fn, 'fused_ok', False))


class ShardedTrainer:
    """Builds sharded init/step functions for a flax LM over a mesh.

    `fused_xent` (None = auto) routes the loss through the blockwise
    LM-head cross-entropy (ops/fused_xent.py): the model returns final
    hidden states and the [B, S, V] logits tensor — the training
    memory high-water mark — is never materialized in either pass.
    Auto enables it whenever the model supports `return_hidden` and
    the loss is the stock CE; `False` forces the naive path.

    `zero1` shards the optimizer moments (ZeRO-1, Xu et al.
    arXiv:2004.13336) over the mesh's `data` axis on top of whatever
    fsdp/tensor layout the params already use: each data replica
    keeps 1/data of the Adam m/v state, GSPMD reduce-scatters the
    grads into the shards and all-gathers the updated params — the
    step math (and loss curve) is unchanged.

    `lora` (models/lora.py LoraSpec) turns the run into a LoRA
    finetune: the params pytree becomes `{'base': ..., 'lora': ...}`,
    the base half is frozen (stop_gradient in the loss + a zeroed
    optimizer partition with NO Adam moments allocated for it), and
    only the per-projection A/B factors train. Guard, checkpoint,
    multi-step, and ZeRO-1 paths see an ordinary params pytree and
    work unchanged; `train_lm --lora` saves the trained factors as a
    serving-ready adapter artifact.

    `guard` arms the self-supervising bad-step guard
    (robustness/train_guard.py): the train step takes an extra
    `ctl = [max_grad_norm, loss_scale]` array, flags the step bad ON
    DEVICE when the loss or global grad norm is non-finite or the
    norm exceeds `max_grad_norm`, and SKIPS the update by selecting
    the old params/opt_state — no host round-trip sits between a NaN
    and the optimizer. The step counter still advances (a skipped
    batch is consumed), aux becomes `(loss, grad_norm, bad)`, and
    `loss_scale` exists so a fault plan can poison one step's loss
    with NaN through the real isfinite path. Guarding implies grad-
    norm collection; the norm is computed ONCE and shared by the
    guard predicate and the metrics aux.
    """

    def __init__(self, model: nn.Module, mesh: Mesh,
                 tx: Optional[optax.GradientTransformation] = None,
                 rules=mesh_lib.DEFAULT_RULES,
                 loss_fn: Callable[[jax.Array, jax.Array],
                                   jax.Array] = next_token_loss,
                 fused_xent: Optional[bool] = None,
                 zero1: bool = False,
                 overlap: bool = False,
                 collect_grad_norm: bool = False,
                 guard: bool = False,
                 lora: Optional[lora_lib.LoraSpec] = None) -> None:
        self.model = model
        self.mesh = mesh
        self.tx = tx if tx is not None else default_optimizer()
        self.lora = lora
        if lora is not None:
            if not lora_lib.supports(model):
                raise ValueError(
                    f'{type(model).__name__} has no LoRA forward '
                    f'path; --lora supports the Llama family '
                    f'(models/lora.py)')
            # Freeze the base: its partition of the optimizer emits
            # zero updates and allocates NO moments (optax.masked
            # replaces frozen leaves with MaskedNode at init), so
            # checkpoints and ZeRO-1 sharding cover only what trains.
            base_tx = self.tx

            def _labels(params):
                return {'base': jax.tree.map(lambda _: 'base',
                                             params['base']),
                        'lora': jax.tree.map(lambda _: 'lora',
                                             params['lora'])}

            self.tx = optax.multi_transform(
                {'lora': base_tx, 'base': optax.set_to_zero()},
                _labels)
        self.rules = rules
        self.loss_fn = loss_fn
        self.zero1 = zero1
        if overlap and not zero1:
            raise ValueError(
                'overlap=True buckets the grad reduce-scatter onto '
                'the ZeRO-1 moment layout; it needs zero1=True')
        # Collective/compute overlap (arXiv:2004.13336 §4): pin each
        # grad LEAF to the ZeRO-1 data-sharded layout right where the
        # backward produces it, so XLA emits one independent
        # reduce-scatter per stacked-layer leaf (schedulable into the
        # backward under OVERLAP_XLA_FLAGS) instead of one fused
        # all-reduce after the full backward.
        self.overlap = overlap
        self.guard = guard
        # Step metrics (`train_lm --metrics-file`): the step returns
        # (loss, grad_norm) instead of a bare loss. The norm is
        # computed from grads already in registers — free next to the
        # step itself. The guard needs it unconditionally.
        self.collect_grad_norm = collect_grad_norm or guard
        supported = _supports_fused(model, loss_fn)
        if fused_xent and not supported:
            raise ValueError(
                f'fused_xent=True but {type(model).__name__} has no '
                f'return_hidden apply path or the loss_fn is not '
                f'fused-compatible')
        self.fused_xent = supported if fused_xent is None else bool(
            fused_xent)
        self.batch_sharding = mesh_lib.batch_sharding(mesh)
        self._state_sharding: Optional[Any] = None
        self._grad_sharding: Optional[Any] = None

    def _full_params(self, rng: jax.Array, example_tokens: jax.Array
                     ) -> Any:
        """The trainable params pytree: the model's init, wrapped as
        {'base', 'lora'} when LoRA-finetuning (fresh factors: a ~
        N(0, .02), b = 0, so step 0 is exactly the base model)."""
        params = self.model.init(rng, example_tokens)['params']
        if self.lora is not None:
            params = {
                'base': params,
                'lora': lora_lib.init_lora_params(
                    jax.random.fold_in(rng, 7), self.model.config,
                    self.lora),
            }
        return params

    # -- sharding inference -------------------------------------------------
    def state_sharding(self, example_tokens: jax.Array) -> Any:
        if self._state_sharding is None:
            abstract = jax.eval_shape(
                lambda: TrainState.create(
                    self._full_params(jax.random.PRNGKey(0),
                                      example_tokens),
                    self.tx))
            specs = nn.get_partition_spec(abstract)
            sharding = nn.logical_to_mesh_sharding(
                specs, self.mesh, self.rules)
            if self.zero1:
                shapes = jax.tree.map(
                    lambda x: x.unbox() if isinstance(x, nn.Partitioned)
                    else x,
                    abstract.opt_state,
                    is_leaf=lambda x: isinstance(x, nn.Partitioned))
                sharding = sharding.replace(
                    opt_state=self._zero1_opt_sharding(
                        sharding.opt_state, shapes))
                # The grad "buckets" for collective/compute overlap:
                # the params tree mapped through the same data-axis
                # layering the moments got — each grad leaf lands
                # directly in the layout its moment shard consumes.
                param_shapes = jax.tree.map(
                    lambda x: x.unbox() if isinstance(x, nn.Partitioned)
                    else x,
                    abstract.params,
                    is_leaf=lambda x: isinstance(x, nn.Partitioned))
                self._grad_sharding = self._zero1_opt_sharding(
                    sharding.params, param_shapes)
            self._state_sharding = sharding
        return self._state_sharding

    def _zero1_opt_sharding(self, opt_sharding: Any, opt_shapes: Any
                            ) -> Any:
        """ZeRO-1: layer the `data` mesh axis onto each optimizer-state
        leaf's sharding. Picks the first dim whose size the combined
        (existing axes x data) factor divides; leaves that fit nowhere
        (scalars like Adam's `count`, odd-sized vectors) stay as-is —
        they are noise next to the m/v moments."""
        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        data = sizes.get('data', 1)
        if data <= 1:
            return opt_sharding

        def _axes(entry):
            if entry is None:
                return ()
            return entry if isinstance(entry, tuple) else (entry,)

        def shard_leaf(s, shape_leaf):
            shape = getattr(shape_leaf, 'shape', ())
            if not isinstance(s, NamedSharding) or len(shape) == 0:
                return s
            spec = list(s.spec) + [None] * (len(shape) - len(s.spec))
            if any('data' in _axes(e) for e in spec):
                return s
            for dim, entry in enumerate(spec):
                axes = _axes(entry)
                cur = 1
                for a in axes:
                    cur *= sizes.get(a, 1)
                if shape[dim] % (cur * data) == 0:
                    spec[dim] = (*axes, 'data') if axes else 'data'
                    return NamedSharding(self.mesh, P(*spec))
            return s

        return jax.tree.map(shard_leaf, opt_sharding, opt_shapes)

    # -- init ---------------------------------------------------------------
    def init(self, rng: jax.Array, example_tokens: jax.Array) -> TrainState:
        sharding = self.state_sharding(example_tokens)

        def _init() -> TrainState:
            params = self._full_params(rng, example_tokens)
            params = jax.tree.map(
                lambda x: x.unbox() if isinstance(x, nn.Partitioned) else x,
                params,
                is_leaf=lambda x: isinstance(x, nn.Partitioned))
            return TrainState.create(params, self.tx)

        from skypilot_tpu.parallel import context as cp_context
        with self.mesh, cp_context.context_parallel(self.mesh):
            with nn.logical_axis_rules(self.rules):
                return jax.jit(_init, out_shardings=sharding)()

    # -- step ---------------------------------------------------------------
    def _compute_loss(self, params: Any, tokens: jax.Array) -> jax.Array:
        extra = {}
        model_params = params
        if self.lora is not None:
            # Frozen base: stop_gradient prunes the base backward
            # pass entirely — grads flow only into the A/B factors
            # applied inside the forward (models/lora.py).
            model_params = jax.lax.stop_gradient(params['base'])
            extra = {'lora': lora_lib.as_model_lora(params['lora'],
                                                    self.lora.scale)}
        if self.fused_xent:
            out = self.model.apply({'params': model_params}, tokens,
                                   return_hidden=True, **extra)
            aux = None
            if isinstance(out, (tuple, list)):
                out, aux = out
            head, vocab_in_rows = fused_xent.find_lm_head(model_params)
            loss = fused_xent.fused_next_token_loss(
                out, head, tokens, vocab_in_rows=vocab_in_rows)
            return loss if aux is None else loss + aux
        outputs = self.model.apply({'params': model_params}, tokens,
                                   **extra)
        return self.loss_fn(outputs, tokens)

    def _step_body(self, state: TrainState, tokens: jax.Array,
                   ctl: Optional[jax.Array] = None
                   ) -> Tuple[TrainState, Any]:
        if ctl is None:
            loss, grads = jax.value_and_grad(self._compute_loss)(
                state.params, tokens)
        else:
            # Guarded step: ctl = [max_grad_norm, loss_scale]. The
            # scale rides INSIDE value_and_grad so an injected NaN
            # poisons loss AND grads — exactly the bf16-overflow
            # shape the isfinite predicate exists for.
            loss, grads = jax.value_and_grad(
                lambda p: self._compute_loss(p, tokens) * ctl[1])(
                    state.params)
        if self.overlap and self._grad_sharding is not None:
            # One constraint PER LEAF: each reduce-scatter becomes an
            # independent collective XLA's latency-hiding scheduler
            # can issue as soon as the backward finishes that leaf,
            # instead of one fused tuple-all-reduce at the join.
            grads = jax.tree.map(
                lambda g, s: jax.lax.with_sharding_constraint(g, s)
                if isinstance(s, NamedSharding) else g,
                grads, self._grad_sharding)
        gnorm = (optax.global_norm(grads) if self.collect_grad_norm
                 else None)
        updates, opt_state = self.tx.update(grads, state.opt_state,
                                            state.params)
        if self.zero1 and self._state_sharding is not None:
            # Pin the moment update to the ZeRO-1 layout *inside* the
            # step (the jit out_shardings only constrain the final
            # carry — this keeps every lax.scan iteration of the
            # multi-step path sharded too, so GSPMD reduce-scatters
            # grads into the moment shards instead of materializing
            # replicated Adam state between inner steps).
            opt_state = jax.lax.with_sharding_constraint(
                opt_state, self._state_sharding.opt_state)
        params = optax.apply_updates(state.params, updates)
        if ctl is None:
            aux = loss if gnorm is None else (loss, gnorm)
            return state.replace(step=state.step + 1, params=params,
                                 opt_state=opt_state), aux
        # Bad step — non-finite loss/norm, or a norm spike past the
        # host-supplied ceiling: select the OLD params and opt_state
        # (the update never happens), but still consume the step.
        bad = jnp.logical_or(
            jnp.logical_or(~jnp.isfinite(loss), ~jnp.isfinite(gnorm)),
            gnorm > ctl[0])
        params = jax.tree.map(
            lambda new, old: jnp.where(bad, old, new),
            params, state.params)
        opt_state = jax.tree.map(
            lambda new, old: jnp.where(bad, old, new),
            opt_state, state.opt_state)
        return state.replace(step=state.step + 1, params=params,
                             opt_state=opt_state), (loss, gnorm, bad)

    def _wrap(self, step: Callable) -> Callable:
        def wrapped(state, tokens, *extra):
            from skypilot_tpu.parallel import context as cp_context
            with self.mesh, cp_context.context_parallel(self.mesh):
                with nn.logical_axis_rules(self.rules):
                    return step(state, tokens, *extra)

        wrapped.lower = lambda s, t: step.lower(s, t)  # type: ignore
        return wrapped

    def make_train_step(self, example_tokens: jax.Array,
                        donate: bool = True) -> Callable:
        """The per-step train fn. Unguarded: `(state, tokens) ->
        (state, aux)`. With `guard=True`: `(state, tokens,
        max_grad_norm, loss_scale) -> (state, (loss, gnorm, bad))` —
        the two guard scalars ride one replicated f32 array."""
        sharding = self.state_sharding(example_tokens)
        scalar = NamedSharding(self.mesh, P())
        if not self.guard:
            step = jax.jit(
                self._step_body,
                in_shardings=(sharding, self.batch_sharding),
                out_shardings=(sharding, scalar),
                donate_argnums=(0,) if donate else ())
            return self._wrap(step)
        step = jax.jit(
            self._step_body,
            in_shardings=(sharding, self.batch_sharding, scalar),
            out_shardings=(sharding, scalar),
            donate_argnums=(0,) if donate else ())
        wrapped = self._wrap(step)

        def guarded(state, tokens, max_grad_norm=float('inf'),
                    loss_scale=1.0):
            ctl = jnp.asarray([max_grad_norm, loss_scale],
                              dtype=jnp.float32)
            return wrapped(state, tokens, ctl)

        return guarded

    def make_multi_step(self, example_tokens: jax.Array,
                        inner_steps: int,
                        donate: bool = True) -> Callable:
        """`inner_steps` optimizer steps inside ONE jitted call.

        `lax.scan` keeps the whole inner loop on-device: one dispatch,
        one executable, N steps — amortizing host->device dispatch
        latency (dominant under remote-relay/RPC device access, and a
        free win on directly-attached chips too). Takes tokens stacked
        [inner_steps, B, S]; returns (state, losses[inner_steps]).
        """
        sharding = self.state_sharding(example_tokens)
        stacked = NamedSharding(
            self.mesh, P(None, *self.batch_sharding.spec))

        def _multi(state: TrainState, tokens_stack: jax.Array
                   ) -> Tuple[TrainState, jax.Array]:
            assert tokens_stack.shape[0] == inner_steps, (
                f'tokens stack has {tokens_stack.shape[0]} steps, '
                f'trainer was built for {inner_steps}')
            return jax.lax.scan(self._step_body, state, tokens_stack)

        step = jax.jit(
            _multi,
            in_shardings=(sharding, stacked),
            out_shardings=(sharding, NamedSharding(self.mesh, P())),
            donate_argnums=(0,) if donate else ())
        return self._wrap(step)

    def make_eval_step(self, example_tokens: jax.Array) -> Callable:
        sharding = self.state_sharding(example_tokens)

        def _eval(state: TrainState, tokens: jax.Array) -> jax.Array:
            return self._compute_loss(state.params, tokens)

        step = jax.jit(_eval,
                       in_shardings=(sharding, self.batch_sharding),
                       out_shardings=NamedSharding(self.mesh, P()))

        def wrapped(state, tokens):
            from skypilot_tpu.parallel import context as cp_context
            with self.mesh, cp_context.context_parallel(self.mesh):
                with nn.logical_axis_rules(self.rules):
                    return step(state, tokens)

        return wrapped


def shard_batch(tokens: jax.Array, mesh: Mesh) -> jax.Array:
    return jax.device_put(tokens, mesh_lib.batch_sharding(mesh))


def shard_batch_stack(tokens_stack: jax.Array, mesh: Mesh) -> jax.Array:
    """Places a [inner_steps, B, S] stack for `make_multi_step`: the
    leading scan axis replicated, each [B, S] slice batch-sharded."""
    spec = mesh_lib.batch_sharding(mesh).spec
    return jax.device_put(tokens_stack,
                          NamedSharding(mesh, P(None, *spec)))
