"""Sharded training harness: init + train step compiled over a mesh.

The pattern ("How to Scale Your Model" recipe): annotate arrays with
logical axes in the model, map logical→mesh with a rules table, give
jit the in/out shardings, and let XLA GSPMD insert the ICI/DCN
collectives. No hand-written collectives in the train loop.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from skypilot_tpu.parallel import mesh as mesh_lib


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any

    @classmethod
    def create(cls, params: Any, tx: optax.GradientTransformation
               ) -> 'TrainState':
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=tx.init(params))


def next_token_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Causal LM loss: predict tokens[:, 1:] from logits[:, :-1].

    logsumexp form: only the [B,S] target logits and the [B,S]
    normalizer survive — no second [B,S,V] log-prob array in HBM
    (the [B,S,V] logits are already the memory high-water mark).
    """
    # Upcast once: bf16 logits (the memory-lean LM-head option) get an
    # f32 logsumexp; XLA fuses the convert into the reduction, so no
    # f32 [B,S,V] array ever lands in HBM.
    logits = logits[:, :-1].astype(jnp.float32)
    targets = tokens[:, 1:]
    target_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0]
    lse = jax.nn.logsumexp(logits, axis=-1)
    return jnp.mean(lse - target_logit)


def default_optimizer(learning_rate: float = 3e-4,
                      weight_decay: float = 0.1,
                      grad_clip: float = 1.0,
                      warmup_steps: int = 0,
                      total_steps: Optional[int] = None
                      ) -> optax.GradientTransformation:
    if warmup_steps or total_steps:
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, learning_rate, warmup_steps or 1,
            total_steps or (warmup_steps or 1) * 10)
    else:
        schedule = learning_rate
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


class ShardedTrainer:
    """Builds sharded init/step functions for a flax LM over a mesh."""

    def __init__(self, model: nn.Module, mesh: Mesh,
                 tx: Optional[optax.GradientTransformation] = None,
                 rules=mesh_lib.DEFAULT_RULES,
                 loss_fn: Callable[[jax.Array, jax.Array],
                                   jax.Array] = next_token_loss) -> None:
        self.model = model
        self.mesh = mesh
        self.tx = tx if tx is not None else default_optimizer()
        self.rules = rules
        self.loss_fn = loss_fn
        self.batch_sharding = mesh_lib.batch_sharding(mesh)
        self._state_sharding: Optional[Any] = None

    # -- sharding inference -------------------------------------------------
    def state_sharding(self, example_tokens: jax.Array) -> Any:
        if self._state_sharding is None:
            abstract = jax.eval_shape(
                lambda: TrainState.create(
                    self.model.init(jax.random.PRNGKey(0), example_tokens)
                    ['params'],
                    self.tx))
            specs = nn.get_partition_spec(abstract)
            self._state_sharding = nn.logical_to_mesh_sharding(
                specs, self.mesh, self.rules)
        return self._state_sharding

    # -- init ---------------------------------------------------------------
    def init(self, rng: jax.Array, example_tokens: jax.Array) -> TrainState:
        sharding = self.state_sharding(example_tokens)

        def _init() -> TrainState:
            params = self.model.init(rng, example_tokens)['params']
            params = jax.tree.map(
                lambda x: x.unbox() if isinstance(x, nn.Partitioned) else x,
                params,
                is_leaf=lambda x: isinstance(x, nn.Partitioned))
            return TrainState.create(params, self.tx)

        from skypilot_tpu.parallel import context as cp_context
        with self.mesh, cp_context.context_parallel(self.mesh):
            with nn.logical_axis_rules(self.rules):
                return jax.jit(_init, out_shardings=sharding)()

    # -- step ---------------------------------------------------------------
    def _step_body(self, state: TrainState, tokens: jax.Array
                   ) -> Tuple[TrainState, jax.Array]:
        def compute_loss(params):
            logits = self.model.apply({'params': params}, tokens)
            return self.loss_fn(logits, tokens)

        loss, grads = jax.value_and_grad(compute_loss)(state.params)
        updates, opt_state = self.tx.update(grads, state.opt_state,
                                            state.params)
        params = optax.apply_updates(state.params, updates)
        return state.replace(step=state.step + 1, params=params,
                             opt_state=opt_state), loss

    def _wrap(self, step: Callable) -> Callable:
        def wrapped(state, tokens):
            from skypilot_tpu.parallel import context as cp_context
            with self.mesh, cp_context.context_parallel(self.mesh):
                with nn.logical_axis_rules(self.rules):
                    return step(state, tokens)

        wrapped.lower = lambda s, t: step.lower(s, t)  # type: ignore
        return wrapped

    def make_train_step(self, example_tokens: jax.Array,
                        donate: bool = True) -> Callable:
        sharding = self.state_sharding(example_tokens)
        step = jax.jit(
            self._step_body,
            in_shardings=(sharding, self.batch_sharding),
            out_shardings=(sharding, NamedSharding(self.mesh, P())),
            donate_argnums=(0,) if donate else ())
        return self._wrap(step)

    def make_multi_step(self, example_tokens: jax.Array,
                        inner_steps: int,
                        donate: bool = True) -> Callable:
        """`inner_steps` optimizer steps inside ONE jitted call.

        `lax.scan` keeps the whole inner loop on-device: one dispatch,
        one executable, N steps — amortizing host->device dispatch
        latency (dominant under remote-relay/RPC device access, and a
        free win on directly-attached chips too). Takes tokens stacked
        [inner_steps, B, S]; returns (state, losses[inner_steps]).
        """
        sharding = self.state_sharding(example_tokens)
        stacked = NamedSharding(
            self.mesh, P(None, *self.batch_sharding.spec))

        def _multi(state: TrainState, tokens_stack: jax.Array
                   ) -> Tuple[TrainState, jax.Array]:
            assert tokens_stack.shape[0] == inner_steps, (
                f'tokens stack has {tokens_stack.shape[0]} steps, '
                f'trainer was built for {inner_steps}')
            return jax.lax.scan(self._step_body, state, tokens_stack)

        step = jax.jit(
            _multi,
            in_shardings=(sharding, stacked),
            out_shardings=(sharding, NamedSharding(self.mesh, P())),
            donate_argnums=(0,) if donate else ())
        return self._wrap(step)

    def make_eval_step(self, example_tokens: jax.Array) -> Callable:
        sharding = self.state_sharding(example_tokens)

        def _eval(state: TrainState, tokens: jax.Array) -> jax.Array:
            logits = self.model.apply({'params': state.params}, tokens)
            return self.loss_fn(logits, tokens)

        step = jax.jit(_eval,
                       in_shardings=(sharding, self.batch_sharding),
                       out_shardings=NamedSharding(self.mesh, P()))

        def wrapped(state, tokens):
            from skypilot_tpu.parallel import context as cp_context
            with self.mesh, cp_context.context_parallel(self.mesh):
                with nn.logical_axis_rules(self.rules):
                    return step(state, tokens)

        return wrapped


def shard_batch(tokens: jax.Array, mesh: Mesh) -> jax.Array:
    return jax.device_put(tokens, mesh_lib.batch_sharding(mesh))


def shard_batch_stack(tokens_stack: jax.Array, mesh: Mesh) -> jax.Array:
    """Places a [inner_steps, B, S] stack for `make_multi_step`: the
    leading scan axis replicated, each [B, S] slice batch-sharded."""
    spec = mesh_lib.batch_sharding(mesh).spec
    return jax.device_put(tokens_stack,
                          NamedSharding(mesh, P(None, *spec)))
