"""Checkpoint integrity manifests: sha256 per file, stdlib only.

One manifest JSON per finalized checkpoint step, written NEXT TO the
step directory (never inside it — orbax owns the step dir layout):

    <ckpt_dir>/manifest-<step>.json
    {"step": N, "files": {"<relpath>": "<sha256>", ...},
     "total_bytes": B}

`parallel/checkpoints.py` writes one after each step finalizes and
verifies it before restoring; a mismatch (torn write, truncated
upload, bit rot) raises `CheckpointCorruptionError` and the manager
falls back to the newest step that verifies. This module is
deliberately dependency-free (os/json/hashlib) so the managed-jobs
controller can preflight a checkpoint directory before relaunching a
job WITHOUT importing jax/orbax into the control plane.

Manifests are themselves written atomically (temp file + fsync +
rename): a crash mid-manifest-write leaves the step unverified
(legacy semantics, restore logs and accepts) rather than falsely
corrupt.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Any, Dict, List, Optional

from skypilot_tpu.robustness.errors import CheckpointCorruptionError

_MANIFEST_RE = re.compile(r'^manifest-(\d+)\.json$')


def manifest_path(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f'manifest-{step}.json')


def _sha256_file(path: str) -> str:
    digest = hashlib.sha256()
    with open(path, 'rb') as f:
        for chunk in iter(lambda: f.read(1 << 20), b''):
            digest.update(chunk)
    return digest.hexdigest()


def compute_manifest(step_dir: str, step: int) -> Dict[str, Any]:
    """Hash every file under the (finalized) step directory."""
    files: Dict[str, str] = {}
    total = 0
    for root, _dirs, names in os.walk(step_dir):
        for name in sorted(names):
            path = os.path.join(root, name)
            rel = os.path.relpath(path, step_dir)
            files[rel] = _sha256_file(path)
            total += os.path.getsize(path)
    return {'step': step, 'files': files, 'total_bytes': total}


def write_manifest(ckpt_dir: str, step: int,
                   step_dir: Optional[str] = None) -> str:
    """Atomically write the manifest for one finalized step; returns
    its path."""
    step_dir = step_dir or os.path.join(ckpt_dir, str(step))
    manifest = compute_manifest(step_dir, step)
    path = manifest_path(ckpt_dir, step)
    tmp = f'{path}.tmp'
    with open(tmp, 'w', encoding='utf-8') as f:
        json.dump(manifest, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def manifest_steps(ckpt_dir: str) -> List[int]:
    """Steps that have a manifest on disk, ascending."""
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return []
    steps = []
    for name in names:
        match = _MANIFEST_RE.match(name)
        if match:
            steps.append(int(match.group(1)))
    return sorted(steps)


def prune_manifests(ckpt_dir: str, keep_steps) -> None:
    """Drop manifests whose step directory is gone (orbax
    max_to_keep GC removed it)."""
    keep = set(int(s) for s in keep_steps)
    for step in manifest_steps(ckpt_dir):
        if step not in keep:
            try:
                os.remove(manifest_path(ckpt_dir, step))
            except OSError:
                pass  # already gone; nothing to prune


def verify_step(ckpt_dir: str, step: int,
                step_dir: Optional[str] = None) -> bool:
    """Verify one step against its manifest. Returns True when
    verified, False when no manifest exists (a pre-integrity-era
    checkpoint: callers log and accept). Raises
    `CheckpointCorruptionError` on any mismatch: a missing file, a
    hash mismatch, or an unreadable manifest."""
    step_dir = step_dir or os.path.join(ckpt_dir, str(step))
    path = manifest_path(ckpt_dir, step)
    if not os.path.exists(path):
        return False
    try:
        with open(path, 'r', encoding='utf-8') as f:
            manifest = json.load(f)
        files = dict(manifest['files'])
    except (OSError, ValueError, KeyError, TypeError) as e:
        raise CheckpointCorruptionError(
            f'checkpoint step {step}: unreadable manifest {path} '
            f'({e})') from e
    for rel, expected in files.items():
        file_path = os.path.join(step_dir, rel)
        if not os.path.exists(file_path):
            raise CheckpointCorruptionError(
                f'checkpoint step {step}: manifest lists {rel} but '
                f'it is missing from {step_dir}')
        actual = _sha256_file(file_path)
        if actual != expected:
            raise CheckpointCorruptionError(
                f'checkpoint step {step}: {rel} sha256 mismatch '
                f'(manifest {expected[:12]}…, on disk '
                f'{actual[:12]}…) — torn or corrupt write')
    return True


def preflight(ckpt_dir: str,
              steps: Optional[List[int]] = None) -> Dict[str, Any]:
    """Controller-side dry run of the restore fallback: which steps
    exist, which verify, and which step a relaunched job will
    actually resume from. Never raises — this is an early-warning
    surface for the jobs recovery path, not a gate."""
    if steps is None:
        steps = []
        try:
            for name in os.listdir(ckpt_dir):
                if name.isdigit() and os.path.isdir(
                        os.path.join(ckpt_dir, name)):
                    steps.append(int(name))
        except OSError:
            pass
        steps = sorted(steps)
    corrupt: List[int] = []
    unverified: List[int] = []
    newest_verifying: Optional[int] = None
    for step in sorted(steps, reverse=True):
        try:
            verified = verify_step(ckpt_dir, step)
        except CheckpointCorruptionError:
            corrupt.append(step)
            continue
        if not verified:
            unverified.append(step)
        if newest_verifying is None:
            newest_verifying = step
    return {'steps': sorted(steps), 'corrupt_steps': sorted(corrupt),
            'unverified_steps': sorted(unverified),
            'newest_verifying': newest_verifying}
