"""Device mesh construction and logical sharding rules.

The TPU-native parallelism model: pick a `jax.sharding.Mesh` whose
axes are the parallelism dimensions (data / fsdp / tensor / expert /
seq), annotate model arrays with *logical* axis names, and map logical
→ mesh axes with a rules table. XLA GSPMD then inserts the ICI/DCN
collectives. (The reference orchestrator has no parallelism layer —
SURVEY.md §2.4 — it launches user torchrun code; here the framework
ships the recipe layer itself, jax-first.)

Multislice: `make_mesh` uses a hybrid mesh when
`jax.devices()` spans slices, putting DCN-parallel axes (data) on the
outer (slice) dimension and ICI axes (fsdp/tensor) inside a slice.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# Logical axis name -> mesh axis (or tuple of mesh axes) mapping.
# Flax linen spmd consumes these as `rules`.
DEFAULT_RULES: Tuple[Tuple[str, Optional[object]], ...] = (
    ('batch', ('data', 'fsdp')),   # batch sharded over data- and fsdp-axes
    ('seq', 'seq'),                # sequence (context) parallelism axis
    ('act_embed', None),           # activations' embed dim stays unsharded
    ('embed', 'fsdp'),             # FSDP: shard params' embed dim
    # Embedding-*table* embed dim stays unsharded: the scatter-add grad of
    # a gather forces GSPMD to reshard the residual-stream cotangent from
    # batch-sharded to embed-over-fsdp with batch replicated — an
    # "involuntary full rematerialization" (replicate-then-repartition).
    # Tables shard over vocab->tensor instead; dense kernels keep
    # embed->fsdp where the backward is a matmul (reduce-scatter-able).
    ('table_embed', None),
    ('heads', 'tensor'),           # TP: attention heads
    ('kv', None),
    ('mlp', 'tensor'),             # TP: MLP hidden
    ('vocab', 'tensor'),           # TP: embedding/vocab
    ('expert', 'expert'),          # MoE expert parallelism
    ('norm', None),
)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Named mesh axis sizes. Size 1 axes are kept (harmless to XLA).

    `stage` is the pipeline-parallel axis (parallel/pipeline.py):
    placed OUTERMOST after data so stage boundaries ride long ICI
    paths (activations cross a stage boundary once per microbatch
    tick, far less often than fsdp/tensor collectives fire)."""
    data: int = 1
    stage: int = 1
    fsdp: int = 1
    tensor: int = 1
    expert: int = 1
    seq: int = 1

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return ('data', 'stage', 'fsdp', 'tensor', 'expert', 'seq')

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.data, self.stage, self.fsdp, self.tensor,
                self.expert, self.seq)

    @property
    def num_devices(self) -> int:
        return math.prod(self.shape)

    @classmethod
    def auto(cls, num_devices: Optional[int] = None,
             tensor: int = 1, expert: int = 1, seq: int = 1,
             num_slices: int = 0) -> 'MeshConfig':
        """FSDP-first auto config: all remaining devices on the fsdp
        axis — except on multislice, where the data axis takes one
        dimension per slice (dp is the DCN-tolerant axis; make_mesh
        lays data rows onto slices). Slice count is detected from the
        devices' slice_index when the full device set is used;
        `num_slices` overrides."""
        devices = jax.devices()
        if num_devices is None:
            num_devices = len(devices)
        if not num_slices:
            if num_devices == len(devices):
                num_slices = len(
                    {getattr(d, 'slice_index', 0) or 0 for d in devices})
            else:
                num_slices = 1
        inner = tensor * expert * seq * num_slices
        if num_devices % inner != 0:
            raise ValueError(
                f'{num_devices} devices not divisible by '
                f'slices*tensor*expert*seq={inner}')
        return cls(data=num_slices, fsdp=num_devices // inner,
                   tensor=tensor, expert=expert, seq=seq)


def make_mesh(config: MeshConfig,
              devices: Optional[Sequence[jax.Device]] = None,
              slice_ids: Optional[Sequence[int]] = None) -> Mesh:
    """Build a Mesh, ICI-topology-aware within a slice, DCN-aware across.

    Within one TPU slice, `mesh_utils.create_device_mesh` lays the mesh
    onto the physical torus so that the innermost axes (tensor) ride
    the shortest ICI paths. Across slices (or hosts without ICI), the
    `data` axis is placed on DCN: the first data-axis dimension
    enumerates slices, so data-parallel gradient psums are the ONLY
    collectives crossing DCN — fsdp/tensor/expert/seq all stay inside
    a slice on ICI.

    `slice_ids` (parallel to `devices`) overrides slice membership —
    the multislice-without-multislice-hardware test path (the driver's
    dryrun fakes two slices over CPU devices); on real TPU the
    devices' own `slice_index` attribute is used.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if config.num_devices != len(devices):
        raise ValueError(
            f'Mesh needs {config.num_devices} devices, got {len(devices)}.')

    if slice_ids is not None:
        if len(slice_ids) != len(devices):
            raise ValueError(
                f'slice_ids ({len(slice_ids)}) must parallel devices '
                f'({len(devices)}).')
    else:
        slice_ids = [getattr(d, 'slice_index', 0) or 0 for d in devices]
    num_slices = len(set(slice_ids))
    if num_slices > 1:
        # Put data-parallel (the DCN-tolerant axis) across slices.
        if config.data % num_slices != 0:
            raise ValueError(
                f'data axis ({config.data}) must be divisible by the '
                f'number of slices ({num_slices}) for multislice meshes.')
        per_slice = len(devices) // num_slices
        ici_shape = [config.data // num_slices, *config.shape[1:]]
        groups: Dict[int, List[jax.Device]] = {}
        for d, sid in zip(devices, slice_ids):
            groups.setdefault(sid, []).append(d)
        if any(len(g) != per_slice for g in groups.values()):
            raise ValueError(
                f'uneven slices: {[len(g) for g in groups.values()]} '
                f'devices per slice (need {per_slice} each).')
        # Hybrid layout by hand (create_hybrid_device_mesh requires the
        # real slice_index attribute, which faked slices lack): each
        # slice gets its own ICI-aware sub-mesh, then slices stack
        # along the leading data axis (= DCN).
        sub_arrays = []
        for sid in sorted(groups):
            try:
                sub = mesh_utils.create_device_mesh(
                    ici_shape, devices=groups[sid])
            except (ValueError, AssertionError):
                sub = np.asarray(groups[sid],
                                 dtype=object).reshape(ici_shape)
            sub_arrays.append(sub)
        device_array = np.concatenate(sub_arrays, axis=0)
    else:
        try:
            device_array = mesh_utils.create_device_mesh(
                config.shape, devices=devices)
        except (ValueError, AssertionError):
            # Fallback (e.g. CPU device counts with no physical topology).
            device_array = np.asarray(devices).reshape(config.shape)
    return Mesh(device_array, config.axis_names)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for (batch, seq, ...) input arrays."""
    return NamedSharding(mesh, P(('data', 'fsdp'), None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def rules_with_overrides(
        overrides: Optional[Dict[str, Optional[object]]] = None
) -> Tuple[Tuple[str, Optional[object]], ...]:
    rules = dict(DEFAULT_RULES)
    if overrides:
        rules.update(overrides)
    return tuple(rules.items())


def mesh_summary(mesh: Mesh) -> str:
    parts = [f'{name}={size}' for name, size in
             zip(mesh.axis_names, mesh.devices.shape) if size > 1]
    return f'Mesh({", ".join(parts) or "single-device"})'
