"""Device mesh construction and logical sharding rules.

The TPU-native parallelism model: pick a `jax.sharding.Mesh` whose
axes are the parallelism dimensions (data / fsdp / tensor / expert /
seq), annotate model arrays with *logical* axis names, and map logical
→ mesh axes with a rules table. XLA GSPMD then inserts the ICI/DCN
collectives. (The reference orchestrator has no parallelism layer —
SURVEY.md §2.4 — it launches user torchrun code; here the framework
ships the recipe layer itself, jax-first.)

Multislice: `make_mesh` uses a hybrid mesh when
`jax.devices()` spans slices, putting DCN-parallel axes (data) on the
outer (slice) dimension and ICI axes (fsdp/tensor) inside a slice.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# Logical axis name -> mesh axis (or tuple of mesh axes) mapping.
# Flax linen spmd consumes these as `rules`.
DEFAULT_RULES: Tuple[Tuple[str, Optional[object]], ...] = (
    ('batch', ('data', 'fsdp')),   # batch sharded over data- and fsdp-axes
    ('seq', 'seq'),                # sequence (context) parallelism axis
    ('act_embed', None),           # activations' embed dim stays unsharded
    ('embed', 'fsdp'),             # FSDP: shard params' embed dim
    # Embedding-*table* embed dim stays unsharded: the scatter-add grad of
    # a gather forces GSPMD to reshard the residual-stream cotangent from
    # batch-sharded to embed-over-fsdp with batch replicated — an
    # "involuntary full rematerialization" (replicate-then-repartition).
    # Tables shard over vocab->tensor instead; dense kernels keep
    # embed->fsdp where the backward is a matmul (reduce-scatter-able).
    ('table_embed', None),
    ('heads', 'tensor'),           # TP: attention heads
    ('kv', None),
    ('mlp', 'tensor'),             # TP: MLP hidden
    ('vocab', 'tensor'),           # TP: embedding/vocab
    ('expert', 'expert'),          # MoE expert parallelism
    ('norm', None),
)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Named mesh axis sizes. Size 1 axes are kept (harmless to XLA)."""
    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    expert: int = 1
    seq: int = 1

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return ('data', 'fsdp', 'tensor', 'expert', 'seq')

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.data, self.fsdp, self.tensor, self.expert, self.seq)

    @property
    def num_devices(self) -> int:
        return math.prod(self.shape)

    @classmethod
    def auto(cls, num_devices: Optional[int] = None,
             tensor: int = 1, expert: int = 1, seq: int = 1) -> 'MeshConfig':
        """FSDP-first auto config: all remaining devices on the fsdp axis."""
        if num_devices is None:
            num_devices = len(jax.devices())
        inner = tensor * expert * seq
        if num_devices % inner != 0:
            raise ValueError(
                f'{num_devices} devices not divisible by '
                f'tensor*expert*seq={inner}')
        return cls(data=1, fsdp=num_devices // inner, tensor=tensor,
                   expert=expert, seq=seq)


def make_mesh(config: MeshConfig,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh, ICI-topology-aware within a slice, DCN-aware across.

    Within one TPU slice, `mesh_utils.create_device_mesh` lays the mesh
    onto the physical torus so that the innermost axes (tensor) ride
    the shortest ICI paths. Across slices (or hosts without ICI), the
    `data` axis is placed on DCN via the hybrid mesh helper.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if config.num_devices != len(devices):
        raise ValueError(
            f'Mesh needs {config.num_devices} devices, got {len(devices)}.')

    num_slices = len({getattr(d, 'slice_index', 0) for d in devices})
    if num_slices > 1:
        # Put data-parallel (the DCN-tolerant axis) across slices.
        if config.data % num_slices != 0:
            raise ValueError(
                f'data axis ({config.data}) must be divisible by the '
                f'number of slices ({num_slices}) for multislice meshes.')
        dcn_shape = [num_slices] + [1] * (len(config.shape) - 1)
        ici_shape = [config.data // num_slices, *config.shape[1:]]
        device_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices)
    else:
        try:
            device_array = mesh_utils.create_device_mesh(
                config.shape, devices=devices)
        except (ValueError, AssertionError):
            # Fallback (e.g. CPU device counts with no physical topology).
            device_array = np.asarray(devices).reshape(config.shape)
    return Mesh(device_array, config.axis_names)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for (batch, seq, ...) input arrays."""
    return NamedSharding(mesh, P(('data', 'fsdp'), None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def rules_with_overrides(
        overrides: Optional[Dict[str, Optional[object]]] = None
) -> Tuple[Tuple[str, Optional[object]], ...]:
    rules = dict(DEFAULT_RULES)
    if overrides:
        rules.update(overrides)
    return tuple(rules.items())


def mesh_summary(mesh: Mesh) -> str:
    parts = [f'{name}={size}' for name, size in
             zip(mesh.axis_names, mesh.devices.shape) if size > 1]
    return f'Mesh({", ".join(parts) or "single-device"})'
