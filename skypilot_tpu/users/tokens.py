"""Service-account tokens: server-derived identity for API requests.

Reference: sky/server/auth/ + sky/client/service_account_auth.py —
tokens minted by an admin, presented as `Authorization: Bearer`, and
resolved server-side to a user identity + role. Round-1's identity was
the client-chosen X-Skypilot-User header (spoofable — ADVICE r1);
with tokens, identity comes from the secret the client *holds*, not a
name it *claims*.

Only SHA-256 hashes are stored; the cleartext token is shown once at
issue time. Issuing the first token flips the server into
auth-required mode (see server.auth_middleware).
"""
from __future__ import annotations

import hashlib
import secrets
import time
import uuid
from typing import Any, Dict, List, Optional

_CREATE_SQL = """\
CREATE TABLE IF NOT EXISTS service_tokens (
    token_id TEXT PRIMARY KEY,
    user_hash TEXT,
    token_hash TEXT,
    created_at REAL,
    last_used_at REAL,
    revoked INTEGER DEFAULT 0
);
"""


_schema_ready: set = set()


def _db():
    from skypilot_tpu.users import core as users_core
    db = users_core._db()  # pylint: disable=protected-access
    # DDL only once per (process, db) — auth_middleware hits this on
    # every request and must not take the sqlite write lock each time.
    key = id(db)
    if key not in _schema_ready:
        with db.conn() as conn:
            conn.executescript(_CREATE_SQL)
        _schema_ready.add(key)
    return db


def _hash(token: str) -> str:
    return hashlib.sha256(token.encode()).hexdigest()


def issue(user_name: str, role: str = 'user') -> Dict[str, str]:
    """Mint a token for `user_name` (creating the user if needed).

    Returns {'token_id', 'token'} — the cleartext token appears only
    here. `role` applies only when the user is being created: minting
    a second token with the default role must never demote an existing
    admin (use `users role` to change roles).
    """
    from skypilot_tpu.users import core as users_core
    if role not in ('admin', 'user'):
        raise ValueError(f'Unknown role {role!r} (admin|user).')
    db = _db()
    existing = db.query_one('SELECT user_hash FROM users WHERE user_hash=?',
                            (user_name,))
    if existing is None:
        users_core.ensure_user(user_name, role)
    token_id = uuid.uuid4().hex[:12]
    token = f'sky-{token_id}-{secrets.token_urlsafe(24)}'
    db.execute(
        'INSERT INTO service_tokens (token_id, user_hash, token_hash, '
        'created_at) VALUES (?,?,?,?)',
        (token_id, user_name, _hash(token), time.time()))
    global _auth_required_cache
    _auth_required_cache = True
    return {'token_id': token_id, 'token': token}


def authenticate(token: str) -> Optional[Dict[str, Any]]:
    """Resolve a presented token → {'user', 'role', 'token_id'} or None."""
    if not token:
        return None
    row = _db().query_one(
        'SELECT token_id, user_hash FROM service_tokens '
        'WHERE token_hash=? AND revoked=0', (_hash(token),))
    if row is None:
        return None
    db = _db()
    db.execute('UPDATE service_tokens SET last_used_at=? WHERE token_id=?',
               (time.time(), row['token_id']))
    user = db.query_one('SELECT user_hash, role FROM users WHERE user_hash=?',
                        (row['user_hash'],))
    role = (user or {}).get('role') or 'user'
    return {'user': row['user_hash'], 'role': role,
            'token_id': row['token_id']}


_auth_required_cache = False
_auth_required_checked = False


def auth_required() -> bool:
    """True once ANY token has ever been issued.

    Deliberately counts revoked tokens too: revoking the last leaked
    token must lock the server down, not silently reopen it to
    unauthenticated requests. The transition is one-way and issue()
    (same process) flips the cache, so after the first check no DB
    query runs on the request hot path in either mode.
    """
    global _auth_required_cache, _auth_required_checked
    if _auth_required_cache or _auth_required_checked:
        return _auth_required_cache
    row = _db().query_one('SELECT COUNT(*) AS n FROM service_tokens', ())
    _auth_required_cache = bool(row and row['n'])
    _auth_required_checked = True
    return _auth_required_cache


def ls() -> List[Dict[str, Any]]:
    return _db().query(
        'SELECT token_id, user_hash, created_at, last_used_at, revoked '
        'FROM service_tokens ORDER BY created_at DESC')


def revoke(token_id: str) -> bool:
    db = _db()
    row = db.query_one('SELECT token_id FROM service_tokens WHERE token_id=?',
                       (token_id,))
    if row is None:
        return False
    db.execute('UPDATE service_tokens SET revoked=1 WHERE token_id=?',
               (token_id,))
    return True
