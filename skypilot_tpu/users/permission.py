"""RBAC policy: what a request may do, checked before scheduling.

Reference: sky/users/permission.py (casbin model) — roles `admin` and
`user`. Here the policy is code, not a casbin DSL:

  - admin: everything.
  - user: reads, creating own resources, and mutating resources they
    own; mutating someone else's cluster/request → PermissionError.

Ownership comes from the clusters table (`owner`, recorded from the
server-derived request identity at launch) and the requests table
(`user`).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from skypilot_tpu import global_state


class PermissionDeniedError(Exception):
    """403 at the HTTP boundary."""


# Endpoint name -> payload key naming the target cluster.
_CLUSTER_MUTATIONS = {
    'launch': 'cluster_name',
    'exec': 'cluster_name',
    'start': 'cluster_name',
    'stop': 'cluster_name',
    'down': 'cluster_name',
    'autostop': 'cluster_name',
    'cancel': 'cluster_name',
}


# Serve mutations keyed by service name; jobs by job id list; pools by
# pool name — all owner-or-admin.
_SERVICE_MUTATIONS = {'serve.update': 'service_name',
                      'serve.down': 'service_name'}
_POOL_MUTATIONS = {'jobs.pool_down': 'pool_name',
                   'jobs.pool_apply': 'pool_name'}


def check_request(name: str, payload: Dict[str, Any], user: str,
                  role: str) -> None:
    """Raise PermissionDeniedError if (user, role) may not run `name`."""
    if role == 'admin':
        return
    key = _CLUSTER_MUTATIONS.get(name)
    if key is not None:
        cluster_name = payload.get(key)
        if cluster_name:  # launch on a fresh auto-named cluster is fine
            _check_cluster_owner(cluster_name, user)
        return
    key = _SERVICE_MUTATIONS.get(name)
    if key is not None:
        _check_service_owner(payload.get(key), user)
        return
    key = _POOL_MUTATIONS.get(name)
    if key is not None:
        _check_pool_owner(payload.get(key), user)
        return
    if name == 'jobs.cancel':
        _check_managed_jobs_owner(payload, user)
        return
    # Reads and remaining non-owned ops are open to every user.


def _check_cluster_owner(cluster_name: str, user: str) -> None:
    record = global_state.get_cluster(cluster_name)
    if record is None:
        return  # creating a new cluster under this name
    owner = record.get('owner')
    if owner and owner != user:
        raise PermissionDeniedError(
            f'Cluster {cluster_name!r} belongs to {owner!r}; role `user` '
            f'may only mutate their own clusters (ask an admin).')


def _check_service_owner(service_name: Optional[str], user: str) -> None:
    if not service_name:
        return
    from skypilot_tpu.serve import serve_state
    record = serve_state.get_service(service_name)
    if record is None:
        return
    owner = record.get('user')
    if owner and owner not in ('unknown', user):
        raise PermissionDeniedError(
            f'Service {service_name!r} belongs to {owner!r}.')


def _check_pool_owner(pool_name: Optional[str], user: str) -> None:
    if not pool_name:
        return
    from skypilot_tpu.jobs import pools
    record = pools.get(pool_name)
    if record is None:
        return  # creating a new pool
    owner = record.get('user')
    if owner and owner not in ('unknown', user):
        raise PermissionDeniedError(
            f'Pool {pool_name!r} belongs to {owner!r}.')


def _check_managed_jobs_owner(payload: Dict[str, Any], user: str) -> None:
    from skypilot_tpu.jobs import state as jobs_state
    job_ids = payload.get('job_ids') or []
    if payload.get('all_jobs'):
        raise PermissionDeniedError(
            'Cancelling ALL managed jobs requires the admin role.')
    for job_id in job_ids:
        record = jobs_state.get_job(int(job_id))
        if record is None:
            continue
        owner = record.get('user')
        if owner and owner not in ('unknown', user):
            raise PermissionDeniedError(
                f'Managed job {job_id} belongs to {owner!r}.')


def check_request_cancel(record: Optional[Dict[str, Any]], user: str,
                         role: str) -> None:
    if role == 'admin' or record is None:
        return
    if record.get('user') and record['user'] != user:
        raise PermissionDeniedError(
            f'Request {record.get("request_id")} belongs to '
            f'{record["user"]!r}.')
