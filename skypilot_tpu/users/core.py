"""User registry: who has touched this API server.

Reference: sky/users/ (2.6k LoC with casbin RBAC). Round-1 scope:
the server records every requesting user (name + first/last seen +
request count) and exposes the registry; role-based enforcement is a
round-2 item layered on the same table.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List

from skypilot_tpu import global_state


def _db():
    db = global_state._db()  # pylint: disable=protected-access
    db.add_column_if_missing('users', 'last_seen', 'REAL')
    db.add_column_if_missing('users', 'request_count',
                             'INTEGER DEFAULT 0')
    db.add_column_if_missing('users', 'role', "TEXT DEFAULT 'user'")
    return db


def record_request(user_name: str) -> None:
    """Upsert the user and bump activity (called per API request)."""
    if not user_name or user_name == 'unknown':
        return
    db = _db()
    now = time.time()
    db.execute(
        'INSERT INTO users (user_hash, name, created_at, last_seen, '
        'request_count) VALUES (?,?,?,?,1) '
        'ON CONFLICT(user_hash) DO UPDATE SET last_seen=excluded.last_seen, '
        'request_count=request_count+1',
        (user_name, user_name, int(now), now))


def ensure_user(user_name: str, role: str = 'user') -> None:
    """Create the user if absent; update role if it already exists."""
    if role not in ('admin', 'user'):
        raise ValueError(f'Unknown role {role!r} (admin|user).')
    db = _db()
    now = time.time()
    db.execute(
        'INSERT INTO users (user_hash, name, created_at, role) '
        'VALUES (?,?,?,?) '
        'ON CONFLICT(user_hash) DO UPDATE SET role=excluded.role',
        (user_name, user_name, int(now), role))


def get_role(user_name: str) -> str:
    row = _db().query_one('SELECT role FROM users WHERE user_hash=?',
                          (user_name,))
    return (row or {}).get('role') or 'user'


def ls() -> List[Dict[str, Any]]:
    return _db().query(
        'SELECT name, role, created_at, last_seen, request_count '
        'FROM users ORDER BY last_seen DESC')


def set_role(user_name: str, role: str) -> None:
    """Update an existing user's role; unknown users are an error (a
    typo must not mint a phantom identity)."""
    if role not in ('admin', 'user'):
        raise ValueError(f'Unknown role {role!r} (admin|user).')
    db = _db()
    row = db.query_one('SELECT user_hash FROM users WHERE user_hash=?',
                       (user_name,))
    if row is None:
        raise KeyError(f'Unknown user {user_name!r}.')
    db.execute('UPDATE users SET role=? WHERE user_hash=?',
               (role, user_name))
