"""OIDC bearer-token verification for the API server.

Reference: sky/server/auth/ + sky/users/token_service.py — OAuth/OIDC
login where identity comes from a signed JWT instead of a stored
service token. Zero-egress friendly: the verification keys come from
config (`oauth.jwks` inline, or `oauth.jwks_path` file — e.g. synced
from the IdP by the operator); no JWKS fetch is required at request
time. RS256 via `cryptography`; no external JWT package.

Config (api server):
  oauth:
    issuer: https://idp.example.com
    client_id: stpu-cli
    jwks_path: /etc/stpu/jwks.json       # or `jwks: {keys: [...]}`
    admin_users: [alice@example.com]
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import time
from typing import Any, Dict, Optional, Tuple

from skypilot_tpu import sky_config


def _b64url_decode(data: str) -> bytes:
    pad = '=' * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def _b64url_encode(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).decode().rstrip('=')


# The oauth config block is read per request in the server's auth
# middleware; sky_config rebuilds (and schema-validates) every YAML
# layer per get_nested call, so snapshot it with a short TTL.
_cfg_cache: Tuple[float, Optional[Dict[str, Any]]] = (0.0, None)
_CFG_TTL = 5.0


def _oauth_cfg() -> Dict[str, Any]:
    global _cfg_cache
    if sky_config.has_overrides():
        # Runtime overrides (per-request config, tests) must never be
        # served from — or poison — the file-layer snapshot.
        return sky_config.get_nested(('oauth',), {}) or {}
    now = time.time()
    ts, cached = _cfg_cache
    if cached is None or now - ts > _CFG_TTL:
        cached = sky_config.get_nested(('oauth',), {}) or {}
        _cfg_cache = (now, cached)
    return cached


def enabled() -> bool:
    return bool(_oauth_cfg().get('issuer'))


def _load_jwks() -> Dict[str, Any]:
    jwks = _oauth_cfg().get('jwks')
    if jwks:
        return jwks
    path = _oauth_cfg().get('jwks_path')
    if path and os.path.exists(os.path.expanduser(str(path))):
        with open(os.path.expanduser(str(path)), 'r',
                  encoding='utf-8') as f:
            return json.load(f)
    return {'keys': []}


_crypto_warned = False


def _require_cryptography() -> bool:
    """RS256 needs the `cryptography` package; it is an OPTIONAL
    dependency (HS256 and service tokens are pure stdlib). Missing →
    verification fails closed with ONE loud, actionable log line
    instead of an ImportError mid-request."""
    global _crypto_warned
    try:
        import cryptography  # noqa: F401  pylint: disable=unused-import
        return True
    except ImportError:
        if not _crypto_warned:
            _crypto_warned = True
            import logging
            logging.getLogger(__name__).error(
                'RS256 JWT presented but the "cryptography" package '
                'is not installed — rejecting. Install it (pip '
                'install cryptography) or configure HS256 '
                '(oauth.hs256_secret).')
        return False


def _rsa_keys_for(kid: Optional[str]):
    """Candidate public keys: the kid match first, else every RSA key
    (key rotation: a JWKS holds old+new; tokens without a kid must be
    tried against each)."""
    from cryptography.hazmat.primitives.asymmetric import rsa
    keys = [k for k in _load_jwks().get('keys', [])
            if k.get('kty') == 'RSA']
    if kid is not None:
        matched = [k for k in keys if k.get('kid') == kid]
        keys = matched or keys
    out = []
    for k in keys:
        n = int.from_bytes(_b64url_decode(k['n']), 'big')
        e = int.from_bytes(_b64url_decode(k['e']), 'big')
        out.append(rsa.RSAPublicNumbers(e, n).public_key())
    return out


def _verify_signature(signing_input: bytes, signature: bytes,
                      alg: str, kid: Optional[str]) -> bool:
    if alg == 'RS256':
        if not _require_cryptography():
            return False
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding
        for key in _rsa_keys_for(kid):
            try:
                key.verify(signature, signing_input, padding.PKCS1v15(),
                           hashes.SHA256())
                return True
            except InvalidSignature:
                continue
        return False
    if alg == 'HS256':
        # Symmetric mode for self-hosted IdPs / tests: shared secret in
        # config (`oauth.hs256_secret`).
        secret = _oauth_cfg().get('hs256_secret')
        if not secret:
            return False
        expected = hmac.new(str(secret).encode(), signing_input,
                            hashlib.sha256).digest()
        return hmac.compare_digest(expected, signature)
    return False


def verify_jwt(token: str) -> Optional[Dict[str, str]]:
    """Verify an OIDC JWT; return {'user','role'} or None.

    Checks: structure, signature (RS256/HS256), exp/nbf, iss, aud
    (when a client_id is configured).
    """
    parts = token.split('.')
    if len(parts) != 3:
        return None
    try:
        header = json.loads(_b64url_decode(parts[0]))
        claims = json.loads(_b64url_decode(parts[1]))
        signature = _b64url_decode(parts[2])
    except (ValueError, json.JSONDecodeError):
        return None
    signing_input = f'{parts[0]}.{parts[1]}'.encode()
    if not _verify_signature(signing_input, signature,
                             header.get('alg', ''), header.get('kid')):
        return None
    now = time.time()
    # exp is REQUIRED: a signed token without one would be valid
    # forever and unrevocable (this is the server's only expiry
    # control for OIDC bearers).
    if claims.get('exp') is None or now >= float(claims['exp']):
        return None
    if claims.get('nbf') is not None and now < float(claims['nbf']):
        return None
    issuer = _oauth_cfg().get('issuer')
    if issuer and claims.get('iss') != issuer:
        return None
    client_id = _oauth_cfg().get('client_id')
    if client_id:
        aud = claims.get('aud')
        auds = aud if isinstance(aud, list) else [aud]
        if client_id not in auds:
            return None
    user = claims.get('email') or claims.get('preferred_username') or \
        claims.get('sub')
    if not user:
        return None
    admins = _oauth_cfg().get('admin_users') or []
    role = 'admin' if user in admins else 'user'
    return {'user': str(user), 'role': role}


def looks_like_jwt(token: str) -> bool:
    """Cheap dispatch: JWTs are three dot-separated b64url segments;
    service-account tokens are flat hex."""
    return token.count('.') == 2


# -- test/dev helper --------------------------------------------------------
def make_hs256_jwt(claims: Dict[str, Any], secret: str) -> str:
    """Mint an HS256 JWT (tests and self-hosted dev IdPs)."""
    header = _b64url_encode(json.dumps({'alg': 'HS256',
                                        'typ': 'JWT'}).encode())
    payload = _b64url_encode(json.dumps(claims).encode())
    sig = hmac.new(secret.encode(), f'{header}.{payload}'.encode(),
                   hashlib.sha256).digest()
    return f'{header}.{payload}.{_b64url_encode(sig)}'
