"""Declarative serving SLOs with multi-window burn-rate accounting.

An SLO spec is a comma list of `dimension=target` pairs:

    --slo p99_ttft_ms=500,p99_itl_ms=100,error_rate=0.01,shed_rate=0.05

Dimensions (all optional — declare only what you promise):

  p99_ttft_ms   99% of requests see their first token within N ms
  p99_itl_ms    99% of inter-token gaps within N ms
  error_rate    fraction of requests answered with an error
  shed_rate     fraction of offered requests shed (429) at admission

Burn-rate model (the standard SRE multi-window construction): each
dimension defines a "bad" predicate over its own sample stream —
requests for error/shed/ttft, inter-token GAPS for itl (one request
contributes as many itl samples as it streams gaps) — and an error
BUDGET, the fraction of samples allowed to be bad: the rate itself
for the rate dimensions, 1% for the p99 latency dimensions. Over a
window

    burn_rate = (bad / total) / budget

burn 1.0 = consuming budget exactly as fast as the SLO allows;
burn 10 on the fast window = page someone. `SloTracker` keeps
fixed-size time buckets (no per-request retention) and reports
burn over a fast and a slow window plus `budget_remaining`
(1 - slow burn, clamped to [0, 1]).

Clock discipline: buckets are keyed by ABSOLUTE bucket index from an
injectable monotonic clock. A stale bucket is reset on first write
after wraparound, and a clock that restarts at zero (process
restart; the "counter reset" case) simply makes old buckets
unreachable — window sums only accept indices inside
(now - window, now], so the math never goes negative.

The same target spec drives three consumers: the live tracker
(`/stats` + `/fleet/status` slo sections, `skypilot_serving_slo_*`
gauges), the LB fleet view, and `serve_bench --slo` pass/fail
scoring via `evaluate()`.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

DIMENSIONS = ('p99_ttft_ms', 'p99_itl_ms', 'error_rate', 'shed_rate')

#: Budget fraction per dimension: how many requests may be "bad"
#: while still meeting the SLO. p99 targets tolerate 1% by
#: definition; rate targets tolerate their own value.
_P99_BUDGET = 0.01

#: Default (fast, slow) burn-rate windows, seconds.
DEFAULT_WINDOWS = (60.0, 600.0)


def parse_slo(spec: str) -> Dict[str, float]:
    """Parse `dim=target,...`; raises ValueError on unknown
    dimensions, malformed pairs, or out-of-range targets."""
    targets: Dict[str, float] = {}
    for part in spec.split(','):
        part = part.strip()
        if not part:
            continue
        if '=' not in part:
            raise ValueError(
                f'bad SLO term {part!r}: expected dimension=target')
        key, _, raw = part.partition('=')
        key = key.strip()
        if key not in DIMENSIONS:
            raise ValueError(
                f'unknown SLO dimension {key!r} (choose from '
                f'{", ".join(DIMENSIONS)})')
        try:
            value = float(raw)
        except ValueError:
            raise ValueError(
                f'bad SLO target {raw!r} for {key}') from None
        if value <= 0:
            raise ValueError(f'SLO target for {key} must be > 0')
        if key.endswith('_rate') and value >= 1:
            raise ValueError(
                f'SLO target for {key} is a fraction; got {value}')
        targets[key] = value
    if not targets:
        raise ValueError(f'empty SLO spec {spec!r}')
    return targets


def budget_fraction(dimension: str, target: float) -> float:
    """Fraction of requests allowed to be bad for a dimension."""
    if dimension.endswith('_rate'):
        return target
    return _P99_BUDGET


class _Bucket:
    __slots__ = ('idx', 'total', 'offered', 'itl', 'bad')

    def __init__(self, idx: int) -> None:
        self.idx = idx
        self.total = 0    # completed requests
        self.offered = 0  # completed + shed
        self.itl = 0      # inter-token gap samples
        self.bad: Dict[str, int] = {}


class SloTracker:
    """Windowed good/bad accounting against a target spec.

    `record_request` is called once per finished (or shed) request
    from the HTTP/LB layer; `snapshot` renders the slo section and
    refreshes the `skypilot_serving_slo_*` gauges. Thread-safe."""

    def __init__(self, targets: Dict[str, float],
                 windows: tuple = DEFAULT_WINDOWS,
                 bucket_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 publish: bool = True) -> None:
        for dim in targets:
            if dim not in DIMENSIONS:
                raise ValueError(f'unknown SLO dimension {dim!r}')
        self.targets = dict(targets)
        self.windows = tuple(sorted(float(w) for w in windows))
        self.bucket_s = float(bucket_s)
        if self.bucket_s <= 0:
            raise ValueError('bucket_s must be > 0')
        self._clock = clock
        self._lock = threading.Lock()
        n = int(self.windows[-1] / self.bucket_s) + 1
        self._buckets: List[Optional[_Bucket]] = [None] * n
        self._bad_totals = {dim: 0 for dim in self.targets}
        self._metrics = None
        if publish:
            self._metrics = _slo_metrics()
            for dim, target in self.targets.items():
                self._metrics['target'].labels(dimension=dim).set(
                    target)

    # -- recording ---------------------------------------------------
    def record_request(self, error: bool = False, shed: bool = False,
                       ttft_ms: Optional[float] = None,
                       now: Optional[float] = None) -> None:
        if now is None:
            now = self._clock()
        bad = []
        if 'shed_rate' in self.targets and shed:
            bad.append('shed_rate')
        if not shed:
            if 'error_rate' in self.targets and error:
                bad.append('error_rate')
            if ('p99_ttft_ms' in self.targets and ttft_ms is not None
                    and ttft_ms > self.targets['p99_ttft_ms']):
                bad.append('p99_ttft_ms')
        with self._lock:
            idx = int(now // self.bucket_s)
            slot = idx % len(self._buckets)
            b = self._buckets[slot]
            if b is None or b.idx != idx:
                b = _Bucket(idx)
                self._buckets[slot] = b
            b.offered += 1
            if not shed:
                b.total += 1
            for dim in bad:
                b.bad[dim] = b.bad.get(dim, 0) + 1
                self._bad_totals[dim] += 1
        if self._metrics is not None:
            for dim in bad:
                self._metrics['bad'].labels(dimension=dim).inc()

    def record_itl(self, gap_ms: float,
                   now: Optional[float] = None) -> None:
        """One inter-token gap sample (streamed requests, measured at
        engine commit). The itl dimension burns against GAP count,
        not request count — a 1000-token stream gets 999 chances to
        blow its p99, exactly like the percentile it models."""
        if 'p99_itl_ms' not in self.targets:
            return
        if now is None:
            now = self._clock()
        bad = gap_ms > self.targets['p99_itl_ms']
        with self._lock:
            idx = int(now // self.bucket_s)
            slot = idx % len(self._buckets)
            b = self._buckets[slot]
            if b is None or b.idx != idx:
                b = _Bucket(idx)
                self._buckets[slot] = b
            b.itl += 1
            if bad:
                b.bad['p99_itl_ms'] = b.bad.get('p99_itl_ms', 0) + 1
                self._bad_totals['p99_itl_ms'] += 1
        if bad and self._metrics is not None:
            self._metrics['bad'].labels(dimension='p99_itl_ms').inc()

    # -- window math -------------------------------------------------
    def _window_counts(self, window: float, now: float
                       ) -> Dict[str, Any]:
        hi = int(now // self.bucket_s)
        lo = hi - int(window / self.bucket_s)
        total = offered = itl = 0
        bad = {dim: 0 for dim in self.targets}
        for b in self._buckets:
            if b is None or not lo < b.idx <= hi:
                continue
            total += b.total
            offered += b.offered
            itl += b.itl
            for dim, n in b.bad.items():
                bad[dim] = bad.get(dim, 0) + n
        return {'total': total, 'offered': offered, 'itl': itl,
                'bad': bad}

    def burn_rate(self, dimension: str, window: float,
                  now: Optional[float] = None) -> float:
        if now is None:
            now = self._clock()
        with self._lock:
            counts = self._window_counts(window, now)
        return self._burn(dimension, counts)

    def _burn(self, dimension: str, counts: Dict[str, Any]) -> float:
        if dimension == 'shed_rate':
            denom = counts['offered']
        elif dimension == 'p99_itl_ms':
            denom = counts['itl']
        else:
            denom = counts['total']
        if denom <= 0:
            return 0.0
        frac = counts['bad'].get(dimension, 0) / denom
        return frac / budget_fraction(dimension,
                                      self.targets[dimension])

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The `slo` section for /stats and /fleet/status. Also
        refreshes the slo gauges (scrape piggybacks on render)."""
        if now is None:
            now = self._clock()
        with self._lock:
            per_window = {w: self._window_counts(w, now)
                          for w in self.windows}
            bad_totals = dict(self._bad_totals)
        windows_out: Dict[str, Any] = {}
        slow = self.windows[-1]
        ok = True
        budget_remaining: Dict[str, float] = {}
        for w, counts in per_window.items():
            label = f'{int(w)}s'
            dims = {}
            for dim in sorted(self.targets):
                burn = round(self._burn(dim, counts), 4)
                dims[dim] = {
                    'bad': counts['bad'].get(dim, 0),
                    'burn_rate': burn,
                }
                if self._metrics is not None:
                    self._metrics['burn'].labels(
                        dimension=dim, window=label).set(burn)
                if w == slow:
                    remaining = round(max(0.0, 1.0 - burn), 4)
                    budget_remaining[dim] = remaining
                    if self._metrics is not None:
                        self._metrics['remaining'].labels(
                            dimension=dim).set(remaining)
                    if burn > 1.0:
                        ok = False
            windows_out[label] = {
                'requests': counts['total'],
                'offered': counts['offered'],
                'itl_samples': counts['itl'],
                'dimensions': dims,
            }
        return {
            'targets': dict(self.targets),
            'windows': windows_out,
            'budget_remaining': budget_remaining,
            'bad_total': bad_totals,
            'ok': ok,
        }


def _slo_metrics() -> Dict[str, Any]:
    """The `skypilot_serving_slo_*` catalog rows, created lazily so
    importing this module never touches the registry."""
    from skypilot_tpu.observability import catalog
    return {
        'target': catalog.gauge('skypilot_serving_slo_target'),
        'burn': catalog.gauge('skypilot_serving_slo_burn_rate'),
        'remaining': catalog.gauge(
            'skypilot_serving_slo_budget_remaining'),
        'bad': catalog.counter('skypilot_serving_slo_bad_total'),
    }


def evaluate(targets: Dict[str, float],
             observed: Dict[str, Optional[float]]) -> Dict[str, Any]:
    """Score one bench run against a target spec. `observed` maps
    dimension -> measured value (missing/None = not measured, which
    fails the dimension: an unmeasured promise is a broken one).
    Returns a machine-checkable block: per-dimension pass/fail plus
    overall `ok` and worst-case `budget_consumed` (observed/target,
    so 1.0 = budget exactly spent)."""
    results = []
    ok = True
    worst = 0.0
    for dim, target in sorted(targets.items()):
        obs = observed.get(dim)
        if obs is None:
            results.append({'dimension': dim, 'target': target,
                            'observed': None, 'ok': False,
                            'budget_consumed': None})
            ok = False
            continue
        consumed = round(float(obs) / target, 4)
        passed = float(obs) <= target
        results.append({'dimension': dim, 'target': target,
                        'observed': round(float(obs), 4),
                        'ok': passed,
                        'budget_consumed': consumed})
        worst = max(worst, consumed)
        ok = ok and passed
    return {'ok': ok, 'budget_consumed': round(worst, 4),
            'results': results}
