"""Trainer step telemetry: one JSONL record per logged step window.

`train_lm.py --metrics-file out.jsonl` constructs a StepMetrics and
calls `log()` at every `--log-every` boundary. Each record carries
the TPU-pod vital signs (step time, tokens/s, loss, grad norm) plus
an achieved-MFU estimate against the device's peak FLOPs — the
"are we running as fast as the hardware allows" number every perf PR
is judged by. Records are flushed line-by-line so a preempted run's
file is still valid JSONL up to the last completed window.

MFU model: achieved = 6 * n_params * tokens/s (the standard dense-
transformer train-FLOPs estimate, fwd+bwd); peak comes from
SKYPILOT_DEVICE_PEAK_FLOPS (per device, bf16) or a small device-kind
table. Unknown hardware (CPU smoke runs) reports mfu = null rather
than a made-up number.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

# Peak bf16 FLOPs per chip (marketing numbers; the MFU denominator).
# device_kind substrings, checked in order.
_PEAK_FLOPS_BY_KIND = (
    ('v5p', 459e12),
    ('v5e', 197e12),  # v5 litepod
    ('v6e', 918e12),
    ('v4', 275e12),
    ('v3', 123e12),
    ('v2', 45e12),
)


def peak_flops_per_device() -> Optional[float]:
    """Per-device peak FLOPs: env override first, then the device-kind
    table; None when neither matches (e.g. CPU)."""
    env = os.environ.get('SKYPILOT_DEVICE_PEAK_FLOPS')
    if env:
        return float(env)
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # pylint: disable=broad-except
        return None
    for sub, flops in _PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return flops
    return None


class StepMetrics:
    """JSONL step-metrics emitter. Construct once per run; `log()`
    per logged window; `close()` at the end (also flushes)."""

    def __init__(self, path: str, *, n_params: Optional[int] = None,
                 n_devices: int = 1,
                 peak_flops: Optional[float] = None) -> None:
        self.path = os.path.expanduser(path)
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        self.n_params = n_params
        self.n_devices = max(n_devices, 1)
        self.peak_flops = (peak_flops if peak_flops is not None
                           else peak_flops_per_device())
        self._f = open(self.path, 'a', encoding='utf-8')

    def mfu(self, tokens_per_sec: float) -> Optional[float]:
        """Achieved-MFU estimate: 6 * N * tok/s over the slice's
        aggregate peak. None without a param count or a known peak."""
        if not self.n_params or not self.peak_flops:
            return None
        achieved = 6.0 * self.n_params * tokens_per_sec
        return round(achieved / (self.peak_flops * self.n_devices), 4)

    def log(self, step: int, *, step_time_s: float, tokens: int,
            loss: float, grad_norm: Optional[float] = None,
            bubble_frac: Optional[float] = None,
            collective_wait_s: Optional[float] = None,
            extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Write one record covering a window that ended at `step`:
        `step_time_s` is the mean per-step wall time over the window,
        `tokens` the tokens consumed by ONE step. `bubble_frac` is
        the pipeline schedule's idle fraction (null for non-pipeline
        runs); `collective_wait_s` the host-observed drain wait at
        the window boundary — the un-overlapped remainder of the
        device critical path the --overlap knob exists to shrink."""
        tokens_per_sec = (tokens / step_time_s if step_time_s > 0
                          else 0.0)
        record: Dict[str, Any] = {
            'step': int(step),
            'time': time.time(),
            'step_time_s': round(float(step_time_s), 6),
            'tokens_per_sec': round(tokens_per_sec, 2),
            'loss': float(loss),
            'grad_norm': (None if grad_norm is None
                          else float(grad_norm)),
            'mfu': self.mfu(tokens_per_sec),
            'bubble_frac': (None if bubble_frac is None
                            else round(float(bubble_frac), 6)),
            'collective_wait_s': (
                None if collective_wait_s is None
                else round(float(collective_wait_s), 6)),
        }
        if extra:
            record.update(extra)
        self._f.write(json.dumps(record) + '\n')
        self._f.flush()
        return record

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self) -> 'StepMetrics':
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a --metrics-file back into records (analysis + tests)."""
    records = []
    with open(os.path.expanduser(path), 'r', encoding='utf-8') as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records
