"""Distributed request tracing for the serving plane.

Dependency-free span layer: every sampled request gets a 16-hex
trace_id that travels LB -> prefill replica -> decode peer over the
`x-skypilot-trace` header, and every interesting stage (route
decision, queue wait, admission, prefill chunks, decode rounds,
device_get stalls, KV handoff export/POST/import, spill/restore)
becomes a complete ('X') Chrome trace event — the exact format
`utils/timeline.py` / `--trace-file` already emits, so a merged
trace loads in chrome://tracing or Perfetto unchanged.

Design constraints, in order:

  1. ZERO overhead when off. `new_ctx()` is one comparison when
     `--trace-sample 0` (the default); `span(name, None)` returns a
     shared no-op singleton — no allocation, no clock reads.
  2. BOUNDED memory. Completed spans land in a per-process LRU of at
     most `MAX_TRACES` traces x `MAX_SPANS_PER_TRACE` spans; an
     unscraped process can run forever.
  3. DETERMINISTIC sampling. The sample decision and the ids both
     come from one seeded `random.Random`, so `--trace-seed` makes a
     run's sampled set (and its ids) reproducible — the property the
     tier-1 determinism test pins.

Wall-clock anchors, monotonic durations: `ts` is `time.time()` (the
only clock comparable across processes — the `stpu trace` merge
sorts on it) while `dur` comes from a `perf_counter` pair, so a span
is never shrunk or stretched by NTP slew.

Header format (`HEADER`): `<trace_id>:<parent_span_id>:<flags>`,
flags bit 0 = sampled. Unsampled requests send no header at all.

Each process tags its spans with a `process` name (`configure`), and
any single span can override it — that is what lets the in-process
stub fleet (LB + N replicas in one interpreter, one shared module)
still produce per-role `pid` rows.

Span discipline: every span must be closed — use `with span(...)`
or put `.end()` in a `finally`. `stpu check` rule SKY007 enforces
this for non-test code.
"""
from __future__ import annotations

import collections
import random
import threading
import time
from typing import Any, Dict, List, Optional

#: The propagation header (lowercase: http.server title-cases on the
#: wire but compares case-insensitively).
HEADER = 'x-skypilot-trace'

#: Bounds on the per-process completed-span store.
MAX_TRACES = 256
MAX_SPANS_PER_TRACE = 512

_lock = threading.Lock()
_sample = 0.0
_rng = random.Random(0)
_process = 'skypilot'
_traces: 'collections.OrderedDict[str, List[dict]]' = \
    collections.OrderedDict()


class Ctx:
    """Propagation context: which trace, and which span is the
    parent of whatever starts next. Immutable by convention."""

    __slots__ = ('trace_id', 'span_id')

    def __init__(self, trace_id: str, span_id: str = '') -> None:
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:
        return f'Ctx({self.trace_id}:{self.span_id})'


def configure(sample: Optional[float] = None,
              seed: Optional[int] = None,
              process: Optional[str] = None) -> None:
    """Set the sampling rate / rng seed / process tag. Any argument
    left None keeps its current value (so the LB can set `process`
    without touching the replica-configured rate in tests)."""
    global _sample, _rng, _process
    with _lock:
        if sample is not None:
            _sample = max(0.0, min(1.0, float(sample)))
        if seed is not None:
            _rng = random.Random(seed)
        if process is not None:
            _process = str(process)


def enabled() -> bool:
    return _sample > 0.0


def new_ctx() -> Optional[Ctx]:
    """Head-based sampling decision for a request arriving with no
    trace header. Returns None (do nothing, forward nothing) for
    unsampled requests — the common case is one float compare."""
    if _sample <= 0.0:
        return None
    with _lock:
        if _rng.random() >= _sample:
            return None
        return Ctx('%016x' % _rng.getrandbits(64))


def _new_span_id() -> str:
    with _lock:
        return '%08x' % _rng.getrandbits(32)


def parse_header(value: Optional[str]) -> Optional[Ctx]:
    """`<trace_id>:<parent_span_id>:<flags>` -> Ctx, or None for a
    missing/malformed/unsampled header (all equivalent: no tracing)."""
    if not value:
        return None
    parts = value.strip().split(':')
    if len(parts) != 3:
        return None
    trace_id, span_id, flags = parts
    if not trace_id or not flags.isdigit() or not (int(flags) & 1):
        return None
    return Ctx(trace_id, span_id)


def format_header(ctx: Ctx) -> str:
    return f'{ctx.trace_id}:{ctx.span_id}:1'


class Span:
    """A live span. Started on construction; records one Chrome
    trace event on `end()` (idempotent). `ctx` is the context to
    hand to children / the wire."""

    __slots__ = ('name', 'ctx', '_parent', '_proc', '_args',
                 '_wall', '_t0', '_done')

    def __init__(self, name: str, ctx: Ctx,
                 process: Optional[str] = None,
                 **args: Any) -> None:
        self.name = name
        self._parent = ctx.span_id
        self.ctx = Ctx(ctx.trace_id, _new_span_id())
        self._proc = process
        self._args = dict(args)
        self._wall = time.time()
        self._t0 = time.perf_counter()
        self._done = False

    def add(self, **kv: Any) -> None:
        """Attach extra args to the span before it ends."""
        self._args.update(kv)

    def end(self, **kv: Any) -> None:
        if self._done:
            return
        self._done = True
        dur = time.perf_counter() - self._t0
        if kv:
            self._args.update(kv)
        args = {'trace_id': self.ctx.trace_id,
                'span_id': self.ctx.span_id,
                'parent_id': self._parent}
        args.update(self._args)
        event = {
            'name': self.name,
            'cat': 'skypilot_tpu',
            'ph': 'X',
            'ts': self._wall * 1e6,
            'dur': dur * 1e6,
            'pid': self._proc if self._proc is not None else _process,
            'tid': threading.get_ident() % 100000,
            'args': args,
        }
        with _lock:
            spans = _traces.get(self.ctx.trace_id)
            if spans is None:
                while len(_traces) >= MAX_TRACES:
                    _traces.popitem(last=False)
                spans = _traces[self.ctx.trace_id] = []
            if len(spans) < MAX_SPANS_PER_TRACE:
                spans.append(event)

    def __enter__(self) -> 'Span':
        return self

    def __exit__(self, *exc: Any) -> None:
        if exc and exc[0] is not None:
            self._args.setdefault('error', str(exc[0].__name__))
        self.end()


class _NoopSpan:
    """Shared do-nothing span for unsampled requests. `ctx` is None
    so children short-circuit the same way."""

    __slots__ = ()
    ctx: Optional[Ctx] = None
    name = ''

    def add(self, **kv: Any) -> None:
        pass

    def end(self, **kv: Any) -> None:
        pass

    def __enter__(self) -> '_NoopSpan':
        return self

    def __exit__(self, *exc: Any) -> None:
        pass


NOOP = _NoopSpan()


def span(name: str, ctx: Optional[Ctx],
         process: Optional[str] = None, **args: Any):
    """Open a span under `ctx`. With `ctx=None` (unsampled) this is
    free: the shared no-op singleton comes back. Close it — context
    manager or `finally` — or SKY007 will flag the call site."""
    if ctx is None:
        return NOOP
    return Span(name, ctx, process=process, **args)


def start_span(name: str, ctx: Optional[Ctx],
               process: Optional[str] = None, **args: Any):
    """Manual-lifetime variant of `span` for spans that cross
    function boundaries (queue wait, decode-round occupancy). The
    caller owns `.end()` — put it in a `finally` (SKY007)."""
    if ctx is None:
        return NOOP
    return Span(name, ctx, process=process, **args)


def record_span(name: str, ctx: Optional[Ctx], dur_s: float,
                start: Optional[float] = None,
                process: Optional[str] = None, **args: Any) -> None:
    """Record an interval the caller already measured (a perf_counter
    pair around existing code) as one completed span. This is how the
    engine scheduler traces without restructuring its hot loop: no
    open span object lives across scheduler iterations, so there is
    nothing for SKY007 to leak. `start` is the wall-clock begin
    (time.time()); default anchors the span so it ENDS now."""
    if ctx is None:
        return
    sp = Span(name, ctx, process=process, **args)
    sp._wall = start if start is not None else time.time() - dur_s
    sp._t0 = time.perf_counter() - dur_s
    sp.end()


def get_trace(trace_id: str) -> Optional[Dict[str, Any]]:
    """Completed spans of one trace as a Chrome-trace JSON body, or
    None if this process recorded nothing for it."""
    with _lock:
        spans = _traces.get(trace_id)
        if spans is None:
            return None
        return {'traceEvents': list(spans)}


def trace_ids() -> List[str]:
    """Known trace ids, oldest first (bounded by MAX_TRACES)."""
    with _lock:
        return list(_traces)


def reset() -> None:
    """Test hook: drop all stored traces and disable sampling."""
    global _sample, _rng, _process
    with _lock:
        _traces.clear()
        _sample = 0.0
        _rng = random.Random(0)
        _process = 'skypilot'


def merge_traces(bodies: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Stitch per-process `get_trace` bodies into one timeline:
    de-duplicate on span_id (an in-process fleet shares one store, so
    every node returns every span), then sort by wall-clock `ts`.
    Used by `stpu trace` and by anything replaying saved dumps."""
    seen = set()
    merged: List[dict] = []
    for body in bodies:
        for ev in (body or {}).get('traceEvents', []):
            key = (ev.get('args', {}).get('span_id'),
                   ev.get('name'), ev.get('ts'))
            if key in seen:
                continue
            seen.add(key)
            merged.append(ev)
    merged.sort(key=lambda e: e.get('ts', 0))
    return {'traceEvents': merged}
