"""Unified observability layer: metrics registry + Prometheus text
exposition (metrics.py), the process-wide metric catalog (catalog.py),
and the trainer's JSONL step-metrics emitter (step_metrics.py).

Scrape points:
  - API server:        GET /api/metrics   (server/server.py)
  - inference server:  GET /metrics       (inference/http_server.py)
  - trainer:           --metrics-file out.jsonl (recipes/train_lm.py)
"""
from skypilot_tpu.observability.metrics import (Counter, Gauge,
                                                Histogram, REGISTRY,
                                                Registry)

__all__ = ['Counter', 'Gauge', 'Histogram', 'REGISTRY', 'Registry']
