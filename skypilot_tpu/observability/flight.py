"""Engine flight recorder: a fixed-size ring of scheduler events.

Aggregate metrics say *that* p99 spiked; the flight recorder says
*what the scheduler did* in the seconds before — which slot was
admitted, which chunk dispatched, who was preempted, which page
chain spilled, and the exact soft-error -> 3-strike -> reset
escalation. It records UNCONDITIONALLY (no sampling flag): one list
slot assignment per event, cheap enough to leave on in production.

The ring is single-writer (the engine scheduler thread owns all
`record()` calls) and lock-free by design: list item assignment is
atomic under the GIL, and `dump()` (HTTP scrape threads) takes a
racy-but-consistent snapshot the same way the engine's counters do.

Event shape: `(wall_ts, kind, fields)` in the ring, rendered as
`{'ts', 'seq', 'kind', **fields}` in dumps. Kinds the engine emits:
admit, chunk_dispatch, round_commit, preempt, evict, spill, restore,
handoff_export, kv_import, soft_error, reset, death. The schema is
open — `fields` is whatever the call site passes.

On engine reset or scheduler death the engine calls `snapshot()`,
which writes the full dump to a JSON file (`STPU_FLIGHT_DIR`, else
the system temp dir) so the postmortem survives the process.
"""
from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import ux_utils

DEFAULT_CAPACITY = 256


class FlightRecorder:
    """Bounded ring of `(ts, kind, fields)` scheduler events."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 name: str = 'engine') -> None:
        if capacity < 1:
            raise ValueError('flight recorder capacity must be >= 1')
        self.capacity = int(capacity)
        self.name = name
        # Single-writer ring: only the engine scheduler thread writes
        # (SKY008-verified via the entry contract on record()); scrape
        # threads take racy snapshot READS, which ownership permits.
        self._buf: List[Optional[tuple]] = [None] * self.capacity  # stpu: owner[scheduler]
        self._n = 0  # total events ever recorded  # stpu: owner[scheduler]

    def record(self, kind: str, **fields: Any) -> None:  # stpu: entry[scheduler]
        """Append one event. ~Zero cost: a clock read, a tuple, one
        list slot write. Safe to call at every scheduler decision."""
        i = self._n
        self._buf[i % self.capacity] = (time.time(), kind,
                                        fields or None)
        self._n = i + 1

    @property
    def recorded(self) -> int:
        return self._n

    def events(self) -> List[Dict[str, Any]]:
        """Retained events, oldest first, each stamped with its
        absolute sequence number (so a dump shows how many events a
        wrapped ring dropped before its first row)."""
        n = self._n
        cap = self.capacity
        if n <= cap:
            rows = list(enumerate(self._buf[:n]))
        else:
            start = n % cap
            ring = self._buf[start:] + self._buf[:start]
            rows = [(n - cap + i, r) for i, r in enumerate(ring)]
        out = []
        for seq, row in rows:
            if row is None:  # racing a concurrent record(); skip
                continue
            ts, kind, fields = row
            ev = {'seq': seq, 'ts': ts, 'kind': kind}
            if fields:
                ev.update(fields)
            out.append(ev)
        return out

    def dump(self) -> Dict[str, Any]:
        events = self.events()
        return {
            'name': self.name,
            'capacity': self.capacity,
            'recorded': self._n,
            'dropped': max(0, self._n - self.capacity),
            'events': events,
        }

    def snapshot(self, reason: str = 'manual',
                 path: Optional[str] = None) -> Optional[str]:
        """Write the dump to a JSON file and return its path. Never
        raises — the recorder is a postmortem aid, not a correctness
        dependency — but a failed write is logged."""
        body = self.dump()
        body['reason'] = reason
        if path is None:
            root = os.environ.get('STPU_FLIGHT_DIR',
                                  tempfile.gettempdir())
            path = os.path.join(
                root, f'stpu-flight-{self.name}-{os.getpid()}-'
                      f'{reason}-{self._n}.json')
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            tmp = f'{path}.tmp.{os.getpid()}'
            with open(tmp, 'w', encoding='utf-8') as f:
                json.dump(body, f)
            os.replace(tmp, path)
            return path
        except OSError as e:
            ux_utils.log(f'flight recorder: snapshot {reason!r} to '
                         f'{path} failed ({e}); dump still available '
                         f'via /debug/flight.')
            return None
