"""The metric catalog: every Prometheus metric this codebase exports.

One table, three consumers:
  - the instrumentation sites (`counter()`/`gauge()`/`histogram()`
    get-or-create against the default REGISTRY from these specs);
  - the docs metric table (docs/guides.md — kept in sync by
    tests/unit_tests/test_metric_catalog.py);
  - the CI name checker (snake_case, `skypilot_` prefix, documented).

Adding a metric = adding a row here + a line in the docs table; the
checker fails the build on drift.
"""
from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

from skypilot_tpu.observability import metrics as m

# Latency buckets, seconds. Step/prefill: device dispatches (ms..s);
# request path: whole generations (up to minutes).
STEP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5, 5.0, 10.0)
REQUEST_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                   10.0, 30.0, 60.0, 120.0)
TOKEN_GAP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5)

# name -> (kind, help, labelnames[, options])
#   kind: counter | gauge | histogram | gauge_as_counter
#   options: {'buckets': (...)} for histograms
SPECS: Dict[str, Tuple] = {
    # -- serving engine (models/batching.py); label engine = instance id
    'skypilot_serving_queue_depth': (
        'gauge', 'Requests waiting for a decode slot (queued + ready)',
        ('engine',)),
    'skypilot_serving_active_slots': (
        'gauge', 'Decode slots currently running a request',
        ('engine',)),
    'skypilot_serving_num_slots': (
        'gauge', 'Decode slot pool size', ('engine',)),
    'skypilot_serving_admissions_total': (
        'counter', 'Requests admitted into a decode slot (prefilled)',
        ('engine',)),
    'skypilot_serving_preemptions_total': (
        'counter', 'Requests preempted by KV page-pool pressure '
                   '(re-queued for recompute)', ('engine',)),
    'skypilot_serving_decode_steps_total': (
        'counter', 'Jitted decode dispatches (plain, chunked, or '
                   'speculative-verify rounds)', ('engine',)),
    'skypilot_serving_tokens_committed_total': (
        'counter', 'Generated tokens committed across all slots',
        ('engine',)),
    'skypilot_serving_decode_step_seconds': (
        'histogram', 'Wall time of one decode round (dispatch + '
                     'host commit)', ('engine',),
        {'buckets': STEP_BUCKETS}),
    'skypilot_serving_prefill_seconds': (
        'histogram', 'Wall time from a request\'s first prefill '
                     'chunk dispatch to its first token (whole-prompt '
                     'prefill when chunking is off)', ('engine',),
        {'buckets': STEP_BUCKETS}),
    'skypilot_serving_prefill_chunk_seconds': (
        'histogram', 'Wall time of one chunked-prefill dispatch '
                     '(async dispatch cost, not device compute — the '
                     'stall-free scheduler never waits on prefill)',
        ('engine',), {'buckets': STEP_BUCKETS}),
    'skypilot_serving_prefill_backlog_tokens': (
        'gauge', 'Prompt-suffix tokens admitted into a slot but not '
                 'yet prefilled (chunked-prefill backlog)',
        ('engine',)),
    'skypilot_serving_prefill_budget_utilization': (
        'gauge', 'Prefill tokens run last iteration / per-iteration '
                 'token budget (0..1)', ('engine',)),
    'skypilot_serving_decode_stall_seconds_total': (
        'counter', 'Cumulative wall time the scheduler host blocked '
                   'on fetching decode tokens from the device '
                   '(pipelining hides this behind the next dispatch)',
        ('engine',)),
    'skypilot_serving_kv_pool_bytes': (
        'gauge', 'Device bytes of the engine\'s KV cache (paged: '
                 'int8/bf16 pages + scale arrays; dense: per-slot '
                 'rows) — the quantized-serving memory denominator',
        ('engine',)),
    'skypilot_serving_kv_pool_bytes_per_device': (
        'gauge', 'KV cache bytes resident on ONE device: sharded '
                 'pool values count a single kv-heads shard, '
                 'replicated leaves in full — the per-chip HBM '
                 'figure --kv-pool-bytes budgets under --tensor '
                 '(equals kv_pool_bytes on a single device)',
        ('engine',)),
    'skypilot_serving_weight_bytes': (
        'gauge', 'Device bytes of the served model weights '
                 '(quantized projections count their int8 + scale '
                 'footprint)', ()),
    'skypilot_serving_storage_info': (
        'gauge', 'Serving storage formats in effect (always 1; read '
                 'the kv_dtype/weight_dtype labels)',
        ('kv_dtype', 'weight_dtype')),
    'skypilot_serving_attention_impl_info': (
        'gauge', 'Resolved paged-attention implementation in effect '
                 '(always 1; read the labels — impl is xla | kernel | '
                 'fused | fused_interpret, or dense when the engine '
                 'runs the dense KV cache; ops/pallas_paged.py '
                 'dispatch rules)',
        ('engine', 'impl', 'kv_dtype')),
    'skypilot_serving_attention_bytes_per_token': (
        'gauge', 'Modeled HBM bytes one decode step moves per '
                 'generated token at the current decode batch: pool '
                 'reads + scale rows + the XLA route\'s dequantize '
                 'materialization + amortized weight reads + LoRA '
                 'factor rows (ops/pallas_paged.bytes_per_token_model '
                 '— the serve_bench roofline denominator)',
        ('engine',)),
    'skypilot_serving_pipeline_stages': (
        'gauge', 'Pipeline-parallel stages the engine serves over '
                 '(--stages; 1 = no stage split). Each stage owns a '
                 'contiguous layer range on its own tensor submesh '
                 'and stores only its layers\' KV pages', ('engine',)),
    'skypilot_serving_prefill_bubble_fraction': (
        'gauge', 'Closed-form pipeline fill/drain bubble of the last '
                 'prefill burst: (S-1)/(M+S-1) for S stages and M '
                 'chunk microbatches (0 when S=1 or no prefill has '
                 'run)', ('engine',)),
    'skypilot_serving_pages_free': (
        'gauge', 'Free pages in the shared KV page pool', ('engine',)),
    'skypilot_serving_pages_used': (
        'gauge', 'Allocated pages in the shared KV page pool '
                 '(incl. prefix-cache residents)', ('engine',)),
    'skypilot_serving_prefix_cache_hits_total': (
        'counter', 'Prompt pages served from the prefix cache '
                   '(prefill skipped)', ('engine',)),
    'skypilot_serving_prefix_cache_misses_total': (
        'counter', 'Full prompt pages that had to be computed',
        ('engine',)),
    'skypilot_serving_prefix_cache_evictions_total': (
        'counter', 'Cached pages evicted back to the allocator under '
                   'pool pressure', ('engine',)),
    'skypilot_serving_engine_restarts_total': (
        'counter', 'Full engine resets after an unrecoverable '
                   'scheduler error (KV cache lost; in-flight '
                   'requests failed, slots rebuilt)', ('engine',)),
    # -- tiered prefix cache + disaggregated prefill/decode handoff
    #    (inference/kv_transfer.py + models/batching.py)
    'skypilot_serving_kv_spill_pages_total': (
        'counter', 'Prefix-cache pages spilled to the host-RAM tier '
                   'on pool-pressure eviction (payload + scales + '
                   'chain key) instead of being dropped', ('engine',)),
    'skypilot_serving_kv_restore_pages_total': (
        'counter', 'Spilled pages restored into the page pool on a '
                   'chain-key hit (bit-identical to the original '
                   'compute; the prefill those pages would have '
                   'cost was skipped)', ('engine',)),
    'skypilot_serving_kv_restore_hit_ratio': (
        'gauge', 'Spill-tier lookups that restored a page / all '
                 'spill-tier lookups (0..1; lookups happen only for '
                 'chain keys past the device-resident prefix)',
        ('engine',)),
    'skypilot_serving_kv_handoff_seconds': (
        'histogram', 'Wall time of one prefill->decode KV page-chain '
                     'handoff (export + POST /kv/import + decode-'
                     'side scatter), success or failure',
        (), {'buckets': REQUEST_BUCKETS}),
    'skypilot_serving_kv_handoff_bytes_total': (
        'counter', 'Packed KV chain bytes shipped to decode replicas '
                   'by this prefill replica', ()),
    # -- live KV-chain migration (models/batching.evacuate_chains +
    #    http_server /kv/evacuate + /kv/migrate)
    'skypilot_serving_migrations_total': (
        'counter', 'Sessions this replica migrated OUT to a peer '
                   '(chain shipped + tail proxied), by trigger: '
                   'drain (scale-down victim / SIGTERM), preempt '
                   '(preemption notice), rebalance (hot-spot '
                   'migration), or local_fallback (peer ship failed; '
                   'finished locally on the promoted warm pages)',
        ('reason',)),
    'skypilot_serving_chains_evacuated_total': (
        'counter', 'Active KV chains the engine evacuated (packed '
                   'committed-token pages + SessionMigratedError to '
                   'the owning HTTP thread); >= migrations_total '
                   'because failed ships fall back locally', ()),
    'skypilot_serving_migration_seconds': (
        'histogram', 'Wall time of one session migration: chain POST '
                     'to /kv/migrate through the peer\'s first '
                     'response byte (success or failure)',
        (), {'buckets': REQUEST_BUCKETS}),
    'skypilot_serving_tokens_recomputed_total': (
        'counter', 'Committed tokens a migrated-in session had to '
                   're-prefill on this replica (committed length '
                   'minus imported/cached full-page coverage): the '
                   'migration-vs-full-replay recompute cost, ~0 when '
                   'the chain shipped intact', ()),
    # -- multi-LoRA adapter registry (inference/adapters.py)
    'skypilot_serving_adapters_loaded': (
        'gauge', 'Adapters resident in the device store (loaded '
                 'stack rows, pinned or LRU-evictable)', ()),
    'skypilot_serving_adapter_requests_total': (
        'counter', 'Requests admitted per adapter (the `model` field '
                   'routed to a LoRA adapter)', ('adapter',)),
    'skypilot_serving_adapter_tokens_total': (
        'counter', 'Generated tokens committed per adapter',
        ('adapter',)),
    'skypilot_serving_adapter_loads_total': (
        'counter', 'Adapter artifacts loaded into the device store '
                   '(cold or re-load after eviction)', ('adapter',)),
    'skypilot_serving_adapter_evictions_total': (
        'counter', 'Unpinned adapters LRU-evicted from the device '
                   'store to make room for a load', ('adapter',)),
    'skypilot_serving_adapter_load_failures_total': (
        'counter', 'Adapter loads that failed (corrupt artifact, '
                   'rank/shape mismatch, or injected adapters.load '
                   'fault); the request fails 503, the engine keeps '
                   'serving', ()),
    # -- serving request path (inference/runtime.py + http_server.py)
    'skypilot_serving_requests_total': (
        'counter', 'Completed generation requests', ()),
    'skypilot_serving_prompt_tokens_total': (
        'counter', 'Prompt tokens across completed requests', ()),
    'skypilot_serving_completion_tokens_total': (
        'counter', 'Generated tokens across completed requests', ()),
    'skypilot_serving_ttft_seconds': (
        'histogram', 'Time to first token: first committed token for '
                     'engine-backed requests (streaming and not)',
        (), {'buckets': REQUEST_BUCKETS}),
    'skypilot_serving_inter_token_seconds': (
        'histogram', 'Gap between consecutive streamed tokens of one '
                     'request row', (),
        {'buckets': TOKEN_GAP_BUCKETS}),
    'skypilot_serving_e2e_latency_seconds': (
        'histogram', 'End-to-end request latency', (),
        {'buckets': REQUEST_BUCKETS}),
    'skypilot_serving_requests_shed_total': (
        'counter', 'Requests rejected 429 by admission control '
                   '(bounded queue full)', ()),
    'skypilot_serving_deadline_exceeded_total': (
        'counter', 'Requests answered 504: deadline expired while '
                   'queued or mid-decode', ()),
    # -- SLO / error-budget accounting (observability/slo.py; fed by
    #    http_server + LB per finished/shed request)
    'skypilot_serving_slo_target': (
        'gauge', 'Declared SLO target per dimension (p99_ttft_ms, '
                 'p99_itl_ms, error_rate, shed_rate) as passed to '
                 '--slo; absent dimensions are not promised',
        ('dimension',)),
    'skypilot_serving_slo_burn_rate': (
        'gauge', 'Error-budget burn rate per dimension and window: '
                 '(bad/total)/budget over the window, where budget '
                 'is the rate target itself or 1% for p99 latency '
                 'dimensions; 1.0 = consuming budget exactly at the '
                 'allowed pace', ('dimension', 'window')),
    'skypilot_serving_slo_budget_remaining': (
        'gauge', 'max(0, 1 - slow-window burn rate) per dimension: '
                 'the fraction of error budget left if the current '
                 'pace holds', ('dimension',)),
    'skypilot_serving_slo_bad_total': (
        'counter', 'Requests that violated an SLO dimension (errored, '
                   'shed, or over the latency target), cumulative '
                   'since process start', ('dimension',)),
    # -- replica plane (serve/replica_plane/: manager + LB front-end)
    'skypilot_lb_requests_routed_total': (
        'counter', 'Requests the replica-plane LB routed to a '
                   'replica, by load-balancing policy (retries count '
                   'once per attempt)', ('policy',)),
    'skypilot_lb_requests_retried_total': (
        'counter', 'Idempotent (not-yet-streamed) requests the LB '
                   'retried on another replica after a replica died '
                   'or refused, by policy', ('policy',)),
    'skypilot_lb_affinity_requests_total': (
        'counter', 'LB requests that carried a prefix-affinity '
                   'routing key (a full prompt page)', ()),
    'skypilot_lb_affinity_hits_total': (
        'counter', 'Keyed LB requests routed to their affinity '
                   'target (the replica already holding the prefix '
                   'KV pages); hits/requests is the affinity hit '
                   'ratio', ()),
    'skypilot_lb_ttft_seconds': (
        'histogram', 'LB-side time to first response byte, anchored '
                     'at the FIRST attempt (a retry after a replica '
                     'death still counts the dead attempt: this is '
                     'user-perceived TTFT)', (),
        {'buckets': REQUEST_BUCKETS}),
    'skypilot_lb_request_seconds': (
        'histogram', 'LB-side end-to-end proxy latency across all '
                     'retry attempts, anchored at the first attempt',
        (), {'buckets': REQUEST_BUCKETS}),
    'skypilot_replica_plane_replicas': (
        'gauge', 'Local serve_lm replicas managed by the replica '
                 'plane, by lifecycle state', ('state',)),
    'skypilot_replica_plane_scrape_errors_total': (
        'counter', 'Replica /stats-/readyz scrapes that failed '
                   '(replica dead, hung, or malformed response)', ()),
    # -- crash-only fleet controller (replica_plane/journal.py,
    #    fleet.py): restart adoption + tick-failure fuse
    'skypilot_fleet_adoptions_total': (
        'counter', 'Replicas a restarted fleet controller verified '
                   '(pid alive + /stats echoing the journaled '
                   'instance UUID) and reattached as live handles '
                   'instead of killing or orphaning them', ()),
    'skypilot_fleet_orphans_reaped_total': (
        'counter', 'Journaled replicas a restarted controller could '
                   'NOT verify (dead pid, unreachable port, or '
                   'instance-UUID mismatch from pid/port reuse) — '
                   'asked to drain via SIGTERM (never SIGKILL) and '
                   'dropped from the journal', ()),
    'skypilot_fleet_tick_errors_total': (
        'counter', 'Fleet-controller ticks that raised; 3 '
                   'consecutive failures flip the degraded gauge',
        ()),
    'skypilot_fleet_controller_degraded': (
        'gauge', '1 while the fleet controller has failed 3+ '
                 'consecutive ticks (replicas keep serving, but '
                 'scaling and routing updates are stalled); back to '
                 '0 on the first successful tick', ()),
    # -- checkpoint integrity (parallel/checkpoints.py + manifests)
    'skypilot_checkpoint_integrity_failures_total': (
        'counter', 'Checkpoint steps that failed sha256 manifest '
                   'verification at restore (torn/corrupt writes); '
                   'each one triggers fallback to the newest '
                   'verifying step', ()),
    # -- self-supervising trainer (robustness/train_guard.py; the
    #    controller-side increments live in jobs/controller.py when a
    #    typed trainer exit lands)
    'skypilot_train_preempt_notices_total': (
        'counter', 'Preemption notices observed (GCE metadata, '
                   'SIGTERM, or injected): each one is a graceful '
                   'checkpoint-now-then-exit the controller answers '
                   'with recovery instead of FAILED', ()),
    'skypilot_train_guard_skipped_steps_total': (
        'counter', 'Optimizer steps the on-device NaN/spike guard '
                   'skipped (non-finite loss/grad norm, or norm '
                   'above the EMA spike threshold); K consecutive '
                   'skips trigger rollback to the last verified '
                   'checkpoint', ()),
    'skypilot_train_watchdog_aborts_total': (
        'counter', 'Hung trainers the step watchdog aborted (stuck '
                   'collective or stalled data loader past the '
                   'per-phase deadline), with all thread stacks '
                   'dumped; the controller relaunches instead of '
                   'waiting forever', ()),
    # -- pipeline schedule + collective overlap (parallel/pipeline.py
    #    + recipes/train_lm.py)
    'skypilot_train_pipeline_bubble_fraction': (
        'gauge', 'Idle fraction of the active pipeline schedule '
                 '(bubble slots / stage-tick slots, '
                 '(S-1)/(M*v+S-1) for every style): drive it down '
                 'by raising microbatches (1f1b frees the '
                 'activation memory to do so) or virtual stages '
                 '(interleaved)', ()),
    'skypilot_train_collective_wait_seconds_total': (
        'counter', 'Host-observed drain wait at step-window '
                   'boundaries: the un-overlapped tail of the '
                   'device critical path (compute + serialized '
                   'collectives). --overlap should shrink it '
                   'run-over-run; the --profile trace names the '
                   'collectives in the gap', ()),
    # -- managed jobs (jobs/controller.py + recovery_strategy.py)
    'skypilot_jobs_recovery_attempts_total': (
        'counter', 'Managed-job recovery attempts (cluster lost or '
                   'reported failed), by recovery strategy',
        ('strategy',)),
    'skypilot_jobs_preemptions_total': (
        'counter', 'Managed-job cluster preemptions detected '
                   '(probes unreachable past the grace window, or '
                   'an external failure source), by zone the lost '
                   'cluster was placed in — a spiking zone label is '
                   'a spot storm', ('zone',)),
    'skypilot_jobs_relaunch_inflight': (
        'gauge', 'Cluster (re)launch attempts currently in flight '
                 'for managed jobs in this process (fleet-wide in '
                 'the fleet simulator; per-controller in '
                 'production) — the thundering-herd signal jittered '
                 'backoff keeps bounded', ()),
    # -- API server (server/server.py)
    'skypilot_api_requests_total': (
        'counter', 'API server HTTP requests', ('route', 'method',
                                                'code')),
    'skypilot_api_request_seconds': (
        'histogram', 'API server HTTP request latency',
        ('route', 'method'), {'buckets': STEP_BUCKETS}),
    'skypilot_api_requests_in_flight': (
        'gauge', 'API server HTTP requests currently being handled',
        ()),
    'skypilot_requests_total': (
        'gauge_as_counter', 'Async request records by status '
                            '(DB-derived at scrape)', ('status',)),
    'skypilot_clusters': (
        'gauge', 'Clusters by status', ('status',)),
    'skypilot_managed_jobs': (
        'gauge', 'Managed jobs by status', ('status',)),
    'skypilot_services': ('gauge', 'SkyServe services', ()),
    'skypilot_service_replicas_ready': (
        'gauge', 'Ready replicas across services', ()),
    'skypilot_server_rss_bytes': (
        'gauge', 'API server process RSS', ()),
    'skypilot_workers_rss_bytes': (
        'gauge', 'Combined RSS of API server child processes', ()),
    'skypilot_server_uptime_seconds': (
        'gauge', 'Seconds since the API server started', ()),
    'skypilot_scrape_errors_total': (
        'counter', 'Orchestration-gauge sections that failed to '
                   'collect (see server log)', ('section',)),
}

_KINDS = {'counter': m.Counter, 'gauge': m.Gauge,
          'histogram': m.Histogram, 'gauge_as_counter': m.Gauge}


def _create(name: str,
            registry: Optional[m.Registry] = None) -> m._Metric:
    spec = SPECS[name]
    kind, help_text, labelnames = spec[0], spec[1], spec[2]
    options = spec[3] if len(spec) > 3 else {}
    registry = registry or m.REGISTRY
    kwargs = dict(options)
    if kind == 'gauge_as_counter':
        kwargs['expose_type'] = 'counter'
    return registry.get_or_create(_KINDS[kind], name, help_text,
                                  labelnames, **kwargs)


def counter(name: str) -> m.Counter:
    return _create(name)


def gauge(name: str) -> m.Gauge:
    return _create(name)


def histogram(name: str) -> m.Histogram:
    return _create(name)


class EngineMetrics:
    """The continuous-batching engine's instrument bundle, one labeled
    child set per engine instance (label engine="0", "1", ...)."""

    def __init__(self, engine_label: str) -> None:
        lab = {'engine': engine_label}
        self._engine_label = engine_label
        self.queue_depth = gauge(
            'skypilot_serving_queue_depth').labels(**lab)
        self.active_slots = gauge(
            'skypilot_serving_active_slots').labels(**lab)
        self.num_slots = gauge(
            'skypilot_serving_num_slots').labels(**lab)
        self.admissions = counter(
            'skypilot_serving_admissions_total').labels(**lab)
        self.preemptions = counter(
            'skypilot_serving_preemptions_total').labels(**lab)
        self.decode_steps = counter(
            'skypilot_serving_decode_steps_total').labels(**lab)
        self.tokens_committed = counter(
            'skypilot_serving_tokens_committed_total').labels(**lab)
        self.decode_step_seconds = histogram(
            'skypilot_serving_decode_step_seconds').labels(**lab)
        self.prefill_seconds = histogram(
            'skypilot_serving_prefill_seconds').labels(**lab)
        self.prefill_chunk_seconds = histogram(
            'skypilot_serving_prefill_chunk_seconds').labels(**lab)
        self.prefill_backlog = gauge(
            'skypilot_serving_prefill_backlog_tokens').labels(**lab)
        self.prefill_budget_utilization = gauge(
            'skypilot_serving_prefill_budget_utilization').labels(
                **lab)
        self.decode_stall_seconds = counter(
            'skypilot_serving_decode_stall_seconds_total').labels(
                **lab)
        self.kv_pool_bytes = gauge(
            'skypilot_serving_kv_pool_bytes').labels(**lab)
        self.kv_pool_bytes_per_device = gauge(
            'skypilot_serving_kv_pool_bytes_per_device').labels(**lab)
        self.pipeline_stages = gauge(
            'skypilot_serving_pipeline_stages').labels(**lab)
        self.prefill_bubble_fraction = gauge(
            'skypilot_serving_prefill_bubble_fraction').labels(**lab)
        self.pages_free = gauge(
            'skypilot_serving_pages_free').labels(**lab)
        self.pages_used = gauge(
            'skypilot_serving_pages_used').labels(**lab)
        self.prefix_hits = counter(
            'skypilot_serving_prefix_cache_hits_total').labels(**lab)
        self.prefix_misses = counter(
            'skypilot_serving_prefix_cache_misses_total').labels(**lab)
        self.prefix_evictions = counter(
            'skypilot_serving_prefix_cache_evictions_total').labels(
                **lab)
        self.engine_restarts = counter(
            'skypilot_serving_engine_restarts_total').labels(**lab)
        self.kv_spill_pages = counter(
            'skypilot_serving_kv_spill_pages_total').labels(**lab)
        self.kv_restore_pages = counter(
            'skypilot_serving_kv_restore_pages_total').labels(**lab)
        self.kv_restore_hit_ratio = gauge(
            'skypilot_serving_kv_restore_hit_ratio').labels(**lab)
        self.attention_bytes_per_token = gauge(
            'skypilot_serving_attention_bytes_per_token').labels(**lab)

    def set_attention_info(self, impl: str, kv_dtype: str) -> None:
        """Info-style gauge (always 1): the resolved paged-attention
        impl and KV storage dtype ride the labels, so a dashboard can
        tell WHICH kernel path an engine is on without parsing logs."""
        gauge('skypilot_serving_attention_impl_info').labels(
            engine=self._engine_label, impl=impl,
            kv_dtype=kv_dtype).set(1)


class RequestMetrics:
    """The inference request path's instrument bundle (process-global,
    shared by every runtime in the process)."""

    def __init__(self) -> None:
        self.requests = counter('skypilot_serving_requests_total')
        self.prompt_tokens = counter(
            'skypilot_serving_prompt_tokens_total')
        self.completion_tokens = counter(
            'skypilot_serving_completion_tokens_total')
        self.ttft_seconds = histogram('skypilot_serving_ttft_seconds')
        self.inter_token_seconds = histogram(
            'skypilot_serving_inter_token_seconds')
        self.e2e_latency_seconds = histogram(
            'skypilot_serving_e2e_latency_seconds')
        self.requests_shed = counter(
            'skypilot_serving_requests_shed_total')
        self.deadline_exceeded = counter(
            'skypilot_serving_deadline_exceeded_total')


class FirstTokenLatch:
    """TTFT for non-streaming engine requests: passed as the engine's
    `on_token` callback, latches the wall-clock instant of the FIRST
    decode-step commit (streaming requests latch in their own
    StreamHandle). Thread-safe by construction: the latch is written
    only by the engine scheduler thread."""

    __slots__ = ('t0', 'first_token_s')

    def __init__(self) -> None:
        self.t0 = time.monotonic()
        self.first_token_s: Optional[float] = None

    def __call__(self, tok: int) -> None:
        del tok
        if self.first_token_s is None:
            self.first_token_s = time.monotonic() - self.t0
