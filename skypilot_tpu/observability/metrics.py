"""Dependency-free metrics registry + Prometheus text exposition.

The single metrics layer shared by the API server, the inference
server, and the trainer (vLLM's /metrics idea without the
prometheus_client dependency — the container images stay stdlib-only).
Three primitive families, all thread-safe:

  Counter    monotonically increasing (`inc`)
  Gauge      set/inc/dec; can also expose under TYPE counter for
             values that are semantically running totals but are
             recomputed from a source of truth at scrape time (the
             API server's DB-derived request counts)
  Histogram  fixed buckets chosen at declaration; cumulative
             `_bucket{le=...}` + `_sum` + `_count` exposition

Metrics are process-global: a family is registered once (by name) in
the default REGISTRY and fans out into labeled children via
`.labels(**kv)`. Rendering (`REGISTRY.render()`) emits Prometheus
text exposition format 0.0.4 — parseable by any Prometheus scraper —
with label values escaped per the spec.

Declare families through `observability/catalog.py` (the single
source of metric names; the docs table and the CI name-checker key
off it) rather than instantiating these classes directly.
"""
from __future__ import annotations

import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r'^[a-z_][a-z0-9_]*$')

# The histogram default: request-latency shaped, seconds.
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                   2.5, 5.0, 10.0)


def _format_value(v: float) -> str:
    """Prometheus sample value: integers render bare (the slow-tier
    tests substring-match `skypilot_clusters{status="up"} 1`)."""
    if v == math.inf:
        return '+Inf'
    if v == -math.inf:
        return '-Inf'
    if v != v:  # NaN
        return 'NaN'
    if float(v).is_integer() and abs(v) < 2**53:
        return str(int(v))
    return repr(float(v))


def escape_label_value(value: str) -> str:
    return (str(value).replace('\\', '\\\\').replace('\n', '\\n')
            .replace('"', '\\"'))


def _escape_help(text: str) -> str:
    return text.replace('\\', '\\\\').replace('\n', '\\n')


class _Child:
    """One labeled series of a family. Holds a float value (Counter/
    Gauge) behind the family lock."""

    __slots__ = ('_family', '_value')

    def __init__(self, family: '_Metric') -> None:
        self._family = family
        self._value = 0.0

    @property
    def value(self) -> float:
        with self._family._lock:
            return self._value


class _CounterChild(_Child):

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f'counters only go up (inc {amount})')
        with self._family._lock:
            self._value += amount


class _GaugeChild(_Child):

    def set(self, value: float) -> None:
        with self._family._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._family._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._family._lock:
            self._value -= amount


class _HistogramChild:

    __slots__ = ('_family', '_counts', '_sum', '_count')

    def __init__(self, family: 'Histogram') -> None:
        self._family = family
        self._counts = [0] * (len(family.buckets) + 1)  # + +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        family = self._family
        with family._lock:
            self._sum += value
            self._count += 1
            # Linear scan: bucket lists are ~a dozen entries and the
            # observe sites are host-side (ms-scale device steps).
            for i, bound in enumerate(family.buckets):
                if value <= bound:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        with self._family._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._family._lock:
            return self._sum


class _Metric:
    """A metric family: name + help + label names, fanning out into
    labeled children. The no-label family is its own single child."""

    typ = 'untyped'
    _child_cls = _Child

    def __init__(self, name: str, help: str,  # pylint: disable=redefined-builtin
                 labelnames: Sequence[str] = (),
                 expose_type: Optional[str] = None) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f'invalid metric name {name!r}')
        for ln in labelnames:
            if not _NAME_RE.match(ln):
                raise ValueError(f'invalid label name {ln!r}')
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.expose_type = expose_type or self.typ
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = self._child_cls(self)

    def labels(self, **labelvalues: str):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f'{self.name} takes labels {self.labelnames}, got '
                f'{tuple(labelvalues)}')
        key = tuple(str(labelvalues[ln]) for ln in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._child_cls(self)
                self._children[key] = child
            return child

    def clear(self) -> None:
        """Drop every labeled child (scrape-time rebuilt gauges: a
        status that disappeared must not linger at its last value)."""
        with self._lock:
            self._children.clear()
            if not self.labelnames:
                self._children[()] = self._child_cls(self)

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f'{self.name} is labeled {self.labelnames}; use '
                f'.labels(...)')
        return self._children[()]

    # -- exposition ---------------------------------------------------------
    def _label_str(self, key: Tuple[str, ...],
                   extra: Tuple[Tuple[str, str], ...] = ()) -> str:
        pairs = [(ln, lv) for ln, lv in zip(self.labelnames, key)]
        pairs.extend(extra)
        if not pairs:
            return ''
        inner = ','.join(f'{ln}="{escape_label_value(lv)}"'
                         for ln, lv in pairs)
        return '{' + inner + '}'

    def collect(self) -> List[str]:
        lines = [f'# HELP {self.name} {_escape_help(self.help)}',
                 f'# TYPE {self.name} {self.expose_type}']
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            lines.append(f'{self.name}{self._label_str(key)} '
                         f'{_format_value(child._value)}')
        return lines


class Counter(_Metric):
    typ = 'counter'
    _child_cls = _CounterChild

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(_Metric):
    typ = 'gauge'
    _child_cls = _GaugeChild

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(_Metric):
    typ = 'histogram'
    _child_cls = _HistogramChild

    def __init__(self, name: str, help: str,  # pylint: disable=redefined-builtin
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        buckets = tuple(sorted(float(b) for b in buckets))
        if not buckets:
            raise ValueError('histogram needs at least one bucket')
        self.buckets = buckets
        super().__init__(name, help, labelnames)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    @property
    def count(self) -> int:
        return self._default().count

    @property
    def sum(self) -> float:
        return self._default().sum

    def collect(self) -> List[str]:
        lines = [f'# HELP {self.name} {_escape_help(self.help)}',
                 f'# TYPE {self.name} histogram']
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            with self._lock:
                counts = list(child._counts)
                total = child._count
                vsum = child._sum
            cum = 0
            for bound, n in zip(self.buckets, counts):
                cum += n
                lab = self._label_str(key,
                                      (('le', _format_value(bound)),))
                lines.append(f'{self.name}_bucket{lab} {cum}')
            lab = self._label_str(key, (('le', '+Inf'),))
            lines.append(f'{self.name}_bucket{lab} {total}')
            lines.append(f'{self.name}_sum{self._label_str(key)} '
                         f'{_format_value(vsum)}')
            lines.append(f'{self.name}_count{self._label_str(key)} '
                         f'{total}')
        return lines


class Registry:
    """Name-keyed family registry. `get_or_create` is the idempotent
    declaration point (tests and reloads re-declare freely; a
    conflicting redeclaration — different type/labels — is a bug and
    raises)."""

    CONTENT_TYPE = 'text/plain; version=0.0.4; charset=utf-8'

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: 'Dict[str, _Metric]' = {}

    def get_or_create(self, cls, name: str, help: str,  # pylint: disable=redefined-builtin
                      labelnames: Sequence[str] = (), **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is not cls or
                        existing.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f'metric {name!r} already registered as '
                        f'{type(existing).__name__}'
                        f'{existing.labelnames}')
                return existing
            metric = cls(name, help, labelnames, **kwargs)
            self._metrics[name] = metric
            return metric

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def render(self, names: Optional[Iterable[str]] = None) -> str:
        """Prometheus text exposition of every (or the named)
        registered family, name-sorted for stable scrapes."""
        with self._lock:
            if names is None:
                metrics = [self._metrics[n] for n in
                           sorted(self._metrics)]
            else:
                metrics = [self._metrics[n] for n in names
                           if n in self._metrics]
        lines: List[str] = []
        for metric in metrics:
            lines.extend(metric.collect())
        return '\n'.join(lines) + '\n'


REGISTRY = Registry()
