"""In-framework LM inference server: the payload of serve replicas.

A JetStream-shaped HTTP server: GET / (readiness), POST /generate
{"tokens": [[...]], "max_new_tokens": N, "temperature": t,
 "top_k": k, "top_p": p} →
{"tokens": [[...]]}. Listens on SKYPILOT_SERVE_PORT (injected by the
serve controller). Two engines:

  - default: one jitted fixed-shape generate fn per batch bucket
    (models/generate.py) — simplest, one request at a time;
  - --continuous-batching: the slot-based engine
    (models/batching.py) — concurrent requests share the decode
    loop, joining and leaving without draining the batch (the
    throughput mode under ragged request lengths).

  stpu serve up -y -n llama task.yaml   # run: python -m
      skypilot_tpu.recipes.serve_lm --model llama-tiny
"""
from __future__ import annotations

import argparse
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Tuple


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='llama-tiny')
    parser.add_argument('--hf', default=None, metavar='DIR',
                        help='serve a HuggingFace checkpoint from a '
                             'local directory (e.g. the target of an '
                             'hf:// storage COPY): weights are '
                             'converted in-process '
                             '(models/hf_import.py) and --model is '
                             'ignored; if tokenizer files are present, '
                             'POST /generate_text serves text in/out')
    parser.add_argument('--ckpt-dir', default=None,
                        help='orbax checkpoint to load weights from')
    parser.add_argument('--max-total-len', type=int, default=256)
    parser.add_argument('--continuous-batching', action='store_true',
                        help='slot-based engine: concurrent requests '
                             'share the decode loop')
    parser.add_argument('--num-slots', type=int, default=8)
    parser.add_argument('--speculative', type=int, default=0,
                        metavar='K',
                        help='prompt-lookup speculative decoding with K '
                             'drafted tokens per step. One-shot engine: '
                             'greedy requests, exact greedy outputs. '
                             'Continuous batching: every slot rides '
                             'verify chunks (greedy exact; sampled '
                             'stays unbiased via match-acceptance)')
    parser.add_argument('--port', type=int,
                        default=int(os.environ.get('SKYPILOT_SERVE_PORT',
                                                   8000)))
    parser.add_argument('--tensor', type=int, default=1,
                        help='tensor-parallel serving over N devices: '
                             'params shard per the training rules '
                             '(heads/mlp/vocab over the tensor axis) '
                             'and XLA propagates the sharding through '
                             'every serving fn — models bigger than '
                             'one chip serve across the slice')
    parser.add_argument('--no-prefix-caching', action='store_true',
                        help='disable shared-prefix KV page reuse '
                             '(vLLM-style APC; on by default with the '
                             'paged cache — repeated system prompts '
                             'skip recomputation and share pool pages)')
    parser.add_argument('--param-dtype', choices=['bf16', 'f32'],
                        default='bf16',
                        help='on-device dtype for --hf weights. bf16 '
                             '(default) halves HBM vs f32; compute '
                             'already runs in bf16 either way. Models '
                             'bigger than one chip serve with '
                             '--tensor N (sharded across the slice). '
                             'f32 is for CPU parity runs')
    parser.add_argument('--cpu', action='store_true',
                        help='pin the CPU backend (smoke/dev runs; the '
                             'JAX_PLATFORMS env var is overridden by '
                             'some TPU plugins, jax.config is not)')
    args = parser.parse_args()

    import flax.linen as nn
    import jax
    if args.cpu:
        jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp

    from skypilot_tpu.models import generate as gen
    from skypilot_tpu.recipes.train_lm import _build_model

    tokenizer_dir = None
    hf_params = None
    if args.hf:
        from skypilot_tpu.models import hf_import
        model, hf_params = hf_import.load_hf_checkpoint(
            args.hf, max_seq_len=args.max_total_len)
        # Raw f32 numpy here; the cast (bf16 via ml_dtypes) happens
        # PER LEAF at placement time below — host transient is one
        # leaf, device footprint is the bf16 shards.
        import ml_dtypes
        import numpy as _np
        serve_cast = (ml_dtypes.bfloat16 if args.param_dtype == 'bf16'
                      else _np.float32)
        vocab_size = model.config.vocab_size
        print(f'loaded HF checkpoint from {args.hf} '
              f'({type(model).__name__}, vocab={vocab_size})', flush=True)
        if any(os.path.exists(os.path.join(args.hf, f))
               for f in ('tokenizer.json', 'tokenizer_config.json',
                         'tokenizer.model')):
            tokenizer_dir = args.hf
    else:
        model, vocab_size, _ = _build_model(args.model,
                                            args.max_total_len,
                                            remat=False)
    # Speculative decoding writes its verify chunk up to K tokens past
    # the last kept one; fail fast / clamp at STARTUP instead of
    # erroring inside every request handler
    # (models/generate.py make_speculative_generate_fn asserts
    # max_total_len + K <= model.config.max_seq_len).
    spec_total = args.max_total_len
    if args.speculative > 0:
        spec_total = min(args.max_total_len,
                         model.config.max_seq_len - args.speculative)
        if spec_total <= 1:
            parser.error(
                f'--speculative {args.speculative} needs headroom in '
                f'the model context: max_seq_len='
                f'{model.config.max_seq_len} leaves no room for the '
                f'verify chunk. Use a smaller K or a longer-context '
                f'model.')
        if spec_total < args.max_total_len:
            print(f'speculative decoding: clamping max_total_len '
                  f'{args.max_total_len} -> {spec_total} (verify chunk '
                  f'needs K={args.speculative} tokens of headroom '
                  f'below max_seq_len={model.config.max_seq_len})',
                  flush=True)
    if hf_params is not None:
        params = hf_params
    else:
        serve_cast = None  # init params stay f32 masters
        params = nn.meta.unbox(model.init(
            jax.random.PRNGKey(0),
            jnp.ones((1, 8), jnp.int32))['params'])
    # ONE placement block for both param sources: TP-shard over the
    # mesh (per-leaf cast, shard-only transfers) or single-device.
    if args.tensor > 1:
        from skypilot_tpu.parallel import mesh as mesh_lib
        from skypilot_tpu.parallel.serving import shard_params_for_serving
        mesh = mesh_lib.make_mesh(
            mesh_lib.MeshConfig(tensor=args.tensor),
            devices=jax.devices()[:args.tensor])
        params = shard_params_for_serving(model, params, mesh,
                                          dtype=serve_cast)
        print(f'tensor-parallel serving over {args.tensor} devices',
              flush=True)
    elif serve_cast is not None:
        import numpy as _np
        params = jax.tree.map(
            lambda x: jnp.asarray(_np.asarray(x).astype(serve_cast)),
            params)
    if args.ckpt_dir:
        from skypilot_tpu.parallel.checkpoints import CheckpointManager
        mgr = CheckpointManager(args.ckpt_dir)
        if mgr.latest_step() is not None:
            from skypilot_tpu.parallel.train import TrainState
            import optax
            template = TrainState.create(params, optax.sgd(1e-3))
            params = mgr.restore(template).params
            print(f'loaded checkpoint step {mgr.latest_step()}', flush=True)

    # Tokenizer, loaded lazily on the first /generate_text request.
    tok_holder: Dict[str, object] = {}
    tok_lock = threading.Lock()

    def get_tokenizer():
        with tok_lock:
            if 'tok' not in tok_holder:
                if tokenizer_dir is None:
                    raise ValueError(
                        'no tokenizer available: /generate_text needs '
                        'a --hf checkpoint with tokenizer files; use '
                        '/generate with token ids instead')
                from skypilot_tpu.models.hf_import import load_tokenizer
                tok_holder['tok'] = load_tokenizer(tokenizer_dir)
            return tok_holder['tok']

    # The engine serves every request class at ONE capacity: the
    # speculative-clamped total when speculation is on (spec rounds
    # drive greedy AND sampled slots in the same verify chunk).
    engine_total = spec_total if args.speculative > 0 \
        else args.max_total_len
    engine = None
    if args.continuous_batching:
        from skypilot_tpu.models.batching import ContinuousBatchingEngine
        engine = ContinuousBatchingEngine(
            model, params, num_slots=args.num_slots,
            max_total_len=engine_total,
            prefix_caching=not args.no_prefix_caching,
            speculative_k=args.speculative)

    # One jitted fn per (batch, temperature, total-length) bucket.
    fns: Dict[Tuple[int, float, int], object] = {}
    lock = threading.Lock()

    def get_fn(batch: int, temperature: float, total: int = 0):
        """One jitted fn per (batch, temperature, total-length) bucket.
        `total` defaults to the engine's full capacity; /generate_text
        passes a smaller bucket so a 4-token completion does not pay
        for a full-buffer decode scan."""
        if total <= 0:
            total = (spec_total
                     if args.speculative > 0 and temperature == 0.0
                     else args.max_total_len)
        key = (batch, temperature, total)
        with lock:
            if key not in fns:
                if args.speculative > 0 and temperature == 0.0:
                    fns[key] = gen.make_speculative_generate_fn(
                        model, total, draft_k=args.speculative)
                else:
                    fns[key] = gen.make_generate_fn(
                        model, total, temperature=temperature)
            return fns[key]

    rng_holder = {'rng': jax.random.PRNGKey(0)}
    # Live POSTs (graceful drain waits on this, covering the window
    # between accept and engine submit and the one-shot engine).
    _inflight = {'n': 0}
    _inflight_lock = threading.Lock()

    class Handler(BaseHTTPRequestHandler):

        def log_message(self, *a):  # quiet
            pass

        def _json(self, obj, code=200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header('Content-Type', 'application/json')
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802
            if self.path in ('/stats', '/v1/stats'):
                self._stats()
                return
            # Advertise the MINIMUM capacity across request classes
            # (greedy requests run through the speculative engine at
            # spec_total; sampled ones at max_total_len) — clients
            # sizing prompts off this can never be rejected.
            self._json({'status': 'ok',
                        'model': (f'hf:{os.path.basename(args.hf)}'
                                  if args.hf else args.model),
                        'vocab_size': vocab_size,
                        'max_total_len': spec_total
                        if args.speculative > 0 else args.max_total_len})

        def _stats(self):
            """Engine observability (the vLLM /metrics idea, JSON):
            slot occupancy, page pool, prefix-cache hit rate, and
            speculation quality (tokens committed per model call)."""
            if engine is None:
                self._json({'engine': 'simple'})
                return
            body = {
                'engine': 'continuous',
                'num_slots': engine.num_slots,
                'active_slots': int(engine.active.sum()),
                'queued': engine._queue.qsize() + len(engine._ready),
                'decode_calls': engine.decode_calls,
                'tokens_committed': engine.tokens_committed,
                'tokens_per_call': round(
                    engine.tokens_committed /
                    max(engine.decode_calls, 1), 3),
                'speculative_k': engine.spec_k,
            }
            if engine.paged:
                body['page_pool'] = {
                    'total': engine.total_pages,
                    'free': engine.allocator.free_pages,
                }
                if engine.prefix_cache is not None:
                    pc = engine.prefix_cache
                    body['prefix_cache'] = {
                        'hits': pc.hits,
                        'misses': pc.misses,
                        'hit_rate': round(
                            pc.hits / max(pc.hits + pc.misses, 1), 3),
                        'resident_unreferenced': len(pc.lru),
                    }
            self._json(body)

        def do_POST(self):  # noqa: N802
            with _inflight_lock:
                _inflight['n'] += 1
            try:
                self._do_post()
            finally:
                with _inflight_lock:
                    _inflight['n'] -= 1

        def _do_post(self):
            if self.path == '/v1/completions':
                self._openai_completions()
                return
            if self.path == '/v1/chat/completions':
                self._openai_chat()
                return
            if self.path in ('/generate_text', '/v1/generate_text'):
                self._generate_text()
                return
            if self.path not in ('/generate', '/v1/generate'):
                self._json({'error': 'POST /generate, /generate_text, '
                                     'or /v1/completions'}, 404)
                return
            try:
                length = int(self.headers.get('Content-Length', 0))
                req = json.loads(self.rfile.read(length))
                tokens = req['tokens']
                temperature = float(req.get('temperature', 0.0))
                top_k = int(req.get('top_k', 0))
                top_p = float(req.get('top_p', 1.0))
                stop_ids = [int(t) for t in
                            req.get('stop_token_ids', [])]
                if engine is not None:
                    # Ragged rows welcome: each joins the shared decode
                    # loop independently, honoring its temperature.
                    max_new = int(req.get('max_new_tokens',
                                          engine_total))
                    for row in tokens:
                        if len(row) >= engine_total:
                            raise ValueError(
                                f'prompt len {len(row)} >= max_total_len '
                                f'{engine_total}')
                    futs = [engine.submit([int(t) for t in row],
                                          max_new_tokens=max_new,
                                          temperature=temperature,
                                          top_k=top_k, top_p=top_p,
                                          stop_token_ids=stop_ids)
                            for row in tokens]
                    self._json({'tokens':
                                [f.result(timeout=600) for f in futs]})
                    return
                prompt = jnp.asarray(tokens, jnp.int32)
                if prompt.ndim != 2:
                    raise ValueError('tokens must be [batch, prompt_len]')
                # The speculative engine serves greedy requests with a
                # clamped total length; validate against what will
                # actually run, not the CLI flag.
                limit = (spec_total
                         if args.speculative > 0 and temperature == 0.0
                         else args.max_total_len)
                if prompt.shape[1] >= limit:
                    raise ValueError(
                        f'prompt len {prompt.shape[1]} >= max_total_len '
                        f'{limit}')
                fn = get_fn(prompt.shape[0], temperature)
                with lock:
                    rng_holder['rng'], sub = jax.random.split(
                        rng_holder['rng'])
                out = fn(params, prompt, sub)
                self._json({'tokens': jax.device_get(out).tolist()})
            except Exception as e:  # pylint: disable=broad-except
                self._json({'error': f'{type(e).__name__}: {e}'}, 400)

        def _openai_chat(self):
            """OpenAI chat completions: renders `messages` through the
            tokenizer's chat template when the checkpoint ships one,
            else a plain `role: content` fallback template, then runs
            the completions path and wraps the answer as an assistant
            message."""
            try:
                tok = get_tokenizer()
                length = int(self.headers.get('Content-Length', 0))
                req = json.loads(self.rfile.read(length))
                messages = req['messages']
                try:
                    prompt = tok.apply_chat_template(
                        messages, tokenize=False,
                        add_generation_prompt=True)
                except Exception:  # pylint: disable=broad-except
                    # No template in the checkpoint: a transparent
                    # fallback beats a 400 for base models.
                    prompt = '\n'.join(
                        f"{m['role']}: {m['content']}"
                        for m in messages) + '\nassistant:'
                out = self._complete(
                    prompts=[prompt],
                    max_new=int(req.get('max_tokens', 16)),
                    temperature=float(req.get('temperature', 1.0)),
                    top_p=float(req.get('top_p', 1.0)),
                    stop_strings=req.get('stop') or [],
                    n=int(req.get('n', 1)),
                    stream=bool(req.get('stream')))
                out['object'] = 'chat.completion'
                for c in out['choices']:
                    c['message'] = {'role': 'assistant',
                                    'content': c.pop('text')}
                self._json(out)
            except Exception as e:  # pylint: disable=broad-except
                self._json({'error': {
                    'message': f'{type(e).__name__}: {e}',
                    'type': 'invalid_request_error'}}, 400)

        def _complete(self, prompts, max_new, temperature, top_p,
                      stop_strings, n, stream):
            """Shared body of the OpenAI shims: run the prompts,
            return the completions-shaped response dict."""
            tok = get_tokenizer()
            if n != 1:
                raise ValueError('n > 1 is not supported')
            if stream:
                raise ValueError('stream=true is not supported')
            if isinstance(stop_strings, str):
                stop_strings = [stop_strings]
            encoded = [tok(p)['input_ids'] for p in prompts]
            limit = (engine_total if engine is not None
                     else args.max_total_len)
            for ids in encoded:
                if len(ids) >= limit:
                    raise ValueError(
                        f'prompt tokenizes to {len(ids)} >= '
                        f'max_total_len {limit}')
            rows = []
            if engine is not None:
                futs = [engine.submit(ids, max_new_tokens=max_new,
                                      temperature=temperature,
                                      top_p=top_p)
                        for ids in encoded]
                rows = [f.result(timeout=600) for f in futs]
            else:
                for ids in encoded:
                    want = len(ids) + max_new
                    bucket = 8
                    while bucket < want:
                        bucket *= 2
                    bucket = min(bucket, limit)
                    fn = get_fn(1, temperature, bucket)
                    with lock:
                        rng_holder['rng'], sub = jax.random.split(
                            rng_holder['rng'])
                    out = fn(params,
                             jnp.asarray([ids], jnp.int32), sub)
                    rows.append(jax.device_get(out)[0]
                                [:min(want, bucket)].tolist())
            choices = []
            total_completion = 0
            for i, (ids, row) in enumerate(zip(encoded, rows)):
                text = tok.decode(row[len(ids):],
                                  skip_special_tokens=True)
                finish = ('length' if len(row) - len(ids) >= max_new
                          else 'stop')
                for ss in stop_strings:
                    cut = text.find(ss)
                    if cut != -1:
                        text = text[:cut]
                        finish = 'stop'
                total_completion += len(row) - len(ids)
                choices.append({'index': i, 'text': text,
                                'finish_reason': finish,
                                'logprobs': None})
            total_prompt = sum(len(ids) for ids in encoded)
            return {
                'object': 'text_completion',
                'model': (f'hf:{os.path.basename(args.hf)}'
                          if args.hf else args.model),
                'choices': choices,
                'usage': {
                    'prompt_tokens': total_prompt,
                    'completion_tokens': total_completion,
                    'total_tokens': total_prompt + total_completion,
                },
            }

        def _openai_completions(self):
            """OpenAI-compatible completions shim: the de-facto
            client contract (the reference's llm/ recipes serve vLLM,
            whose clients speak this). Maps prompt/max_tokens/
            temperature/top_p/stop onto the engine and returns the
            OpenAI response shape (choices/usage). Requires tokenizer
            files (--hf with a full checkpoint repo)."""
            try:
                length = int(self.headers.get('Content-Length', 0))
                req = json.loads(self.rfile.read(length))
                prompts = req.get('prompt', '')
                if isinstance(prompts, str):
                    prompts = [prompts]
                self._json(self._complete(
                    prompts=prompts,
                    max_new=int(req.get('max_tokens', 16)),
                    temperature=float(req.get('temperature', 1.0)),
                    top_p=float(req.get('top_p', 1.0)),
                    stop_strings=req.get('stop') or [],
                    n=int(req.get('n', 1)),
                    stream=bool(req.get('stream'))))
            except Exception as e:  # pylint: disable=broad-except
                self._json({'error': {
                    'message': f'{type(e).__name__}: {e}',
                    'type': 'invalid_request_error'}}, 400)

        def _generate_text(self):
            """Text in / text out, via the --hf checkpoint's tokenizer:
            {"prompts": ["..."], "max_new_tokens": N, "temperature": t}
            -> {"texts": ["..."]}. Each prompt runs independently
            (continuous-batching engine when enabled, else batch-1
            one-shot calls)."""
            try:
                tok = get_tokenizer()
                length = int(self.headers.get('Content-Length', 0))
                req = json.loads(self.rfile.read(length))
                prompts = req['prompts']
                if isinstance(prompts, str):
                    prompts = [prompts]
                temperature = float(req.get('temperature', 0.0))
                top_k = int(req.get('top_k', 0))
                top_p = float(req.get('top_p', 1.0))
                stop_strings = req.get('stop') or []
                if isinstance(stop_strings, str):
                    stop_strings = [stop_strings]
                max_new = int(req.get('max_new_tokens', 64))
                encoded = [tok(p)['input_ids'] for p in prompts]
                limit = (engine_total if engine is not None else
                         (spec_total
                          if args.speculative > 0 and temperature == 0.0
                          else args.max_total_len))
                for ids in encoded:
                    if len(ids) >= limit:
                        raise ValueError(
                            f'prompt tokenizes to {len(ids)} >= '
                            f'max_total_len {limit}')
                if engine is not None:
                    futs = [engine.submit(ids, max_new_tokens=max_new,
                                          temperature=temperature,
                                          top_k=top_k, top_p=top_p)
                            for ids in encoded]
                    rows = [f.result(timeout=600) for f in futs]
                else:
                    rows = []
                    for ids in encoded:
                        # Power-of-two total-length bucket: a 4-token
                        # completion must not pay a full-buffer decode
                        # scan; bounded bucket count limits recompiles.
                        want = len(ids) + max_new
                        bucket = 8
                        while bucket < want:
                            bucket *= 2
                        bucket = min(bucket, limit)
                        fn = get_fn(1, temperature, bucket)
                        with lock:
                            rng_holder['rng'], sub = jax.random.split(
                                rng_holder['rng'])
                        out = fn(params,
                                 jnp.asarray([ids], jnp.int32), sub)
                        stop = min(want, bucket)
                        rows.append(jax.device_get(out)[0][:stop]
                                    .tolist())
                texts = [tok.decode(row[len(ids):],
                                    skip_special_tokens=True)
                         for ids, row in zip(encoded, rows)]
                if stop_strings:
                    # Trim each completion at the FIRST occurrence of
                    # any stop string (the string itself excluded —
                    # the OpenAI-style `stop` contract).
                    def trim(text):
                        cut = len(text)
                        for ss in stop_strings:
                            i = text.find(ss)
                            if i != -1:
                                cut = min(cut, i)
                        return text[:cut]
                    texts = [trim(t) for t in texts]
                self._json({'texts': texts})
            except Exception as e:  # pylint: disable=broad-except
                self._json({'error': f'{type(e).__name__}: {e}'}, 400)

    server = ThreadingHTTPServer(('0.0.0.0', args.port), Handler)

    _term = threading.Event()

    def _drain_loop():
        """Graceful drain on SIGTERM (rolling updates / replica
        replacement): let the accept loop pick up stragglers briefly,
        stop accepting, wait for in-flight POSTs (bounded), exit 0 —
        a mid-generation client must not see a reset because the
        controller culled this replica. All work happens on this
        pre-started thread; the signal handler only sets an event
        (anything heavier in the signal frame proved crash-prone
        against the XLA runtime's own thread machinery)."""
        _term.wait()
        print('serve_lm: SIGTERM — draining in-flight requests',
              flush=True)
        time.sleep(0.5)     # stragglers: normal accept loop gets them
        server.shutdown()   # stops accepting; handlers keep running
        deadline = time.time() + 60
        while time.time() < deadline:
            with _inflight_lock:
                if _inflight['n'] == 0:
                    break
            time.sleep(0.2)
        if engine is not None:
            engine.stop()
        os._exit(0)

    import signal
    import time
    threading.Thread(target=_drain_loop, daemon=True).start()
    signal.signal(signal.SIGTERM, lambda *_: _term.set())
    print(f'serve_lm listening on :{args.port} model={args.model}',
          flush=True)
    server.serve_forever()


if __name__ == '__main__':
    main()
