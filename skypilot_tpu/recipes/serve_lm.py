"""In-framework LM inference server: the payload of serve replicas.

Thin CLI over `skypilot_tpu.inference` (runtime construction in
inference/runtime.py, HTTP + SSE streaming in inference/http_server.py,
OpenAI shims in inference/openai_compat.py). A JetStream-shaped HTTP
server: GET / (readiness), POST /generate {"tokens": [[...]],
"max_new_tokens": N, "temperature": t, "top_k": k, "top_p": p} →
{"tokens": [[...]]}, plus /generate_text and OpenAI-compatible
/v1/completions + /v1/chat/completions with SSE streaming
(`"stream": true`) and n>1, plus observability endpoints: GET /stats
(JSON rolling-window snapshot) and GET /metrics (Prometheus text —
engine internals + request-path histograms; metric catalog in
docs/guides.md). Listens on SKYPILOT_SERVE_PORT (injected
by the serve controller). Two engines:

  - default: one jitted fixed-shape generate fn per batch bucket
    (models/generate.py) — simplest, one request at a time (streaming
    requests ride a small lazily-built slot engine);
  - --continuous-batching: the slot-based engine
    (models/batching.py) — concurrent requests share the decode
    loop, joining and leaving without draining the batch (the
    throughput mode under ragged request lengths).

  stpu serve up -y -n llama task.yaml   # run: python -m
      skypilot_tpu.recipes.serve_lm --model llama-tiny
"""
from __future__ import annotations

import argparse
import os


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='llama-tiny')
    parser.add_argument('--hf', default=None, metavar='DIR',
                        help='serve a HuggingFace checkpoint from a '
                             'local directory (e.g. the target of an '
                             'hf:// storage COPY): weights are '
                             'converted in-process '
                             '(models/hf_import.py) and --model is '
                             'ignored; if tokenizer files are present, '
                             'POST /generate_text serves text in/out')
    parser.add_argument('--ckpt-dir', default=None,
                        help='orbax checkpoint to load weights from')
    parser.add_argument('--max-total-len', type=int, default=256)
    parser.add_argument('--continuous-batching', action='store_true',
                        help='slot-based engine: concurrent requests '
                             'share the decode loop')
    parser.add_argument('--num-slots', type=int, default=8)
    parser.add_argument('--decode-chunk', type=int, default=1,
                        metavar='N',
                        help='continuous engine: N decode steps per '
                             'jitted dispatch (lax.scan) — outputs '
                             'identical to step-by-step; amortizes '
                             'per-dispatch host overhead (the serving '
                             'analog of the trainer multi-step). '
                             'Trade-off: up to N-1 wasted steps per '
                             'finishing request, admission at chunk '
                             'boundaries. Exclusive with --speculative')
    parser.add_argument('--prefill-chunk', type=int, default=256,
                        metavar='C',
                        help='continuous engine: chunked prefill — '
                             'admitted prompts prefill in C-token '
                             'chunks interleaved with decode steps '
                             '(one compiled shape instead of a log2 '
                             'bucket ladder), so one long prompt '
                             'cannot stall every active decode slot. '
                             '0 = whole-prompt prefill (the legacy '
                             'synchronous path)')
    parser.add_argument('--prefill-budget', type=int, default=0,
                        metavar='T',
                        help='max prefill tokens run per scheduler '
                             'iteration (chunked prefill only). '
                             'Default 0 = one chunk per iteration — '
                             'maximal decode interleaving; raise it '
                             'to favor time-to-first-token over '
                             'inter-token latency')
    parser.add_argument('--no-pipeline-decode', action='store_true',
                        help='disable one-step host/device decode '
                             'pipelining (dispatch round N+1 before '
                             'committing round N). On by default for '
                             'the plain decode loop; greedy outputs '
                             'are identical either way')
    parser.add_argument('--speculative', type=int, default=0,
                        metavar='K',
                        help='prompt-lookup speculative decoding with K '
                             'drafted tokens per step. One-shot engine: '
                             'greedy requests, exact greedy outputs. '
                             'Continuous batching: every slot rides '
                             'verify chunks (greedy exact; sampled '
                             'stays unbiased via match-acceptance)')
    parser.add_argument('--port', type=int,
                        default=int(os.environ.get('SKYPILOT_SERVE_PORT',
                                                   8000)))
    parser.add_argument('--zone', default='',
                        help='placement zone label (spot decode '
                             'pools): scoped into the preemption '
                             'watcher\'s serve.preempt_notice fault '
                             'point, echoed in /stats — a zone-'
                             'scoped storm plan preempts only the '
                             'replicas carrying the zone')
    parser.add_argument('--tensor', type=int, default=1,
                        help='tensor-parallel serving over N devices: '
                             'params shard per the training rules '
                             '(heads/mlp/vocab over the tensor axis) '
                             'and XLA propagates the sharding through '
                             'every serving fn — models bigger than '
                             'one chip serve across the slice. The '
                             'KV page pool shards its kv-heads axis '
                             'too (when N divides the head count), '
                             'so N chips hold ~Nx the pages at fixed '
                             'per-chip --kv-pool-bytes')
    parser.add_argument('--stages', type=int, default=1,
                        help='pipeline-parallel serving over S stages: '
                             'the layer stack splits into S contiguous '
                             'ranges, each placed on its own tensor '
                             'submesh of a (stage, tensor) mesh — '
                             'total chips = S x --tensor. Prefill '
                             'streams chunk microbatches through the '
                             'stage chain; decode keeps S slot groups '
                             'in flight so every stage works each '
                             'step. Each stage\'s KV pool holds only '
                             'its own layers\' pages, so the pool '
                             'scales ~S x --tensor ways at fixed '
                             'per-chip --kv-pool-bytes. Needs '
                             '--continuous-batching; does not compose '
                             'with --weight-dtype int8 or '
                             '--decode-chunk > 1')
    parser.add_argument('--adapter-dir', default=None, metavar='DIR',
                        help='multi-LoRA serving: a local or gs:// '
                             'directory of adapter artifacts '
                             '(<name>/adapter_config.json + weights, '
                             'the train_lm --lora output). The '
                             '`model` field on /v1/* and /generate* '
                             'selects an adapter by name; adapters '
                             'hot-load on first use and LRU-evict '
                             'under the --max-adapters device budget')
    parser.add_argument('--max-adapters', type=int, default=8,
                        metavar='N',
                        help='device-resident adapter slots in the '
                             'stacked LoRA store (memory = N x '
                             'per-adapter factor bytes; see '
                             'docs/guides.md "Multi-LoRA serving")')
    parser.add_argument('--max-lora-rank', type=int, default=0,
                        metavar='R',
                        help='store rank ceiling (smaller-rank '
                             'adapters zero-pad). 0 = the max rank '
                             'seen in --adapter-dir at startup; set '
                             'it explicitly if bigger-rank adapters '
                             'will be hot-dropped in later')
    parser.add_argument('--no-prefix-caching', action='store_true',
                        help='disable shared-prefix KV page reuse '
                             '(vLLM-style APC; on by default with the '
                             'paged cache — repeated system prompts '
                             'skip recomputation and share pool pages)')
    parser.add_argument('--kv-dtype', choices=['bf16', 'int8'],
                        default='bf16',
                        help='KV page-pool storage format. int8 '
                             'stores quantized pages + per-page-slot '
                             'f32 scales (quantize on write, dequant '
                             'inside attention): ~2x decode slots and '
                             'prefix-cache residency per HBM byte, '
                             'quality pinned by the logprob-tolerance '
                             'contract (docs/guides.md "Quantized '
                             'serving"). Needs --continuous-batching')
    parser.add_argument('--kv-pool-bytes', type=int, default=0,
                        metavar='B',
                        help='size the KV page pool by PER-CHIP '
                             'device bytes instead of the model '
                             'default page count: kv_total_pages = '
                             'B // per-page-per-chip bytes under '
                             '--kv-dtype and --tensor, so a bf16 vs '
                             'int8 A/B at the same B spends the same '
                             'HBM (int8 buys ~2x the pages) and an '
                             'N-chip mesh with the kv-heads axis '
                             'sharded holds ~Nx the TOTAL pages at '
                             'the same per-chip spend. 0 = '
                             'model-default page count')
    parser.add_argument('--weight-dtype', choices=['bf16', 'int8'],
                        default='bf16',
                        help='serving storage for the projection '
                             'weights (wq/wk/wv/wo, w_gate/w_up/'
                             'w_down). int8 = per-output-channel '
                             'symmetric quantization, dequantized on '
                             'read inside the jitted fns — halves '
                             'weight-streaming HBM bandwidth; '
                             'embeddings/norms/head stay bf16. '
                             'Composes with --tensor (scales shard '
                             'with their channel) and LoRA (deltas '
                             'ride the dequantized base)')
    parser.add_argument('--param-dtype', choices=['bf16', 'f32'],
                        default='bf16',
                        help='on-device dtype for --hf weights. bf16 '
                             '(default) halves HBM vs f32; compute '
                             'already runs in bf16 either way. Models '
                             'bigger than one chip serve with '
                             '--tensor N (sharded across the slice). '
                             'f32 is for CPU parity runs')
    parser.add_argument('--role', choices=['', 'prefill', 'decode'],
                        default='',
                        help='disaggregated serving role. "prefill": '
                             'this replica prefills prompts and hands '
                             'the KV page chain off to a decode peer '
                             '(POST /kv/import) instead of decoding '
                             'locally, falling back to local serving '
                             'when the transfer fails; "decode": '
                             'label only (pool membership for the '
                             'fleet controller / LB). Default: '
                             'unified replica. prefill needs '
                             '--continuous-batching')
    parser.add_argument('--decode-peers', default=None,
                        metavar='HOST:PORT,...',
                        help='static decode pool for --role prefill '
                             '(the fleet controller pushes the live '
                             'set via POST /kv/peers instead)')
    parser.add_argument('--kv-spill-bytes', type=int, default=0,
                        metavar='B',
                        help='tiered prefix cache: spill evicted KV '
                             'pages (payload + scales + chain key) '
                             'into a host-RAM LRU of at most B bytes '
                             'instead of dropping them; a later '
                             'chain-key hit restores the exact bytes '
                             '(bit-identical to fresh compute). 0 = '
                             'off. Needs --continuous-batching')
    parser.add_argument('--kv-cold-dir', default=None, metavar='DIR',
                        help='cold tier behind --kv-spill-bytes: '
                             'pages LRU-evicted from host RAM land '
                             'in DIR (local path or gs:// prefix) '
                             'and survive process restarts — meant '
                             'for giant shared system prompts')
    parser.add_argument('--drain-grace', type=float, default=630.0,
                        help='SIGTERM drain: seconds to wait for '
                             'in-flight requests before exiting. The '
                             'default exceeds the request-timeout '
                             'default so a worst-case generation still '
                             'completes; requests outliving the grace '
                             'window are dropped at exit')
    parser.add_argument('--request-timeout', type=float, default=600.0,
                        help='per-request deadline ceiling, seconds: '
                             'requests carrying a smaller `timeout` '
                             'body field use that, anything else (and '
                             'anything larger) is clamped here. '
                             'Expired requests are reaped mid-decode '
                             'and answered 504')
    parser.add_argument('--max-queue-requests', type=int, default=0,
                        metavar='N',
                        help='admission control: shed (429 + '
                             'Retry-After) once N requests are '
                             'waiting for a decode slot. 0 = '
                             'unbounded (the pre-hardening behavior)')
    parser.add_argument('--max-queue-tokens', type=int, default=0,
                        metavar='T',
                        help='admission control: shed once the queued '
                             'prompts hold T tokens (a token-aware '
                             'bound sheds one 4k-prompt instead of '
                             'forty short ones). 0 = unbounded')
    parser.add_argument('--fault-plan', default=None, metavar='JSON',
                        help='chaos testing: a fault plan (inline '
                             'JSON or a path to a JSON file) arming '
                             'the skypilot_tpu.robustness.faults '
                             'injection points in this process; see '
                             'docs/guides.md "Serving robustness". '
                             'Equivalent to the STPU_FAULT_PLAN env '
                             'var. Never set this in production')
    parser.add_argument('--trace-sample', type=float, default=0.0,
                        metavar='P',
                        help='distributed request tracing: sample '
                             'this fraction of requests (0..1) into '
                             'Chrome-trace spans, served at GET '
                             '/debug/trace/<id> and merged across '
                             'processes by `stpu trace`. Requests '
                             'arriving with an x-skypilot-trace '
                             'header are always traced (the caller '
                             'already paid the sampling decision). '
                             '0 = off (zero overhead)')
    parser.add_argument('--trace-seed', type=int, default=None,
                        help='seed the trace sampler: the sampled '
                             'set and its ids become reproducible')
    parser.add_argument('--slo', default=None, metavar='SPEC',
                        help='declarative serving SLOs, e.g. '
                             '"p99_ttft_ms=500,p99_itl_ms=100,'
                             'error_rate=0.01,shed_rate=0.05": '
                             '/stats grows an `slo` section with '
                             'multi-window burn rates and the '
                             'skypilot_serving_slo_* gauges go live '
                             '(docs/guides.md "Tracing & SLOs")')
    parser.add_argument('--cpu', action='store_true',
                        help='pin the CPU backend (smoke/dev runs; the '
                             'JAX_PLATFORMS env var is overridden by '
                             'some TPU plugins, jax.config is not)')
    args = parser.parse_args()
    if args.slo:
        # Fail fast at startup, not at first scrape.
        from skypilot_tpu.observability import slo as slo_lib
        try:
            slo_lib.parse_slo(args.slo)
        except ValueError as e:
            parser.error(str(e))
    if args.decode_chunk > 1 and not args.continuous_batching:
        parser.error('--decode-chunk is a continuous-engine knob; '
                     'add --continuous-batching (the one-shot engine '
                     'would silently ignore it)')
    if args.kv_dtype == 'int8' and not args.continuous_batching:
        parser.error('--kv-dtype int8 requires --continuous-batching '
                     '(the one-shot engine decodes through the dense '
                     'per-slot cache, which has no scale storage)')
    if (args.kv_spill_bytes or args.kv_cold_dir) and \
            not args.continuous_batching:
        parser.error('--kv-spill-bytes/--kv-cold-dir require '
                     '--continuous-batching (the spill tier stores '
                     'evicted prefix-cache pages of the paged slot '
                     'engine)')
    if args.role == 'prefill' and not args.continuous_batching:
        parser.error('--role prefill requires --continuous-batching '
                     '(the handoff exports KV page chains from the '
                     'slot engine\'s prefix cache)')
    if args.stages > 1:
        if not args.continuous_batching:
            parser.error('--stages requires --continuous-batching '
                         '(pipeline serving runs the paged slot '
                         'engine; the one-shot path has no microbatch '
                         'stream to fill the stage bubble)')
        if args.weight_dtype == 'int8':
            parser.error('--stages does not compose with '
                         '--weight-dtype int8 (the quantized wrapper '
                         'has no per-stage split; use int8 KV pages '
                         'via --kv-dtype int8 instead)')
        if args.decode_chunk > 1:
            parser.error('--stages does not compose with '
                         '--decode-chunk > 1 (the in-flight group '
                         'ring feeds one token per slot per round)')
        if args.num_slots % args.stages != 0:
            parser.error(f'--num-slots {args.num_slots} must divide '
                         f'evenly into --stages {args.stages} slot '
                         f'groups (the decode ring assigns '
                         f'num_slots/stages slots per group)')

    if args.fault_plan:
        from skypilot_tpu.robustness import faults
        faults.install_plan(args.fault_plan)
        print(f'serve_lm: FAULT PLAN ARMED '
              f'({sorted(faults.stats())}) — chaos mode', flush=True)

    from skypilot_tpu.inference.http_server import serve
    from skypilot_tpu.inference.runtime import build_runtime
    serve(build_runtime(args), args.port,
          drain_grace=args.drain_grace, zone=args.zone)


if __name__ == '__main__':
    main()
