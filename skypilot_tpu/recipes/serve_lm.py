"""In-framework LM inference server: the payload of serve replicas.

A JetStream-shaped HTTP server: GET / (readiness), POST /generate
{"tokens": [[...]], "max_new_tokens": N, "temperature": t} →
{"tokens": [[...]]}. Listens on SKYPILOT_SERVE_PORT (injected by the
serve controller). Two engines:

  - default: one jitted fixed-shape generate fn per batch bucket
    (models/generate.py) — simplest, one request at a time;
  - --continuous-batching: the slot-based engine
    (models/batching.py) — concurrent requests share the decode
    loop, joining and leaving without draining the batch (the
    throughput mode under ragged request lengths).

  stpu serve up -y -n llama task.yaml   # run: python -m
      skypilot_tpu.recipes.serve_lm --model llama-tiny
"""
from __future__ import annotations

import argparse
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Tuple


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='llama-tiny')
    parser.add_argument('--ckpt-dir', default=None,
                        help='orbax checkpoint to load weights from')
    parser.add_argument('--max-total-len', type=int, default=256)
    parser.add_argument('--continuous-batching', action='store_true',
                        help='slot-based engine: concurrent requests '
                             'share the decode loop')
    parser.add_argument('--num-slots', type=int, default=8)
    parser.add_argument('--speculative', type=int, default=0,
                        metavar='K',
                        help='greedy prompt-lookup speculative decoding '
                             'with K drafted tokens per step (one-shot '
                             'engine only; exact greedy outputs)')
    parser.add_argument('--port', type=int,
                        default=int(os.environ.get('SKYPILOT_SERVE_PORT',
                                                   8000)))
    parser.add_argument('--cpu', action='store_true',
                        help='pin the CPU backend (smoke/dev runs; the '
                             'JAX_PLATFORMS env var is overridden by '
                             'some TPU plugins, jax.config is not)')
    args = parser.parse_args()

    import flax.linen as nn
    import jax
    if args.cpu:
        jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp

    from skypilot_tpu.models import generate as gen
    from skypilot_tpu.recipes.train_lm import _build_model

    model, vocab_size, _ = _build_model(args.model, args.max_total_len,
                                        remat=False)
    # Speculative decoding writes its verify chunk up to K tokens past
    # the last kept one; fail fast / clamp at STARTUP instead of
    # erroring inside every request handler
    # (models/generate.py make_speculative_generate_fn asserts
    # max_total_len + K <= model.config.max_seq_len).
    spec_total = args.max_total_len
    if args.speculative > 0:
        spec_total = min(args.max_total_len,
                         model.config.max_seq_len - args.speculative)
        if spec_total <= 1:
            parser.error(
                f'--speculative {args.speculative} needs headroom in '
                f'the model context: max_seq_len='
                f'{model.config.max_seq_len} leaves no room for the '
                f'verify chunk. Use a smaller K or a longer-context '
                f'model.')
        if spec_total < args.max_total_len:
            print(f'speculative decoding: clamping max_total_len '
                  f'{args.max_total_len} -> {spec_total} (verify chunk '
                  f'needs K={args.speculative} tokens of headroom '
                  f'below max_seq_len={model.config.max_seq_len})',
                  flush=True)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0),
        jnp.ones((1, 8), jnp.int32))['params'])
    if args.ckpt_dir:
        from skypilot_tpu.parallel.checkpoints import CheckpointManager
        mgr = CheckpointManager(args.ckpt_dir)
        if mgr.latest_step() is not None:
            from skypilot_tpu.parallel.train import TrainState
            import optax
            template = TrainState.create(params, optax.sgd(1e-3))
            params = mgr.restore(template).params
            print(f'loaded checkpoint step {mgr.latest_step()}', flush=True)

    engine = None
    if args.continuous_batching:
        from skypilot_tpu.models.batching import ContinuousBatchingEngine
        engine = ContinuousBatchingEngine(
            model, params, num_slots=args.num_slots,
            max_total_len=args.max_total_len)

    # One jitted fn per (batch, temperature) bucket.
    fns: Dict[Tuple[int, float], object] = {}
    lock = threading.Lock()

    def get_fn(batch: int, temperature: float):
        key = (batch, temperature)
        with lock:
            if key not in fns:
                if args.speculative > 0 and temperature == 0.0:
                    fns[key] = gen.make_speculative_generate_fn(
                        model, spec_total,
                        draft_k=args.speculative)
                else:
                    fns[key] = gen.make_generate_fn(
                        model, args.max_total_len,
                        temperature=temperature)
            return fns[key]

    rng_holder = {'rng': jax.random.PRNGKey(0)}

    class Handler(BaseHTTPRequestHandler):

        def log_message(self, *a):  # quiet
            pass

        def _json(self, obj, code=200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header('Content-Type', 'application/json')
            self.send_header('Content-Length', str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802
            # Advertise the SPECULATIVE capacity when that engine will
            # serve greedy requests — clients size prompts off this.
            self._json({'status': 'ok', 'model': args.model,
                        'vocab_size': vocab_size,
                        'max_total_len': spec_total
                        if args.speculative > 0 else args.max_total_len})

        def do_POST(self):  # noqa: N802
            if self.path not in ('/generate', '/v1/generate'):
                self._json({'error': 'POST /generate'}, 404)
                return
            try:
                length = int(self.headers.get('Content-Length', 0))
                req = json.loads(self.rfile.read(length))
                tokens = req['tokens']
                temperature = float(req.get('temperature', 0.0))
                if engine is not None:
                    # Ragged rows welcome: each joins the shared decode
                    # loop independently, honoring its temperature.
                    max_new = int(req.get('max_new_tokens',
                                          args.max_total_len))
                    for row in tokens:
                        if len(row) >= args.max_total_len:
                            raise ValueError(
                                f'prompt len {len(row)} >= max_total_len '
                                f'{args.max_total_len}')
                    futs = [engine.submit([int(t) for t in row],
                                          max_new_tokens=max_new,
                                          temperature=temperature)
                            for row in tokens]
                    self._json({'tokens':
                                [f.result(timeout=600) for f in futs]})
                    return
                prompt = jnp.asarray(tokens, jnp.int32)
                if prompt.ndim != 2:
                    raise ValueError('tokens must be [batch, prompt_len]')
                # The speculative engine serves greedy requests with a
                # clamped total length; validate against what will
                # actually run, not the CLI flag.
                limit = (spec_total
                         if args.speculative > 0 and temperature == 0.0
                         else args.max_total_len)
                if prompt.shape[1] >= limit:
                    raise ValueError(
                        f'prompt len {prompt.shape[1]} >= max_total_len '
                        f'{limit}')
                fn = get_fn(prompt.shape[0], temperature)
                with lock:
                    rng_holder['rng'], sub = jax.random.split(
                        rng_holder['rng'])
                out = fn(params, prompt, sub)
                self._json({'tokens': jax.device_get(out).tolist()})
            except Exception as e:  # pylint: disable=broad-except
                self._json({'error': f'{type(e).__name__}: {e}'}, 400)

    server = ThreadingHTTPServer(('0.0.0.0', args.port), Handler)
    print(f'serve_lm listening on :{args.port} model={args.model}',
          flush=True)
    server.serve_forever()


if __name__ == '__main__':
    main()
