"""Runnable LM-training recipe: the payload of the example task YAMLs.

Consumes the gang-exec env contract (backends/task_codegen.py):
`jax.distributed.initialize` bootstraps from JAX_COORDINATOR_ADDRESS /
JAX_NUM_PROCESSES / JAX_PROCESS_ID, so `stpu launch` of this script on
a multi-host TPU slice (or multislice) just works. Checkpoints go
through parallel/checkpoints.py (async orbax, GCS-capable) — the
managed-jobs preemption-recovery contract: on relaunch the script
resumes from the latest step in --ckpt-dir.

Usage (see examples/*.yaml):
  python -m skypilot_tpu.recipes.train_lm --model gpt2-124m \
      --steps 100 --seq 1024 --ckpt-dir gs://bucket/ckpts
"""
from __future__ import annotations

import argparse
import os
import sys
import time

from skypilot_tpu.robustness import faults
from skypilot_tpu.robustness import train_guard


def _maybe_init_distributed() -> None:
    num = int(os.environ.get('JAX_NUM_PROCESSES', '1'))
    if num <= 1:
        return
    import jax
    jax.distributed.initialize(
        coordinator_address=os.environ['JAX_COORDINATOR_ADDRESS'],
        num_processes=num,
        process_id=int(os.environ['JAX_PROCESS_ID']))


def _build_model(name: str, seq: int, remat: bool):
    import jax.numpy as jnp
    if name == 'gpt2-124m':
        from skypilot_tpu.models.gpt import GPT, GPTConfig
        cfg = GPTConfig.gpt2_124m(remat=remat)
        return GPT(cfg), cfg.vocab_size, None
    if name == 'tiny':
        from skypilot_tpu.models.gpt import GPT, GPTConfig
        cfg = GPTConfig.tiny(remat=remat)
        return GPT(cfg), cfg.vocab_size, None
    if name == 'llama3-8b':
        from skypilot_tpu.models.llama import Llama, LlamaConfig
        cfg = LlamaConfig.llama3_8b(max_seq_len=max(seq, 2048), remat=remat)
        return Llama(cfg), cfg.vocab_size, None
    if name == 'llama-tiny':
        from skypilot_tpu.models.llama import Llama, LlamaConfig
        cfg = LlamaConfig.tiny(remat=remat)
        if seq > cfg.max_seq_len:
            # Long-context runs on the tiny model (serving benchmarks
            # exercising long-prompt regimes): params are seq-length
            # independent (RoPE is computed from positions), so grow
            # the context and scale the KV page pool to keep the same
            # full-depth slot coverage.
            import dataclasses
            grow = -(-seq // cfg.max_seq_len)
            cfg = dataclasses.replace(
                cfg, max_seq_len=seq,
                kv_total_pages=cfg.kv_total_pages * grow)
        return Llama(cfg), cfg.vocab_size, None
    if name == 'mixtral-8x7b':
        from skypilot_tpu.models.mixtral import (Mixtral, MixtralConfig,
                                                 moe_next_token_loss)
        cfg = MixtralConfig.mixtral_8x7b(remat=remat)
        return Mixtral(cfg), cfg.vocab_size, moe_next_token_loss
    if name == 'mixtral-tiny':
        from skypilot_tpu.models.mixtral import (Mixtral, MixtralConfig,
                                                 moe_next_token_loss)
        cfg = MixtralConfig.tiny(remat=remat)
        return Mixtral(cfg), cfg.vocab_size, moe_next_token_loss
    if name == 'deepseek-v2-lite':
        from skypilot_tpu.models.deepseek import Deepseek, DeepseekConfig
        cfg = DeepseekConfig.v2_lite(max_seq_len=max(seq, 4096),
                                     remat=remat)
        return Deepseek(cfg), cfg.vocab_size, None
    if name == 'deepseek-tiny':
        from skypilot_tpu.models.deepseek import Deepseek, DeepseekConfig
        cfg = DeepseekConfig.tiny(remat=remat)
        return Deepseek(cfg), cfg.vocab_size, None
    if name == 'qwen2-7b':
        from skypilot_tpu.models.llama import Llama, LlamaConfig
        cfg = LlamaConfig(vocab_size=152064, num_layers=28,
                          num_heads=28, num_kv_heads=4,
                          embed_dim=3584, mlp_dim=18944,
                          rope_theta=1e6, norm_eps=1e-6,
                          max_seq_len=max(seq, 2048),
                          qkv_bias=True, remat=remat)
        return Llama(cfg), cfg.vocab_size, None
    if name == 'qwen-tiny':
        from skypilot_tpu.models.llama import Llama, LlamaConfig
        cfg = LlamaConfig.tiny(qkv_bias=True, remat=remat)
        return Llama(cfg), cfg.vocab_size, None
    raise ValueError(f'unknown model {name!r}')


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='gpt2-124m')
    parser.add_argument('--steps', type=int, default=100)
    parser.add_argument('--seq', type=int, default=1024)
    parser.add_argument('--global-batch', type=int, default=0,
                        help='0 = 8 per device')
    parser.add_argument('--data', default='synthetic',
                        help='"synthetic" or a dir/glob of token .bin '
                             'shards')
    parser.add_argument('--ckpt-dir', default=None)
    parser.add_argument('--init-from-hf', default=None, metavar='DIR',
                        help='initialize weights from a local '
                             'HuggingFace checkpoint directory (e.g. '
                             'the target of an hf:// storage COPY) — '
                             'the finetuning path; --model is ignored '
                             'and the architecture comes from the '
                             "checkpoint's config.json "
                             '(models/hf_import.py)')
    parser.add_argument('--ckpt-every', type=int, default=50)
    parser.add_argument('--ckpt-interval', default=None,
                        metavar='auto|SECONDS',
                        help='checkpoint cadence as WALL TIME instead '
                             'of --ckpt-every steps: a number of '
                             'seconds, or "auto" to solve the '
                             'Young/Daly optimum tau* = sqrt(2*delta/'
                             'lambda) from the zone preemption rate '
                             '(--preemption-rate) and the checkpoint '
                             'overhead (--ckpt-overhead) — '
                             'jobs/policy.py. The cadence in steps is '
                             'fixed from the measured mean step time '
                             'of the first logged window and printed')
    parser.add_argument('--preemption-rate', type=float, default=None,
                        metavar='PER_HOUR',
                        help='zone spot preemption rate lambda '
                             '(preemptions/hour) for --ckpt-interval '
                             'auto; default: the '
                             'SKYPILOT_PREEMPTION_RATE_PER_HOUR env '
                             'var (set it in the task env, e.g. from '
                             'the catalog\'s per-zone PreemptionRate '
                             'column)')
    parser.add_argument('--ckpt-overhead', type=float, default=None,
                        metavar='SECONDS',
                        help='checkpoint write overhead delta for '
                             '--ckpt-interval auto (default: '
                             'jobs/policy.DEFAULT_CKPT_OVERHEAD_S, '
                             '60s)')
    parser.add_argument('--guard', action='store_true',
                        help='arm the self-supervising trainer '
                             '(robustness/train_guard.py): preemption'
                             '-notice watcher (GCE metadata + '
                             'SIGTERM) checkpoints NOW and exits '
                             'with the typed code 83 the managed-'
                             'jobs controller maps to recovery; '
                             'on-device NaN/spike guard skips bad '
                             'optimizer steps and rolls back to the '
                             'last checkpoint after --rollback-after '
                             'consecutive ones; a step watchdog '
                             'dumps all thread stacks and aborts '
                             'with code 84 on a hung collective or '
                             'stalled data loader')
    parser.add_argument('--spike-factor', type=float, default=10.0,
                        help='grad-norm spike threshold as a '
                             'multiple of its EMA (guard)')
    parser.add_argument('--guard-warmup', type=int, default=10,
                        help='good steps of EMA warmup before spike '
                             'detection arms (guard)')
    parser.add_argument('--rollback-after', type=int, default=3,
                        help='consecutive bad steps before rolling '
                             'back to the last checkpoint (guard)')
    parser.add_argument('--watchdog-deadline', type=float,
                        default=300.0, metavar='SECONDS',
                        help='per-phase step-watchdog deadline; 0 '
                             'disables the watchdog (guard)')
    parser.add_argument('--watchdog-compile-deadline', type=float,
                        default=1800.0, metavar='SECONDS',
                        help='watchdog deadline for the first step '
                             '(covers XLA compilation)')
    parser.add_argument('--preempt-poll', type=float, default=5.0,
                        metavar='SECONDS',
                        help='preemption-notice metadata poll '
                             'interval (guard)')
    parser.add_argument('--lora', type=int, default=0, metavar='RANK',
                        help='LoRA finetune: freeze the base params '
                             'and train rank-RANK A/B factors on the '
                             'attention (and optionally MLP) '
                             'projections (models/lora.py). The '
                             'trained factors are saved as a serving '
                             'adapter artifact (--adapter-out) that '
                             'serve_lm --adapter-dir loads '
                             'unmodified. Llama-family models only')
    parser.add_argument('--lora-alpha', type=float, default=0.0,
                        help='LoRA alpha (delta scale = alpha/rank); '
                             '0 = alpha = rank (scale 1.0)')
    parser.add_argument('--lora-targets', default='attn',
                        choices=['attn', 'mlp', 'attn-mlp'],
                        help='projections the adapter touches: attn '
                             '(q/k/v/o, the default), mlp '
                             '(gate/up/down), or both')
    parser.add_argument('--adapter-out', default=None, metavar='DIR',
                        help='where --lora writes the adapter '
                             'artifact (adapter_config.json + '
                             'adapter_weights.npz). Default: '
                             '<--ckpt-dir>/adapter, or ./adapter_out '
                             'without a checkpoint dir')
    parser.add_argument('--lr', type=float, default=3e-4)
    parser.add_argument('--tensor', type=int, default=1,
                        help='tensor-parallel mesh axis size')
    parser.add_argument('--expert', type=int, default=1)
    parser.add_argument('--pipeline-stages', type=int, default=1,
                        help='GPipe pipeline parallelism over a stage '
                             'mesh axis (parallel/pipeline.py; '
                             'GPT/Llama/Mixtral/DeepSeek). Composes '
                             'with --tensor/--expert (sharded WITHIN '
                             'each stage) and data parallelism; '
                             'uneven num_layers pads with masked '
                             'identity slots')
    parser.add_argument('--microbatches', type=int, default=0,
                        help='pipeline microbatches (0 = 4 x stages; '
                             'utilization = M / (M + stages - 1))')
    parser.add_argument('--pipeline-schedule', default='gpipe',
                        choices=['gpipe', '1f1b', 'interleaved'],
                        help='pipeline execution schedule (parallel/'
                             'pipeline_schedule.py): gpipe = fused '
                             'fill/drain scan (activation memory '
                             'O(microbatches)); 1f1b = one-forward-'
                             'one-backward, caps live activations at '
                             'O(stages) so microbatches — and with '
                             'them the bubble fraction — can scale; '
                             'interleaved = 1f1b over --virtual-'
                             'stages layer chunks per device, '
                             'dividing the bubble fraction by v')
    parser.add_argument('--virtual-stages', type=int, default=0,
                        help='layer chunks per device for '
                             '--pipeline-schedule interleaved '
                             '(0 = auto: 2 for interleaved, 1 '
                             'otherwise)')
    parser.add_argument('--overlap', action='store_true',
                        help='overlap collectives with compute: adds '
                             "XLA's async-collective latency-hiding "
                             'flags to XLA_FLAGS (TPU; no-op on '
                             '--cpu) and, with --zero1, buckets the '
                             'grad reduce-scatter per parameter leaf '
                             'so it issues as backward produces each '
                             'leaf instead of one fused update after '
                             'the full backward')
    parser.add_argument('--seq-parallel', type=int, default=1,
                        help='context-parallel mesh axis size '
                             '(ring attention)')
    parser.add_argument('--no-fused-xent', action='store_true',
                        help='disable the fused blockwise LM-head '
                             'cross-entropy (ops/fused_xent.py) and '
                             'materialize the full [B,S,V] logits — '
                             'the escape hatch; fused is the default '
                             'whenever the model supports it')
    parser.add_argument('--zero1', action='store_true',
                        help='ZeRO-1: shard optimizer moments (Adam '
                             'm/v) over the data mesh axis — cuts '
                             'per-chip optimizer HBM by the data-'
                             'parallel degree with step-identical '
                             'math (GSPMD reduce-scatters grads into '
                             'the shards and all-gathers updated '
                             'params)')
    parser.add_argument('--remat', action='store_true')
    parser.add_argument('--log-every', type=int, default=10)
    parser.add_argument('--metrics-file', default=None, metavar='PATH',
                        help='append one JSONL record per --log-every '
                             'window: step, step_time_s, '
                             'tokens_per_sec, loss, grad_norm, and an '
                             'achieved-MFU estimate '
                             '(observability/step_metrics.py) — the '
                             'machine-readable twin of the printed '
                             'log line')
    parser.add_argument('--trace-file', default=None, metavar='PATH',
                        help='write a Chrome-trace timeline (load in '
                             'Perfetto) with per-phase spans — init, '
                             'data, step, checkpoint — same format as '
                             'SKYPILOT_TIMELINE_FILE_PATH, enabled '
                             'from the CLI')
    parser.add_argument('--profile', default=None, metavar='DIR',
                        help='capture a jax.profiler trace '
                             '(TensorBoard/Perfetto-readable) of a few '
                             'steady-state steps into DIR — the MFU '
                             'triage tool: fusion gaps, transfer '
                             'stalls, collective overlap all show up '
                             'in the trace')
    parser.add_argument('--profile-steps', default='4:8',
                        metavar='START:STOP',
                        help='step window to trace (after compile; '
                             'default 4:8)')
    parser.add_argument('--cpu', action='store_true',
                        help='pin the CPU backend (smoke/dev runs; the '
                             'JAX_PLATFORMS env var is overridden by '
                             'some TPU plugins, jax.config is not)')
    args = parser.parse_args()

    if args.overlap:
        # XLA reads XLA_FLAGS at backend init — extend it before any
        # device access. CPU adds none: that build aborts on unknown
        # --xla_tpu_* flags (and its collectives hide nothing).
        from skypilot_tpu.parallel.train import overlap_xla_flags
        flags = overlap_xla_flags('cpu' if args.cpu else None)
        existing = os.environ.get('XLA_FLAGS', '')
        add = [f for f in flags if f.split('=')[0] not in existing]
        if add:
            os.environ['XLA_FLAGS'] = (existing + ' ' +
                                       ' '.join(add)).strip()
            print(f'overlap: XLA_FLAGS += {" ".join(add)}',
                  flush=True)

    if args.cpu:
        import jax
        jax.config.update('jax_platforms', 'cpu')
    _maybe_init_distributed()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from skypilot_tpu.utils import timeline
    if args.trace_file:
        timeline.enable(args.trace_file)

    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel.train import (ShardedTrainer,
                                             default_optimizer, shard_batch)

    n_dev = len(jax.devices())
    proc_id = jax.process_index()
    if args.microbatches and args.pipeline_stages <= 1:
        raise SystemExit('--microbatches only applies with '
                         '--pipeline-stages > 1')
    if args.overlap and not args.zero1 and args.pipeline_stages <= 1:
        raise SystemExit('--overlap buckets the grad reduce-scatter '
                         'onto the ZeRO-1 moment layout; add --zero1 '
                         '(under --pipeline-stages it only sets the '
                         'XLA latency-hiding flags)')
    if args.virtual_stages and args.pipeline_schedule != 'interleaved':
        raise SystemExit('--virtual-stages only applies with '
                         '--pipeline-schedule interleaved')
    if args.pipeline_schedule != 'gpipe' and args.pipeline_stages <= 1:
        raise SystemExit('--pipeline-schedule needs '
                         '--pipeline-stages > 1')
    if args.lora and args.pipeline_stages > 1:
        raise SystemExit('--lora needs the sharded trainer (the '
                         'GPipe path splits params per stage); '
                         'drop one')
    if args.ckpt_interval is not None:
        if not args.ckpt_dir:
            raise SystemExit('--ckpt-interval needs --ckpt-dir')
        if args.ckpt_interval != 'auto':
            try:
                if float(args.ckpt_interval) <= 0:
                    raise ValueError
            except ValueError:
                raise SystemExit('--ckpt-interval takes "auto" or a '
                                 'positive number of seconds') \
                    from None
        elif args.preemption_rate is None and not os.environ.get(
                'SKYPILOT_PREEMPTION_RATE_PER_HOUR'):
            raise SystemExit(
                '--ckpt-interval auto needs the zone preemption '
                'rate: pass --preemption-rate or set '
                'SKYPILOT_PREEMPTION_RATE_PER_HOUR')
    if args.pipeline_stages > 1:
        # v2: tensor and expert shard WITHIN each pipeline stage
        # (shard_map auto axes — GSPMD inserts the within-stage
        # collectives); sequence parallelism stays exclusive (the
        # ring-attention dispatch assumes the non-pipeline trainer).
        if args.seq_parallel != 1:
            raise SystemExit('--pipeline-stages does not compose with '
                             '--seq-parallel; drop one')
        inner = args.pipeline_stages * args.tensor * args.expert
        if n_dev % inner:
            raise SystemExit(
                f'{n_dev} devices not divisible by stages x tensor x '
                f'expert = {inner}')
        mesh_cfg = mesh_lib.MeshConfig(
            data=n_dev // inner,
            stage=args.pipeline_stages,
            tensor=args.tensor, expert=args.expert)
    else:
        mesh_cfg = mesh_lib.MeshConfig.auto(n_dev, tensor=args.tensor,
                                            expert=args.expert,
                                            seq=args.seq_parallel)
    mesh = mesh_lib.make_mesh(mesh_cfg)
    if proc_id == 0:
        print(f'devices={n_dev} {mesh_lib.mesh_summary(mesh)}', flush=True)

    hf_params = None
    if args.init_from_hf:
        from skypilot_tpu.models import hf_import
        model, hf_params = hf_import.load_hf_checkpoint(
            args.init_from_hf, max_seq_len=max(args.seq, 128),
            remat=args.remat)
        vocab_size = model.config.vocab_size
        from skypilot_tpu.models.mixtral import (Mixtral,
                                                 moe_next_token_loss)
        loss_fn = (moe_next_token_loss if isinstance(model, Mixtral)
                   else None)
        if proc_id == 0:
            print(f'initializing from HF checkpoint {args.init_from_hf} '
                  f'({type(model).__name__}, vocab={vocab_size})',
                  flush=True)
    else:
        model, vocab_size, loss_fn = _build_model(args.model, args.seq,
                                                  args.remat)
    batch = args.global_batch or 8 * n_dev
    lora_spec = None
    if args.lora:
        from skypilot_tpu.models import lora as lora_lib
        lora_spec = lora_lib.LoraSpec(
            rank=args.lora,
            alpha=args.lora_alpha or float(args.lora),
            targets=lora_lib.targets_from_name(args.lora_targets))
    tx = default_optimizer(learning_rate=args.lr, warmup_steps=10,
                           total_steps=max(args.steps, 20))
    if args.pipeline_stages > 1:
        from skypilot_tpu.models.gpt import GPT
        from skypilot_tpu.models.llama import Llama
        from skypilot_tpu.models.mixtral import Mixtral
        from skypilot_tpu.parallel.pipeline import PipelinedLM
        if not isinstance(model, (GPT, Llama, Mixtral)):
            raise SystemExit('--pipeline-stages supports the GPT, '
                             'Llama, and Mixtral families (v1)')
        microbatches = args.microbatches or 4 * args.pipeline_stages
        denom = microbatches * mesh_cfg.data
        if batch % denom:
            batch = max(denom, (batch // denom) * denom)
            if proc_id == 0:
                print(f'pipeline: rounding global batch to {batch} '
                      f'({microbatches} microbatches x '
                      f'data={mesh_cfg.data})', flush=True)
        if (args.no_fused_xent or args.zero1) and proc_id == 0:
            print('pipeline trainer: --no-fused-xent/--zero1 ignored '
                  '(the pipeline path computes its head per-stage '
                  'and keeps per-stage opt state)', flush=True)
        virtual = args.virtual_stages or (
            2 if args.pipeline_schedule == 'interleaved' else 1)
        try:
            pp = PipelinedLM(model, mesh,
                             num_microbatches=microbatches,
                             schedule=args.pipeline_schedule,
                             virtual_stages=virtual)
        except ValueError as e:
            raise SystemExit(f'--pipeline-schedule: {e}') from None
        if proc_id == 0:
            print(f'pipeline schedule: {pp.schedule.describe()}',
                  flush=True)
        example = jnp.zeros((batch, args.seq), jnp.int32)
        state = pp.init(jax.random.PRNGKey(0), example, tx)
        if hf_params is not None:
            hf_params = pp.split_params(hf_params)
        step_fn = pp.make_train_step(
            tx, guard=args.guard,
            collect_grad_norm=args.metrics_file is not None)
        pipeline_bubble_frac = pp.schedule.bubble_fraction
        from skypilot_tpu.observability import catalog
        catalog.gauge('skypilot_train_pipeline_bubble_fraction').set(
            pipeline_bubble_frac)
    else:
        kwargs = {} if loss_fn is None else {'loss_fn': loss_fn}
        trainer = ShardedTrainer(
            model, mesh, tx=tx,
            # None = auto: fused whenever the model supports it (all
            # bundled families do; an hf-imported exotic module
            # without return_hidden falls back to the naive path).
            fused_xent=False if args.no_fused_xent else None,
            zero1=args.zero1,
            overlap=args.overlap,
            # --metrics-file wants grad_norm in every record; --guard
            # needs it unconditionally (the trainer forces it on and
            # computes the norm once for both consumers).
            collect_grad_norm=args.metrics_file is not None,
            guard=args.guard,
            lora=lora_spec,
            **kwargs)
        if proc_id == 0:
            print(f'fused_xent={trainer.fused_xent} '
                  f'zero1={args.zero1} overlap={args.overlap} lora='
                  f'{args.lora or "off"}', flush=True)

        example = jnp.zeros((batch, args.seq), jnp.int32)
        with timeline.Event('train/init'):
            state = trainer.init(jax.random.PRNGKey(0), example)
        step_fn = trainer.make_train_step(example)
        pipeline_bubble_frac = None
    if hf_params is not None:
        # Replace the random init with the imported weights, placed
        # with the SAME shardings the trainer chose (device_put
        # against the initialized leaves' shardings — fsdp/tp/stage-
        # safe). Fresh optimizer moments are correct for a finetune
        # start. With --lora only the frozen base half is replaced
        # (the fresh factors ARE the finetune).
        place = lambda init_leaf, w: jax.device_put(  # noqa: E731
            jnp.asarray(w, init_leaf.dtype), init_leaf.sharding)
        if args.lora:
            state = state.replace(params={
                'base': jax.tree.map(place, state.params['base'],
                                     hf_params),
                'lora': state.params['lora']})
        else:
            state = state.replace(params=jax.tree.map(
                place, state.params, hf_params))
        del hf_params

    # Checkpoint resume (preemption recovery path).
    mgr = None
    if args.ckpt_dir:
        from skypilot_tpu.parallel.checkpoints import CheckpointManager
        # Interval mode gates the cadence host-side (it can change
        # once the step cost is measured), so orbax itself must not
        # filter steps.
        mgr = CheckpointManager(
            args.ckpt_dir,
            save_interval_steps=(1 if args.ckpt_interval is not None
                                 else args.ckpt_every))
        latest = mgr.latest_step()
        if latest is not None:
            # restore() verifies sha256 manifests and falls back to
            # the newest verifying step if the latest is corrupt —
            # report the step actually read, not the one asked for.
            state = mgr.restore(state, latest)
            restored = mgr.last_restored_step
            if restored != latest:
                print(f'checkpoint step {latest} corrupt; resumed '
                      f'from step {restored} instead', flush=True)
            else:
                print(f'resumed from checkpoint step {restored}',
                      flush=True)

    # Data.
    loader = None
    if args.data != 'synthetic':
        import glob
        paths = sorted(glob.glob(os.path.join(args.data, '*.bin'))
                       if os.path.isdir(args.data) else glob.glob(args.data))
        from skypilot_tpu.data.token_loader import TokenLoader
        loader = TokenLoader(paths, batch=batch, seq=args.seq,
                             rank=proc_id, world=jax.process_count())

    rng = np.random.default_rng(0)
    start_step = int(state.step)
    # Fire-site context for the train.* fault points: scoped rules
    # can target the first launch ({"resume": "0"}) and leave the
    # checkpoint-resumed run alone.
    resume_ctx = {'resume': '1' if start_step > 0 else '0'}

    def next_tokens():
        # Chaos: a delay rule here is a stalled data loader — the
        # step watchdog must abort past its deadline.
        faults.point('train.data_next', **resume_ctx)
        if loader is not None:
            arr = loader.next_batch()[:, :-1].astype(np.int32)
        else:
            arr = rng.integers(0, vocab_size, (batch, args.seq),
                               dtype=np.int32)
        return shard_batch(jnp.asarray(arr), mesh)

    prof_start = prof_stop = -1
    if args.profile and proc_id == 0:
        prof_start, prof_stop = (int(x) for x in
                                 args.profile_steps.split(':'))
    tracing = False

    # Step telemetry (--metrics-file): one JSONL record per logged
    # window. Both trainers return (loss, grad_norm) when metrics are
    # on — and with --guard, (loss, grad_norm, bad).
    has_gnorm = args.metrics_file is not None
    emitter = None
    if args.metrics_file and proc_id == 0:
        from skypilot_tpu.observability.step_metrics import StepMetrics
        n_params = sum(int(np.prod(x.shape))
                       for x in jax.tree.leaves(state.params))
        emitter = StepMetrics(args.metrics_file, n_params=n_params,
                              n_devices=n_dev)
        print(f'step metrics -> {args.metrics_file} '
              f'(n_params={n_params:,})', flush=True)

    # Self-supervising guards (--guard): preemption-notice watcher,
    # on-device NaN/spike skip + rollback, step watchdog.
    sup = None
    if args.guard:
        sup = train_guard.TrainSupervisor(
            spike_factor=args.spike_factor,
            warmup_steps=args.guard_warmup,
            rollback_after=args.rollback_after,
            watchdog_deadline_s=args.watchdog_deadline,
            compile_deadline_s=args.watchdog_compile_deadline,
            notice_poll_s=args.preempt_poll,
            ctx=resume_ctx)
        sup.start()
        if proc_id == 0:
            wd = (f'{args.watchdog_deadline:.0f}s'
                  if args.watchdog_deadline > 0 else 'off')
            print(f'train-guard armed: spike_factor='
                  f'{args.spike_factor} warmup={args.guard_warmup} '
                  f'rollback_after={args.rollback_after} '
                  f'watchdog={wd} preempt_poll='
                  f'{args.preempt_poll:.1f}s', flush=True)

    # Checkpoint cadence: steps (--ckpt-every) or wall time
    # (--ckpt-interval SECONDS | auto). Auto solves the Young/Daly
    # optimum from the zone preemption rate; either interval form is
    # converted to steps from the measured mean step time of the
    # first logged window (compile inflates that window, so the
    # first estimate errs toward checkpointing too OFTEN — the safe
    # side).
    ckpt_every_steps = args.ckpt_every
    ckpt_interval_s = None
    if args.ckpt_interval == 'auto':
        from skypilot_tpu.jobs import policy as jobs_policy
        rate = (args.preemption_rate
                if args.preemption_rate is not None else
                float(os.environ['SKYPILOT_PREEMPTION_RATE_PER_HOUR']))
        overhead = (args.ckpt_overhead
                    if args.ckpt_overhead is not None else
                    jobs_policy.DEFAULT_CKPT_OVERHEAD_S)
        ckpt_interval_s = jobs_policy.optimal_checkpoint_interval(
            rate, overhead)
        if proc_id == 0:
            print(f'ckpt-interval auto: lambda={rate}/hr '
                  f'delta={overhead:.0f}s -> tau*='
                  f'{ckpt_interval_s:.0f}s (step cadence fixed after '
                  f'the first logged window)', flush=True)
    elif args.ckpt_interval is not None:
        ckpt_interval_s = float(args.ckpt_interval)
    cadence_fixed = ckpt_interval_s is None

    t0 = time.perf_counter()
    window_tokens = 0
    window_steps = 0
    step = start_step
    pending = None  # guard: last dispatched step's un-fetched aux
    while step < args.steps:
        if sup is not None and sup.preempted:
            # Preemption notice (metadata, SIGTERM, or injected):
            # checkpoint NOW and exit with the typed code the
            # managed-jobs controller maps to recovery — the resumed
            # run loses at most the step currently in flight.
            if sup.watchdog is not None:
                sup.watchdog.stop()  # a slow save must not trip it
            if proc_id == 0:
                print(f'preemption notice ({sup.preempt_reason}) at '
                      f'step {step}: checkpointing and exiting '
                      f'rc={train_guard.EXIT_PREEMPTED_GRACEFUL}',
                      flush=True)
            if mgr is not None:
                with timeline.Event('train/checkpoint', 'preempt'):
                    mgr.save(step, state, force=True)
                    mgr.wait_until_finished()
                    mgr.close()
            if emitter is not None:
                emitter.close()
            if args.trace_file:
                timeline.save()
            sup.stop()
            sys.exit(train_guard.EXIT_PREEMPTED_GRACEFUL)
        # >= not ==: a checkpoint resume may land past prof_start.
        if not tracing and prof_start >= 0 and \
                prof_start <= step < prof_stop:
            jax.profiler.start_trace(args.profile)
            tracing = True
        first = step == start_step
        if sup is not None:
            sup.beat('data', first_step=first)
        with timeline.Event('train/data'):
            tokens = next_tokens()
        if sup is not None:
            sup.beat('step', first_step=first)
        with timeline.Event('train/step', f'step {step}'):
            if sup is not None:
                max_gnorm, loss_scale = sup.step_ctl(step)
                state, aux = step_fn(state, tokens, max_gnorm,
                                     loss_scale)
            else:
                faults.point('train.step', step=str(step),
                             **resume_ctx)
                state, aux = step_fn(state, tokens)
        if sup is not None:
            loss, gnorm, bad_flag = aux
        elif has_gnorm:
            loss, gnorm = aux
            bad_flag = None
        else:
            loss, gnorm, bad_flag = aux, None, None
        if tracing and step + 1 >= prof_stop:
            # Block so the trace holds COMPLETE device timelines for
            # the window, not just dispatches.
            jax.block_until_ready(loss)
            jax.profiler.stop_trace()
            tracing = False
            print(f'profile: steps {prof_start}..{prof_stop} traced '
                  f'to {args.profile}', flush=True)
        window_tokens += batch * args.seq
        window_steps += 1
        if sup is not None:
            # Lagged observation: fetch the PREVIOUS step's verdict
            # while this one computes (one-step pipelining keeps the
            # device busy; a rollback discards at most the one step
            # dispatched since).
            if pending is not None:
                p_step, p_loss, p_gnorm, p_bad = pending
                pending = None
                verdict = sup.observe(p_step, float(p_loss),
                                      float(p_gnorm), bool(p_bad))
                if verdict == 'rollback':
                    from skypilot_tpu.robustness.errors import (
                        CheckpointNotFoundError)
                    restored = False
                    if mgr is not None:
                        try:
                            state = mgr.restore(state)
                            restored = True
                        except CheckpointNotFoundError:
                            pass
                    if restored:
                        sup.guard.reset_after_rollback()
                        step = int(state.step)
                        t0 = time.perf_counter()
                        window_tokens = 0
                        window_steps = 0
                        if proc_id == 0:
                            print(f'train-guard: rolled back to '
                                  f'last checkpoint (step {step})',
                                  flush=True)
                        continue
                    # Nothing to roll back to. The params are still
                    # clean (every bad step was skipped on device):
                    # reset the escalation counter and keep skipping.
                    sup.guard.consecutive_bad = 0
                    if proc_id == 0:
                        print('train-guard: rollback requested but '
                              'no checkpoint available; continuing '
                              'with per-step skips', flush=True)
            pending = (step, loss, gnorm, bad_flag)
        if mgr is not None and (ckpt_interval_s is None or
                                (step + 1) % ckpt_every_steps == 0):
            with timeline.Event('train/checkpoint', f'step {step + 1}'):
                mgr.save(step + 1, state)
        if tracing and step + 1 >= args.steps:
            # Window ran past the final step: still flush the trace.
            jax.block_until_ready(loss)
            jax.profiler.stop_trace()
            tracing = False
            print(f'profile: traced through final step {step + 1} '
                  f'to {args.profile}', flush=True)
        boundary = (step + 1) % args.log_every == 0
        if boundary and not cadence_fixed:
            # Every process fixes the cadence (checkpoint saves are
            # collective); proc 0's value is broadcast so clock skew
            # cannot desynchronize the save schedule.
            if sup is not None:
                sup.beat('commit')
            jax.block_until_ready(loss)
            mean_step = ((time.perf_counter() - t0) /
                         max(window_steps, 1))
            cadence = max(1, round(ckpt_interval_s /
                                   max(mean_step, 1e-9)))
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                cadence = int(multihost_utils.broadcast_one_to_all(
                    np.int32(cadence)))
            ckpt_every_steps = cadence
            cadence_fixed = True
            if proc_id == 0:
                print(f'ckpt cadence: interval '
                      f'{ckpt_interval_s:.0f}s / measured step '
                      f'{mean_step:.3f}s -> checkpoint every '
                      f'{ckpt_every_steps} steps', flush=True)
        if boundary and proc_id == 0:
            if sup is not None:
                sup.beat('commit')
            # Host-observed drain wait for the in-flight step: the
            # device's critical path (compute + any un-overlapped
            # collectives) still outstanding at the window boundary.
            # On TPU the --profile trace shows WHICH collectives the
            # gap is; this counter tracks whether --overlap shrinks
            # it run-over-run.
            wait0 = time.perf_counter()
            jax.block_until_ready(loss)
            collective_wait_s = time.perf_counter() - wait0
            from skypilot_tpu.observability import catalog
            catalog.counter(
                'skypilot_train_collective_wait_seconds_total').inc(
                    collective_wait_s)
            dt = time.perf_counter() - t0
            print(f'step {step + 1}/{args.steps} '
                  f'loss={float(loss):.4f} '
                  f'tokens/s={window_tokens / dt:,.0f}', flush=True)
            if emitter is not None:
                emitter.log(
                    step + 1,
                    step_time_s=dt / max(window_steps, 1),
                    tokens=batch * args.seq,
                    loss=float(loss),
                    grad_norm=(float(gnorm) if gnorm is not None
                               else None),
                    bubble_frac=pipeline_bubble_frac,
                    collective_wait_s=collective_wait_s)
            t0 = time.perf_counter()
            window_tokens = 0
            window_steps = 0
        step += 1
    if sup is not None:
        if pending is not None:
            p_step, p_loss, p_gnorm, p_bad = pending
            sup.observe(p_step, float(p_loss), float(p_gnorm),
                        bool(p_bad))
        sup.stop()  # before the final save: it can be slow
        if proc_id == 0:
            print(f'train-guard summary: {sup.summary()}', flush=True)
    if mgr is not None:
        with timeline.Event('train/checkpoint', 'final'):
            mgr.save(args.steps, state, force=True)
            mgr.wait_until_finished()
            mgr.close()
    if lora_spec is not None and proc_id == 0:
        # The produce half of the fine-tune-and-serve loop: the
        # trained factors become a registry-loadable artifact
        # (serve_lm --adapter-dir <parent>, model field = dir name).
        from skypilot_tpu.models import lora as lora_lib
        out_dir = args.adapter_out or (
            os.path.join(args.ckpt_dir, 'adapter') if args.ckpt_dir
            else 'adapter_out')
        lora_np = jax.device_get(state.params['lora'])
        lora_lib.save_adapter(
            out_dir, lora_np, lora_spec,
            base_model=args.init_from_hf or args.model,
            step=int(state.step))
        print(f'adapter artifact -> {out_dir} (rank={lora_spec.rank} '
              f'alpha={lora_spec.alpha} '
              f'targets={list(lora_spec.targets)})', flush=True)
    if emitter is not None:
        emitter.close()
    if proc_id == 0:
        print('training done', flush=True)
    if args.trace_file:
        timeline.save()


if __name__ == '__main__':
    main()
