"""Multi-replica LM serving fleet on one host: N real `serve_lm`
processes behind the replica-plane load balancer, autoscaled from
scraped engine metrics.

  python -m skypilot_tpu.recipes.serve_fleet \
      --model llama-tiny --cpu --replicas 2 --max-replicas 4 \
      --lb-port 9000 --lb-policy prefix_affinity

The LB serves /generate, /generate_text and /v1/* on --lb-port with
prefix-cache-affinity routing (requests sharing a system prompt land
on the replica already holding those KV pages), /fleet/status with
per-replica scraped state + LB counters, and /metrics. Scale-up
triggers on engine pressure (prefill backlog tokens, queue depth,
shed rate); scale-down always drains: the victim leaves the routing
set, gets SIGTERM, finishes its in-flight requests, and only then
exits. SIGTERM to THIS process drains the whole fleet.

Crash-only restart: with `--state-dir DIR` the replica manager
journals every replica lifecycle change to DIR/fleet.journal
(fsync'd JSONL). Killing THIS process — even SIGKILL — orphans
nothing: restart with the same --state-dir and the controller
replays the journal, verifies each journaled replica (pid alive,
/stats echoing the journaled instance UUID), adopts the survivors
back into the routing ring (prefix-affinity keys land back on the
replicas still holding their KV pages), resumes interrupted drains,
and politely SIGTERMs (never SIGKILLs) anything it cannot verify.

Chaos: --fault-plan is forwarded to every replica (the plan arms
inside each serve_lm process; see docs/guides.md "Serving
robustness"). --stub-replicas swaps serve_lm for the model-free
stub replica (chaos drills and the controller-restart e2e). Never
in production.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def build_replica_cmd(args: argparse.Namespace) -> list:
    """The serve_lm command line shared by every replica (no --port:
    the manager appends one per replica)."""
    cmd = [sys.executable, '-m', 'skypilot_tpu.recipes.serve_lm',
           '--model', args.model,
           '--max-total-len', str(args.max_total_len),
           '--continuous-batching',
           '--num-slots', str(args.num_slots)]
    if args.hf:
        cmd += ['--hf', args.hf]
    if args.ckpt_dir:
        cmd += ['--ckpt-dir', args.ckpt_dir]
    if args.adapter_dir:
        cmd += ['--adapter-dir', args.adapter_dir,
                '--max-adapters', str(args.max_adapters)]
    if args.prefill_chunk is not None:
        cmd += ['--prefill-chunk', str(args.prefill_chunk)]
    if args.max_queue_requests:
        cmd += ['--max-queue-requests', str(args.max_queue_requests)]
    if args.max_queue_tokens:
        cmd += ['--max-queue-tokens', str(args.max_queue_tokens)]
    if args.kv_dtype:
        cmd += ['--kv-dtype', args.kv_dtype]
    if args.kv_pool_bytes:
        cmd += ['--kv-pool-bytes', str(args.kv_pool_bytes)]
    if args.weight_dtype:
        cmd += ['--weight-dtype', args.weight_dtype]
    if args.tensor > 1:
        cmd += ['--tensor', str(args.tensor)]
    if args.stages > 1:
        cmd += ['--stages', str(args.stages)]
    if args.kv_spill_bytes:
        cmd += ['--kv-spill-bytes', str(args.kv_spill_bytes)]
    if args.kv_cold_dir:
        cmd += ['--kv-cold-dir', args.kv_cold_dir]
    if args.fault_plan:
        cmd += ['--fault-plan', args.fault_plan]
    if args.trace_sample:
        # Replicas never head-sample in a fleet (the LB owns the
        # decision and propagates it via the trace header); the flag
        # still turns their span recording on.
        cmd += ['--trace-sample', str(args.trace_sample)]
        if args.trace_seed is not None:
            cmd += ['--trace-seed', str(args.trace_seed)]
    if args.slo:
        cmd += ['--slo', args.slo]
    if args.cpu:
        cmd += ['--cpu']
    return cmd


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='llama-tiny')
    parser.add_argument('--hf', default=None)
    parser.add_argument('--ckpt-dir', default=None)
    parser.add_argument('--max-total-len', type=int, default=256)
    parser.add_argument('--num-slots', type=int, default=8)
    parser.add_argument('--prefill-chunk', type=int, default=None)
    parser.add_argument('--max-queue-requests', type=int, default=0)
    parser.add_argument('--max-queue-tokens', type=int, default=0)
    parser.add_argument('--adapter-dir', default=None, metavar='DIR',
                        help='multi-LoRA serving: forwarded to every '
                             'replica; a shared artifact dir means '
                             'any replica can hot-load any tenant '
                             'adapter (the LB affinity key pins a '
                             'tenant to the replica already holding '
                             'its pages + adapter)')
    parser.add_argument('--max-adapters', type=int, default=8,
                        help='forwarded to serve_lm --max-adapters')
    parser.add_argument('--kv-dtype', choices=['bf16', 'int8'],
                        default=None,
                        help='forwarded to every replica: int8 KV '
                             'pages (~2x slots / prefix residency '
                             'per HBM byte; docs/guides.md '
                             '"Quantized serving")')
    parser.add_argument('--kv-pool-bytes', type=int, default=0,
                        metavar='B',
                        help='forwarded to serve_lm --kv-pool-bytes')
    parser.add_argument('--weight-dtype', choices=['bf16', 'int8'],
                        default=None,
                        help='forwarded to every replica: int8 '
                             'per-channel projection weights')
    parser.add_argument('--tensor', type=int, default=1,
                        help='forwarded to every replica: tensor-'
                             'parallel serving over N devices '
                             '(serve_lm --tensor). Each replica '
                             'claims its own N chips')
    parser.add_argument('--stages', type=int, default=1,
                        help='forwarded to every replica: pipeline-'
                             'parallel serving over S stages '
                             '(serve_lm --stages); composes with '
                             '--tensor for S x N chips per replica')
    parser.add_argument('--fault-plan', default=None, metavar='JSON')
    parser.add_argument('--cpu', action='store_true')
    parser.add_argument('--state-dir', default=None, metavar='DIR',
                        help='durable fleet journal directory: '
                             'restarting with the same DIR adopts '
                             'surviving replicas instead of '
                             'orphaning them')
    parser.add_argument('--stub-replicas', action='store_true',
                        help='model-free stub replicas '
                             '(replica_plane/stub.py) instead of '
                             'serve_lm — chaos drills only')
    parser.add_argument('--replicas', type=int, default=2,
                        help='initial + minimum replica count (the '
                             'DECODE pool when --prefill-replicas '
                             'is set)')
    parser.add_argument('--spot-decode', type=int, default=0,
                        metavar='N',
                        help='spot decode pool: N additional decode '
                             'replicas labeled with zones walked in '
                             'the catalog\'s RISK-ADJUSTED spot '
                             'order (spot_zone_economics: price x '
                             'preemption-rate multiplier) for '
                             '--spot-accelerator. A PreemptionNotice '
                             '(or a serve.preempt_notice fault rule '
                             'scoped to the zone) makes the replica '
                             'evacuate every KV chain to on-demand '
                             'survivors inside the ~30s grace '
                             'window instead of dropping sessions')
    parser.add_argument('--spot-accelerator', default='tpu-v5e-16',
                        metavar='ACC',
                        help='TPU type whose catalog rows price the '
                             'spot decode pool (zone labels + '
                             '$/hour in /fleet/status and the '
                             'journal)')
    parser.add_argument('--rebalance-skew', type=float, default=0.0,
                        metavar='R',
                        help='hot-spot rebalancing: when one ready '
                             'replica\'s load (prefill backlog '
                             'tokens + queue depth) exceeds R x the '
                             'pool median for --rebalance-ticks '
                             'consecutive scrapes, the controller '
                             'migrates its hottest sessions\' KV '
                             'chains to the coldest replica between '
                             'requests. 0 disables (default)')
    parser.add_argument('--rebalance-ticks', type=int, default=3,
                        help='consecutive skewed scrapes (same '
                             'hottest replica) before a rebalance '
                             'fires')
    parser.add_argument('--rebalance-sessions', type=int, default=2,
                        help='sessions migrated per rebalance step '
                             '(small on purpose: each step is '
                             're-evaluated against fresh load)')
    parser.add_argument('--prefill-replicas', type=int, default=0,
                        metavar='N',
                        help='disaggregated serving: N additional '
                             'replicas spawned with --role prefill. '
                             'Long prompts (>= --disagg-prompt-'
                             'threshold) route to them; they prefill '
                             'and hand the KV page chain to a decode '
                             'replica (POST /kv/import), which '
                             'serves the decode phase — decode-pool '
                             'ITL stays flat as long-prompt traffic '
                             'rises. 0 = unified fleet')
    parser.add_argument('--disagg-prompt-threshold', type=int,
                        default=256, metavar='T',
                        help='LB routing threshold, prompt tokens '
                             '(text endpoints estimate chars/4): '
                             'requests at or above it go to the '
                             'prefill pool (when --prefill-replicas '
                             '> 0)')
    parser.add_argument('--kv-spill-bytes', type=int, default=0,
                        metavar='B',
                        help='forwarded to every replica: tiered '
                             'prefix cache — evicted KV pages spill '
                             'to a host-RAM LRU of B bytes and '
                             'restore bit-identically on a later '
                             'chain-key hit')
    parser.add_argument('--kv-cold-dir', default=None, metavar='DIR',
                        help='forwarded to every replica: cold tier '
                             'behind the host spill (local dir or '
                             'gs:// prefix)')
    parser.add_argument('--max-replicas', type=int, default=None,
                        help='autoscaler ceiling (default: --replicas '
                             '— fixed-size fleet)')
    parser.add_argument('--lb-port', type=int,
                        default=int(os.environ.get(
                            'SKYPILOT_SERVE_PORT', 9000)))
    parser.add_argument('--lb-policy', default='prefix_affinity',
                        help='round_robin | least_load | '
                             'prefix_affinity')
    parser.add_argument('--page-size', type=int, default=16,
                        help='affinity hashing page size; must match '
                             'the engine KV page size')
    parser.add_argument('--scrape-interval', type=float, default=1.0)
    parser.add_argument('--drain-grace', type=float, default=630.0,
                        help='seconds a draining replica gets to '
                             'finish in-flight requests before '
                             'SIGKILL')
    parser.add_argument('--target-queue-per-replica', type=float,
                        default=4.0)
    parser.add_argument('--target-backlog-per-replica', type=float,
                        default=4096.0)
    parser.add_argument('--upscale-delay', type=float, default=10.0)
    parser.add_argument('--downscale-delay', type=float, default=60.0)
    parser.add_argument('--trace-sample', type=float, default=0.0,
                        metavar='P',
                        help='distributed tracing: the LB samples '
                             'this fraction of requests and '
                             'propagates the decision to replicas '
                             'over the x-skypilot-trace header; '
                             '`stpu trace <id>` merges the per-'
                             'process spans into one Chrome trace')
    parser.add_argument('--trace-seed', type=int, default=None,
                        help='seed the LB trace sampler '
                             '(reproducible sampled set + ids)')
    parser.add_argument('--slo', default=None, metavar='SPEC',
                        help='fleet SLO targets (e.g. "p99_ttft_ms='
                             '500,error_rate=0.01"): the LB tracks '
                             'user-perceived burn rates in '
                             '/fleet/status and each replica tracks '
                             'its own in /stats')
    args = parser.parse_args()
    slo_targets = None
    if args.slo:
        from skypilot_tpu.observability import slo as slo_lib
        try:
            slo_targets = slo_lib.parse_slo(args.slo)
        except ValueError as e:
            parser.error(str(e))

    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve import load_balancing_policies as lb_policies
    from skypilot_tpu.serve import service_spec as spec_lib
    from skypilot_tpu.serve.replica_plane import (FleetController,
                                                  PrefillPool,
                                                  ReplicaManager,
                                                  make_lb_server,
                                                  serve_lm_factory,
                                                  stub_factory)
    from skypilot_tpu.utils.registry import LB_POLICY_REGISTRY

    # The spot decode pool is part of the serving floor: the
    # autoscaler must not read the extra spot replicas as surplus
    # and drain them right back down.
    total_decode = args.replicas + max(args.spot_decode, 0)
    max_replicas = max(args.max_replicas or total_decode,
                       total_decode)
    spec = spec_lib.SkyServiceSpec(
        min_replicas=total_decode, max_replicas=max_replicas,
        upscale_delay_seconds=args.upscale_delay,
        downscale_delay_seconds=args.downscale_delay)
    autoscaler = autoscalers.EngineMetricsAutoscaler(
        spec,
        target_queue_per_replica=args.target_queue_per_replica,
        target_backlog_per_replica=args.target_backlog_per_replica)
    policy_cls = LB_POLICY_REGISTRY.from_str(args.lb_policy)
    policy: lb_policies.LoadBalancingPolicy = policy_cls()

    # Disaggregated mode: a fixed-size (min==max) prefill pool with
    # its own backlog-driven autoscaler, and the LB routing long
    # prompts to it.
    prefill_autoscaler = None
    prefill_pool = None
    if args.prefill_replicas > 0:
        prefill_spec = spec_lib.SkyServiceSpec(
            min_replicas=args.prefill_replicas,
            max_replicas=args.prefill_replicas,
            upscale_delay_seconds=args.upscale_delay,
            downscale_delay_seconds=args.downscale_delay)
        prefill_autoscaler = autoscalers.EngineMetricsAutoscaler(
            prefill_spec,
            target_queue_per_replica=args.target_queue_per_replica,
            target_backlog_per_replica=args.target_backlog_per_replica)
        prefill_pool = PrefillPool()

    env = dict(os.environ)
    if args.stub_replicas:
        if args.fault_plan:
            # Stubs take no --fault-plan flag; the plan arms from
            # the environment at import (robustness/faults.py).
            env['STPU_FAULT_PLAN'] = args.fault_plan
        factory = stub_factory(env=env)
    else:
        factory = serve_lm_factory(build_replica_cmd(args), env=env)
    manager = ReplicaManager(factory,
                             drain_grace_s=args.drain_grace,
                             state_dir=args.state_dir)
    controller = FleetController(
        manager, policy, autoscaler,
        interval_s=args.scrape_interval,
        prefill_autoscaler=prefill_autoscaler,
        prefill_pool=prefill_pool,
        rebalance_skew=args.rebalance_skew,
        rebalance_ticks=args.rebalance_ticks,
        rebalance_sessions=args.rebalance_sessions)
    lb = make_lb_server(
        policy, args.lb_port,
        policy_name=args.lb_policy, manager=manager,
        page_size=args.page_size,
        disagg_threshold=(args.disagg_prompt_threshold
                          if args.prefill_replicas > 0 else 0),
        prefill_pool=prefill_pool,
        trace_sample=args.trace_sample,
        trace_seed=args.trace_seed,
        slo_targets=slo_targets)

    def handle_term(signum, frame):  # noqa: ARG001
        def _shutdown():
            controller.shutdown()
            lb.shutdown()
        threading.Thread(target=_shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, handle_term)
    adopted = 0
    if args.state_dir:
        summary = manager.adopt()
        adopted = len(summary['adopted'])
        if any(summary.values()):
            print(f'serve_fleet: adopted {summary["adopted"]} from '
                  f'{args.state_dir}, resumed drains '
                  f'{summary["resumed_drains"]}, reaped orphans '
                  f'{summary["orphans"]}', flush=True)
    adopted_prefill = sum(
        1 for v in manager.views() if v.role == 'prefill')
    adopted_spot = sum(
        1 for v in manager.views()
        if v.role != 'prefill' and v.zone)
    decode_role = 'decode' if args.prefill_replicas else ''
    for _ in range(max(0, args.replicas -
                       (adopted - adopted_prefill - adopted_spot))):
        manager.spawn(role=decode_role)
    if args.spot_decode > 0:
        # Walk the catalog's risk-adjusted spot order (cheapest
        # effective $/hour first, preemption risk priced in) and
        # label each spot replica with its zone + price — the zone
        # is what a PreemptionNotice (or a zone-scoped
        # serve.preempt_notice fault rule) later targets, and the
        # price feeds the $/1M-token accounting in /fleet/status.
        from skypilot_tpu.catalog import gcp_catalog
        try:
            econ = gcp_catalog.spot_zone_economics(
                args.spot_accelerator)
        except Exception as e:
            print(f'serve_fleet: spot catalog lookup for '
                  f'{args.spot_accelerator} failed ({e}); spot '
                  f'replicas spawn zoneless.', flush=True)
            econ = []
        for i in range(max(0, args.spot_decode - adopted_spot)):
            if econ:
                zone, price, _rate = econ[i % len(econ)]
            else:
                zone, price = f'spot-zone-{i}', 0.0
            manager.spawn(role=decode_role, zone=zone,
                          price_per_hour=price)
    for _ in range(max(0, args.prefill_replicas - adopted_prefill)):
        manager.spawn(role='prefill')
    loop = threading.Thread(target=controller.run, daemon=True)
    loop.start()
    print(f'serve_fleet: LB on :{args.lb_port} '
          f'policy={args.lb_policy} replicas={args.replicas}..'
          f'{max_replicas} model={args.model}', flush=True)
    try:
        lb.serve_forever()
    finally:
        controller.shutdown()


if __name__ == '__main__':
    main()
