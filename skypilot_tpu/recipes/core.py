"""Recipe registry: curated runnable task YAMLs.

Reference: sky/recipes/core.py (`sky recipes`). Recipes are the
bundled examples/ YAMLs; `stpu recipes list|show` browses them.
"""
from __future__ import annotations

import os
from typing import Dict, List

import yaml

_EXAMPLES_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), 'examples')


def list_recipes() -> List[Dict[str, str]]:
    out = []
    if not os.path.isdir(_EXAMPLES_DIR):
        return out
    for fname in sorted(os.listdir(_EXAMPLES_DIR)):
        if not fname.endswith(('.yaml', '.yml')):
            continue
        path = os.path.join(_EXAMPLES_DIR, fname)
        description = ''
        with open(path, 'r', encoding='utf-8') as f:
            for line in f:
                if line.startswith('#'):
                    description = line.lstrip('# ').strip()
                    break
        try:
            with open(path, 'r', encoding='utf-8') as f:
                config = yaml.safe_load(f)
            accelerator = ((config.get('resources') or {})
                           .get('accelerators', '-'))
        except yaml.YAMLError:
            accelerator = '?'
        out.append({
            'name': fname.rsplit('.', 1)[0],
            'path': path,
            'description': description,
            'accelerator': str(accelerator),
        })
    return out


def get_recipe_path(name: str) -> str:
    for recipe in list_recipes():
        if recipe['name'] == name:
            return recipe['path']
    raise FileNotFoundError(
        f'Recipe {name!r} not found; `stpu recipes list`.')
