"""Dag: a graph of Tasks with `>>` chaining.

Reference: sky/dag.py (228 LoC) — networkx-backed task graph, chain
detection, thread-local dag context for `with Dag():` blocks.
"""
from __future__ import annotations

import threading
from typing import List, Optional

from skypilot_tpu import task as task_lib


class Dag:

    def __init__(self, name: Optional[str] = None) -> None:
        self.name = name
        self.tasks: List[task_lib.Task] = []
        import networkx as nx  # lazy, like the reference
        self.graph = nx.DiGraph()
        self.policy_applied: bool = False

    def add(self, task: task_lib.Task) -> None:
        self.graph.add_node(task)
        self.tasks.append(task)
        task.dag = self

    def remove(self, task: task_lib.Task) -> None:
        self.tasks.remove(task)
        self.graph.remove_node(task)
        task.dag = None

    def add_edge(self, op1: task_lib.Task, op2: task_lib.Task) -> None:
        assert op1 in self.graph.nodes, 'Add tasks before adding edges.'
        assert op2 in self.graph.nodes, 'Add tasks before adding edges.'
        self.graph.add_edge(op1, op2)

    def __len__(self) -> int:
        return len(self.tasks)

    def __enter__(self) -> 'Dag':
        push_dag(self)
        return self

    def __exit__(self, *args) -> None:
        pop_dag()

    def is_chain(self) -> bool:
        """True iff the graph is a linear chain (possibly a single task)."""
        import networkx as nx
        if len(self.tasks) <= 1:
            return True
        if any(d > 1 for _, d in self.graph.in_degree()):
            return False
        if any(d > 1 for _, d in self.graph.out_degree()):
            return False
        return (nx.is_weakly_connected(self.graph) and
                nx.is_directed_acyclic_graph(self.graph) and
                self.graph.number_of_edges() == len(self.tasks) - 1)

    def get_sorted_tasks(self) -> List[task_lib.Task]:
        import networkx as nx
        return list(nx.topological_sort(self.graph))

    def validate(self) -> None:
        import networkx as nx
        if not nx.is_directed_acyclic_graph(self.graph):
            raise ValueError('DAG has a cycle.')

    def __repr__(self) -> str:
        return f'Dag({self.name!r}, {len(self.tasks)} tasks)'


_LOCAL = threading.local()


def push_dag(dag: Dag) -> None:
    if not hasattr(_LOCAL, 'stack'):
        _LOCAL.stack = []
    _LOCAL.stack.append(dag)


def pop_dag() -> Dag:
    return _LOCAL.stack.pop()


def get_current_dag() -> Optional[Dag]:
    stack = getattr(_LOCAL, 'stack', None)
    return stack[-1] if stack else None
