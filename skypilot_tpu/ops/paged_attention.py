"""Paged KV-cache attention for LM serving.

The vLLM idea, TPU-native: instead of one dense [B, max_len] KV cache
per slot (allocated for the worst case), K/V live in fixed-size pages
shared by all slots; each sequence owns a page list. Total page count
is sized for the *aggregate* live tokens, so many short sequences fit
where the dense layout would exhaust HBM — more decode slots, higher
serving throughput.

On TPU the attention reads dispatch to the pallas paged-attention
kernel (jax.experimental.pallas.ops.tpu.paged_attention — blockwise
page gathers in VMEM); elsewhere a pure-XLA reference (gather + masked
attention) keeps the path testable and correct. The reference also
defines the semantics the kernel is tested against on TPU.

Layouts (matching the pallas kernel):
  q            [B, num_q_heads, head_dim]      one decode token per row
  k/v_pages    [num_kv_heads, total_pages, page_size, head_dim]
  lengths      i32[B]   tokens already in the cache (incl. current)
  page_indices i32[B, pages_per_seq]  physical page ids per sequence

Page allocation is host-side (`PageAllocator`): XLA needs static
shapes, so the device arrays are fixed-size and the allocator only
decides which physical pages a sequence uses.

TENSOR-PARALLEL POOLS (parallel/serving.py, PR 15): under a mesh the
pool's LEADING kv-heads axis is sharded over `tensor`, so each chip
holds a head-slice of every page. These ops are sharding-transparent
— the page gather indexes the pages axis (axis 1) and every
per-token compute is elementwise over heads — so GSPMD partitions
them without inserting pool-shaped collectives (asserted by the
pool_collective_lines guard). Page ids, lengths, and page tables are
replicated host-side values; the scale arrays (below) have no heads
axis and replicate.

INT8 KV PAGES (kv_dtype='int8' on the model config): the page pool
stores int8 with one f32 scale per page SLOT (i.e. per cached token,
shared across KV heads) living in a parallel scale-page array
  k/v_scales   f32[total_pages, page_size]
Quantization is symmetric absmax over that token's (Hkv, head_dim)
values, applied on every cache write (`write_kv_quant` /
`write_kv_chunk_quant`); the attention reads dequantize right after
the page gather so every matmul stays bf16/f32. Scales travel with
their physical page, so allocation, free-lists, prefix sharing and
chain keys are untouched — a shared prefix page is one int8 copy
plus its scales, refcounted exactly like a bf16 page. Per-slot
scales (rather than one scale per whole page) keep single-token
decode writes requantization-free: a write never touches another
token's already-quantized values.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp


def _pallas_paged_available() -> bool:
    """Upstream bf16 pallas kernel usable here. Probe result (and the
    failure REASON, for /stats and skip messages) is cached at module
    level in ops/pallas_paged.py — see `pallas_paged.available()` /
    `unavailable_reason()` for the in-repo fused kernel's probe."""
    from skypilot_tpu.ops import pallas_paged
    return pallas_paged.upstream_available()


def quantize_kv_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric absmax int8 quantization of per-token KV rows.

    x: [..., num_kv_heads, head_dim] (one leading index per cached
    token). Returns (q int8 same shape, scale f32[...]) with the
    scale taken over each token's (Hkv, D) values. An all-zero token
    quantizes to scale 0 / values 0 (dequant is exactly zero)."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=(-2, -1))
    scale = amax / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(x32 / safe[..., None, None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_kv(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of `quantize_kv_rows`: q [..., Hkv-or-Hq, D] int8,
    scale [...] f32 broadcast over the trailing two dims."""
    return q.astype(jnp.float32) * scale[..., None, None]


def paged_decode_attention(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, lengths: jax.Array,
                           page_indices: jax.Array,
                           *, k_scales: Optional[jax.Array] = None,
                           v_scales: Optional[jax.Array] = None,
                           impl: str = 'auto') -> jax.Array:
    """Attention of one query token per row over its paged KV history.

    Returns [B, num_q_heads, head_dim] (q.dtype). GQA: num_q_heads may
    be a multiple of num_kv_heads. `k_scales`/`v_scales`
    (f32[total_pages, page_size]) mark int8 pages.

    `impl` resolves through `pallas_paged.resolve_impl` (overridable
    process-wide via $SKYPILOT_TPU_PAGED_IMPL / `impl_scope`):
    'kernel' is the upstream bf16 pallas kernel, 'fused' /
    'fused_interpret' the in-repo kernel that dequantizes int8 pages
    in-register (ops/pallas_paged.py), 'xla' the gather reference —
    which dequantizes in HBM, the traffic the fused path deletes.
    """
    assert q.ndim == 3 and k_pages.ndim == 4, (q.shape, k_pages.shape)
    from skypilot_tpu.ops import pallas_paged
    impl = pallas_paged.resolve_impl(impl, quantized=k_scales is not None)
    if impl in ('fused', 'fused_interpret'):
        out = pallas_paged.fused_paged_attention(
            q[:, None], k_pages, v_pages, (lengths - 1)[:, None],
            page_indices, k_scales=k_scales, v_scales=v_scales,
            interpret=impl == 'fused_interpret')
        return out[:, 0]
    if impl == 'kernel':
        from jax.experimental.pallas.ops.tpu.paged_attention import (
            paged_attention)
        pages_per_seq = page_indices.shape[1]
        # Block size must divide the per-sequence page walk.
        block = min(8, pages_per_seq)
        while pages_per_seq % block != 0:
            block -= 1
        # The pallas kernel applies NO attention scaling internally
        # (its qk is a raw einsum) — pre-scale q to match the
        # reference semantics (MaxText does the same).
        scale = 1.0 / (q.shape[-1] ** 0.5)
        return paged_attention(q * scale, k_pages, v_pages, lengths,
                               page_indices,
                               pages_per_compute_block=block)
    return _reference_paged_attention(q, k_pages, v_pages, lengths,
                                      page_indices,
                                      k_scales=k_scales,
                                      v_scales=v_scales)


def _gather_kv(q_heads: int, k_pages: jax.Array, v_pages: jax.Array,
               page_indices: jax.Array,
               k_scales: Optional[jax.Array] = None,
               v_scales: Optional[jax.Array] = None
               ) -> Tuple[jax.Array, jax.Array]:
    """Per-row page gather + GQA head expansion: the shared read side
    of every XLA paged-attention path. Returns k/v as [B, T, Hq, D]
    where T = pages_per_seq * page_size. With scale pages the gather
    DEQUANTIZES (int8 * per-slot f32 scale) before head expansion —
    the one place quantized storage meets the math."""
    num_kv_heads, _, page_size, head_dim = k_pages.shape
    max_len = page_indices.shape[1] * page_size

    # [Hkv, pages, page, D] -> [T, Hkv, D], per row.
    def gather_row(pages, idx):
        g = pages[:, idx]                       # [Hkv, pages, page, D]
        g = jnp.swapaxes(g, 0, 1)               # [pages, Hkv, page, D]
        g = jnp.swapaxes(g, 1, 2)               # [pages, page, Hkv, D]
        return g.reshape(max_len, num_kv_heads, head_dim)

    def gather_scale_row(scales, idx):
        return scales[idx].reshape(max_len)     # [pages, page] -> [T]

    k_all = jax.vmap(gather_row, in_axes=(None, 0))(k_pages, page_indices)
    v_all = jax.vmap(gather_row, in_axes=(None, 0))(v_pages, page_indices)
    if k_scales is not None:
        k_s = jax.vmap(gather_scale_row,
                       in_axes=(None, 0))(k_scales, page_indices)
        v_s = jax.vmap(gather_scale_row,
                       in_axes=(None, 0))(v_scales, page_indices)
        k_all = k_all.astype(jnp.float32) * k_s[:, :, None, None]
        v_all = v_all.astype(jnp.float32) * v_s[:, :, None, None]
    if q_heads != num_kv_heads:
        rep = q_heads // num_kv_heads
        k_all = jnp.repeat(k_all, rep, axis=2)
        v_all = jnp.repeat(v_all, rep, axis=2)
    return k_all, v_all


def _reference_paged_attention(q: jax.Array, k_pages: jax.Array,
                               v_pages: jax.Array, lengths: jax.Array,
                               page_indices: jax.Array,
                               k_scales: Optional[jax.Array] = None,
                               v_scales: Optional[jax.Array] = None
                               ) -> jax.Array:
    """Pure-XLA semantics: gather each row's pages, masked softmax."""
    head_dim = k_pages.shape[-1]
    max_len = page_indices.shape[1] * k_pages.shape[2]
    k_all, v_all = _gather_kv(q.shape[1], k_pages, v_pages,
                              page_indices, k_scales, v_scales)

    scale = 1.0 / (head_dim ** 0.5)
    s = jnp.einsum('bhd,bkhd->bhk', q.astype(jnp.float32),
                   k_all.astype(jnp.float32)) * scale
    mask = (jnp.arange(max_len)[None, :] < lengths[:, None])[:, None, :]
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum('bhk,bkhd->bhd', p, v_all.astype(jnp.float32))
    return out.astype(q.dtype)


def write_kv(k_pages: jax.Array, v_pages: jax.Array, k_new: jax.Array,
             v_new: jax.Array, positions: jax.Array,
             page_indices: jax.Array
             ) -> Tuple[jax.Array, jax.Array]:
    """Write one token's K/V per row at its position's page slot.

    k_new/v_new: [B, num_kv_heads, head_dim]; positions: i32[B] (the
    index the token lands at, i.e. lengths - 1 after admission);
    returns updated (k_pages, v_pages). Rows write distinct physical
    pages (the allocator guarantees no sharing), so a scatter over
    (page, slot) pairs is race-free.
    """
    page_size = k_pages.shape[2]
    logical_page = positions // page_size
    slot = positions % page_size
    batch = positions.shape[0]
    physical = page_indices[jnp.arange(batch), logical_page]  # [B]

    # [Hkv, P, page, D] scatter at (:, physical[b], slot[b], :) = new[b]
    def write_one(pages, new):
        # pages: [Hkv, P, page, D]; new: [B, Hkv, D]
        return pages.at[:, physical, slot, :].set(
            jnp.swapaxes(new, 0, 1))

    return write_one(k_pages, k_new), write_one(v_pages, v_new)


def write_kv_chunk(k_pages: jax.Array, v_pages: jax.Array,
                   k_new: jax.Array, v_new: jax.Array,
                   positions: jax.Array, page_indices: jax.Array
                   ) -> Tuple[jax.Array, jax.Array]:
    """Chunked-prefill write: S tokens per row in one scatter.

    k_new/v_new: [B, S, num_kv_heads, head_dim]; positions: i32[B, S].
    Within a row positions are distinct; padded-tail positions map to
    unallocated table entries, i.e. the trash page (duplicate writes
    there are benign).
    """
    batch, chunk = positions.shape
    page_size = k_pages.shape[2]
    logical = positions // page_size                       # [B, S]
    slot = (positions % page_size).reshape(-1)             # [B*S]
    physical = jnp.take_along_axis(page_indices, logical,
                                   axis=1).reshape(-1)     # [B*S]

    def write_one(pages, new):
        flat = new.reshape(batch * chunk, *new.shape[2:])  # [BS, Hkv, D]
        return pages.at[:, physical, slot, :].set(
            jnp.swapaxes(flat, 0, 1))

    return write_one(k_pages, k_new), write_one(v_pages, v_new)


def write_kv_quant(k_pages: jax.Array, v_pages: jax.Array,
                   k_scales: jax.Array, v_scales: jax.Array,
                   k_new: jax.Array, v_new: jax.Array,
                   positions: jax.Array, page_indices: jax.Array
                   ) -> Tuple[jax.Array, jax.Array, jax.Array,
                              jax.Array]:
    """`write_kv` for an int8 pool: quantize the token's K/V rows and
    scatter values + per-slot scales in one pass. Same race-freedom
    argument (rows own distinct physical pages; trash-page collisions
    write junk over junk)."""
    page_size = k_pages.shape[2]
    logical_page = positions // page_size
    slot = positions % page_size
    batch = positions.shape[0]
    physical = page_indices[jnp.arange(batch), logical_page]  # [B]
    qk, sk = quantize_kv_rows(k_new)
    qv, sv = quantize_kv_rows(v_new)

    def write_one(pages, new):
        return pages.at[:, physical, slot, :].set(
            jnp.swapaxes(new, 0, 1))

    return (write_one(k_pages, qk), write_one(v_pages, qv),
            k_scales.at[physical, slot].set(sk),
            v_scales.at[physical, slot].set(sv))


def write_kv_chunk_quant(k_pages: jax.Array, v_pages: jax.Array,
                         k_scales: jax.Array, v_scales: jax.Array,
                         k_new: jax.Array, v_new: jax.Array,
                         positions: jax.Array,
                         page_indices: jax.Array
                         ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                    jax.Array]:
    """`write_kv_chunk` for an int8 pool: S tokens per row quantized
    (one scale per (row, position) token) and scattered with their
    scales. Padded-tail positions land in the trash page exactly as
    the bf16 write does."""
    batch, chunk = positions.shape
    page_size = k_pages.shape[2]
    logical = positions // page_size                       # [B, S]
    slot = (positions % page_size).reshape(-1)             # [B*S]
    physical = jnp.take_along_axis(page_indices, logical,
                                   axis=1).reshape(-1)     # [B*S]
    qk, sk = quantize_kv_rows(k_new)                       # sk: [B, S]
    qv, sv = quantize_kv_rows(v_new)

    def write_one(pages, new):
        flat = new.reshape(batch * chunk, *new.shape[2:])
        return pages.at[:, physical, slot, :].set(
            jnp.swapaxes(flat, 0, 1))

    return (write_one(k_pages, qk), write_one(v_pages, qv),
            k_scales.at[physical, slot].set(sk.reshape(-1)),
            v_scales.at[physical, slot].set(sv.reshape(-1)))


def gather_page_rows(arr: jax.Array, idx: jax.Array) -> jax.Array:
    """Gather whole physical pages out of a pool-shaped cache leaf
    (the export side of KV-page handoff / spill).

    Page arrays [Hkv, total_pages, page_size, D] gather along axis 1
    and come back page-major ([n, Hkv, page_size, D] — one leading
    row per page, the wire/spill layout); scale arrays
    [total_pages, page_size] gather along axis 0 ([n, page_size]).
    Pure indexing: int8 pages stay int8, bf16 stays bf16 — the
    gathered bytes ARE the pool's bytes (bit-identical round trip).
    """
    if arr.ndim == 4:
        return jnp.swapaxes(arr[:, idx], 0, 1)
    assert arr.ndim == 2, arr.shape
    return arr[idx]


def scatter_page_rows(arr: jax.Array, idx: jax.Array,
                      rows: jax.Array) -> jax.Array:
    """Inverse of `gather_page_rows`: write page-major rows back into
    a pool-shaped leaf at physical pages `idx` (the import/restore
    side). Same dtype-preserving contract."""
    if arr.ndim == 4:
        return arr.at[:, idx].set(jnp.swapaxes(rows, 0, 1))
    assert arr.ndim == 2, arr.shape
    return arr.at[idx].set(rows)


class PageAllocator:
    """Host-side free-list over the fixed physical page pool.

    Not traced: the engine calls it between steps to grow a sequence's
    page list or release a finished sequence's pages.
    """

    def __init__(self, total_pages: int, pages_per_seq: int) -> None:
        self.total_pages = total_pages
        self.pages_per_seq = pages_per_seq
        self._free: List[int] = list(range(total_pages - 1, -1, -1))
        # page 0 may be handed out like any other; rows' unused table
        # entries point at whatever page — masked out by `lengths`.

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def can_allocate(self, num_pages: int) -> bool:
        return len(self._free) >= num_pages

    def allocate(self, num_pages: int) -> List[int]:
        if not self.can_allocate(num_pages):
            raise MemoryError(
                f'paged KV cache exhausted: need {num_pages} pages, '
                f'{len(self._free)} free of {self.total_pages}')
        return [self._free.pop() for _ in range(num_pages)]

    def release(self, pages: List[int]) -> None:
        self._free.extend(pages)

    def pages_needed(self, num_tokens: int, page_size: int) -> int:
        return -(-num_tokens // page_size)  # ceil div


def init_pages(num_kv_heads: int, total_pages: int, page_size: int,
               head_dim: int, dtype=jnp.bfloat16
               ) -> Tuple[jax.Array, jax.Array]:
    shape = (num_kv_heads, total_pages, page_size, head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def paged_chunk_attention(q: jax.Array, k_pages: jax.Array,
                          v_pages: jax.Array, positions: jax.Array,
                          page_indices: jax.Array,
                          k_scales: Optional[jax.Array] = None,
                          v_scales: Optional[jax.Array] = None,
                          impl: str = 'auto') -> jax.Array:
    """S queries per row over the row's FULL paged history.

    The paged analog of ops.attention.chunked_cache_attention's read
    side: query s of row b attends every cache index <= positions[b, s]
    — what speculative-decoding verification chunks need (the chunk's
    K/V must already be written via `write_kv_chunk`). Chunk sizes are
    small (draft_k + 1), so the gather-based XLA path is a fine shape;
    the fused kernel (ops/pallas_paged.py) handles S>1 blocks natively
    and takes over when `impl` resolves to it — on int8 pools that
    again skips the HBM dequantize-materialize step.

    q: [B, S, num_q_heads, head_dim]; positions: i32[B, S].
    Returns [B, S, num_q_heads, head_dim] (q.dtype).
    """
    from skypilot_tpu.ops import pallas_paged
    resolved = pallas_paged.resolve_impl(impl,
                                         quantized=k_scales is not None)
    if resolved in ('fused', 'fused_interpret'):
        return pallas_paged.fused_paged_attention(
            q, k_pages, v_pages, positions, page_indices,
            k_scales=k_scales, v_scales=v_scales,
            interpret=resolved == 'fused_interpret')
    head_dim = k_pages.shape[-1]
    max_len = page_indices.shape[1] * k_pages.shape[2]
    k_all, v_all = _gather_kv(q.shape[2], k_pages, v_pages,
                              page_indices, k_scales, v_scales)

    scale = 1.0 / (head_dim ** 0.5)
    s = jnp.einsum('bshd,bthd->bhst', q.astype(jnp.float32),
                   k_all.astype(jnp.float32)) * scale
    mask = (jnp.arange(max_len)[None, None, :]
            <= positions[:, :, None])[:, None]              # [B,1,S,T]
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum('bhst,bthd->bshd', p, v_all.astype(jnp.float32))
    return out.astype(q.dtype)
