"""Paged KV-cache attention for LM serving.

The vLLM idea, TPU-native: instead of one dense [B, max_len] KV cache
per slot (allocated for the worst case), K/V live in fixed-size pages
shared by all slots; each sequence owns a page list. Total page count
is sized for the *aggregate* live tokens, so many short sequences fit
where the dense layout would exhaust HBM — more decode slots, higher
serving throughput.

On TPU the attention reads dispatch to the pallas paged-attention
kernel (jax.experimental.pallas.ops.tpu.paged_attention — blockwise
page gathers in VMEM); elsewhere a pure-XLA reference (gather + masked
attention) keeps the path testable and correct. The reference also
defines the semantics the kernel is tested against on TPU.

Layouts (matching the pallas kernel):
  q            [B, num_q_heads, head_dim]      one decode token per row
  k/v_pages    [num_kv_heads, total_pages, page_size, head_dim]
  lengths      i32[B]   tokens already in the cache (incl. current)
  page_indices i32[B, pages_per_seq]  physical page ids per sequence

Page allocation is host-side (`PageAllocator`): XLA needs static
shapes, so the device arrays are fixed-size and the allocator only
decides which physical pages a sequence uses.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp


@functools.lru_cache(maxsize=1)
def _pallas_paged_available() -> bool:
    if jax.default_backend() != 'tpu':
        return False
    try:
        from jax.experimental.pallas.ops.tpu.paged_attention import (  # noqa: F401
            paged_attention)
        return True
    except ImportError:
        return False


def paged_decode_attention(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, lengths: jax.Array,
                           page_indices: jax.Array,
                           *, impl: str = 'auto') -> jax.Array:
    """Attention of one query token per row over its paged KV history.

    Returns [B, num_q_heads, head_dim] (q.dtype). GQA: num_q_heads may
    be a multiple of num_kv_heads.
    """
    assert q.ndim == 3 and k_pages.ndim == 4, (q.shape, k_pages.shape)
    use_kernel = (impl == 'kernel' or
                  (impl == 'auto' and _pallas_paged_available()))
    if use_kernel:
        from jax.experimental.pallas.ops.tpu.paged_attention import (
            paged_attention)
        pages_per_seq = page_indices.shape[1]
        # Block size must divide the per-sequence page walk.
        block = min(8, pages_per_seq)
        while pages_per_seq % block != 0:
            block -= 1
        # The pallas kernel applies NO attention scaling internally
        # (its qk is a raw einsum) — pre-scale q to match the
        # reference semantics (MaxText does the same).
        scale = 1.0 / (q.shape[-1] ** 0.5)
        return paged_attention(q * scale, k_pages, v_pages, lengths,
                               page_indices,
                               pages_per_compute_block=block)
    return _reference_paged_attention(q, k_pages, v_pages, lengths,
                                      page_indices)


def _gather_kv(q_heads: int, k_pages: jax.Array, v_pages: jax.Array,
               page_indices: jax.Array
               ) -> Tuple[jax.Array, jax.Array]:
    """Per-row page gather + GQA head expansion: the shared read side
    of every XLA paged-attention path. Returns k/v as [B, T, Hq, D]
    where T = pages_per_seq * page_size."""
    num_kv_heads, _, page_size, head_dim = k_pages.shape
    max_len = page_indices.shape[1] * page_size

    # [Hkv, pages, page, D] -> [T, Hkv, D], per row.
    def gather_row(pages, idx):
        g = pages[:, idx]                       # [Hkv, pages, page, D]
        g = jnp.swapaxes(g, 0, 1)               # [pages, Hkv, page, D]
        g = jnp.swapaxes(g, 1, 2)               # [pages, page, Hkv, D]
        return g.reshape(max_len, num_kv_heads, head_dim)

    k_all = jax.vmap(gather_row, in_axes=(None, 0))(k_pages, page_indices)
    v_all = jax.vmap(gather_row, in_axes=(None, 0))(v_pages, page_indices)
    if q_heads != num_kv_heads:
        rep = q_heads // num_kv_heads
        k_all = jnp.repeat(k_all, rep, axis=2)
        v_all = jnp.repeat(v_all, rep, axis=2)
    return k_all, v_all


def _reference_paged_attention(q: jax.Array, k_pages: jax.Array,
                               v_pages: jax.Array, lengths: jax.Array,
                               page_indices: jax.Array) -> jax.Array:
    """Pure-XLA semantics: gather each row's pages, masked softmax."""
    head_dim = k_pages.shape[-1]
    max_len = page_indices.shape[1] * k_pages.shape[2]
    k_all, v_all = _gather_kv(q.shape[1], k_pages, v_pages, page_indices)

    scale = 1.0 / (head_dim ** 0.5)
    s = jnp.einsum('bhd,bkhd->bhk', q.astype(jnp.float32),
                   k_all.astype(jnp.float32)) * scale
    mask = (jnp.arange(max_len)[None, :] < lengths[:, None])[:, None, :]
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum('bhk,bkhd->bhd', p, v_all.astype(jnp.float32))
    return out.astype(q.dtype)


def write_kv(k_pages: jax.Array, v_pages: jax.Array, k_new: jax.Array,
             v_new: jax.Array, positions: jax.Array,
             page_indices: jax.Array
             ) -> Tuple[jax.Array, jax.Array]:
    """Write one token's K/V per row at its position's page slot.

    k_new/v_new: [B, num_kv_heads, head_dim]; positions: i32[B] (the
    index the token lands at, i.e. lengths - 1 after admission);
    returns updated (k_pages, v_pages). Rows write distinct physical
    pages (the allocator guarantees no sharing), so a scatter over
    (page, slot) pairs is race-free.
    """
    page_size = k_pages.shape[2]
    logical_page = positions // page_size
    slot = positions % page_size
    batch = positions.shape[0]
    physical = page_indices[jnp.arange(batch), logical_page]  # [B]

    # [Hkv, P, page, D] scatter at (:, physical[b], slot[b], :) = new[b]
    def write_one(pages, new):
        # pages: [Hkv, P, page, D]; new: [B, Hkv, D]
        return pages.at[:, physical, slot, :].set(
            jnp.swapaxes(new, 0, 1))

    return write_one(k_pages, k_new), write_one(v_pages, v_new)


def write_kv_chunk(k_pages: jax.Array, v_pages: jax.Array,
                   k_new: jax.Array, v_new: jax.Array,
                   positions: jax.Array, page_indices: jax.Array
                   ) -> Tuple[jax.Array, jax.Array]:
    """Chunked-prefill write: S tokens per row in one scatter.

    k_new/v_new: [B, S, num_kv_heads, head_dim]; positions: i32[B, S].
    Within a row positions are distinct; padded-tail positions map to
    unallocated table entries, i.e. the trash page (duplicate writes
    there are benign).
    """
    batch, chunk = positions.shape
    page_size = k_pages.shape[2]
    logical = positions // page_size                       # [B, S]
    slot = (positions % page_size).reshape(-1)             # [B*S]
    physical = jnp.take_along_axis(page_indices, logical,
                                   axis=1).reshape(-1)     # [B*S]

    def write_one(pages, new):
        flat = new.reshape(batch * chunk, *new.shape[2:])  # [BS, Hkv, D]
        return pages.at[:, physical, slot, :].set(
            jnp.swapaxes(flat, 0, 1))

    return write_one(k_pages, k_new), write_one(v_pages, v_new)


class PageAllocator:
    """Host-side free-list over the fixed physical page pool.

    Not traced: the engine calls it between steps to grow a sequence's
    page list or release a finished sequence's pages.
    """

    def __init__(self, total_pages: int, pages_per_seq: int) -> None:
        self.total_pages = total_pages
        self.pages_per_seq = pages_per_seq
        self._free: List[int] = list(range(total_pages - 1, -1, -1))
        # page 0 may be handed out like any other; rows' unused table
        # entries point at whatever page — masked out by `lengths`.

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def can_allocate(self, num_pages: int) -> bool:
        return len(self._free) >= num_pages

    def allocate(self, num_pages: int) -> List[int]:
        if not self.can_allocate(num_pages):
            raise MemoryError(
                f'paged KV cache exhausted: need {num_pages} pages, '
                f'{len(self._free)} free of {self.total_pages}')
        return [self._free.pop() for _ in range(num_pages)]

    def release(self, pages: List[int]) -> None:
        self._free.extend(pages)

    def pages_needed(self, num_tokens: int, page_size: int) -> int:
        return -(-num_tokens // page_size)  # ceil div


def init_pages(num_kv_heads: int, total_pages: int, page_size: int,
               head_dim: int, dtype=jnp.bfloat16
               ) -> Tuple[jax.Array, jax.Array]:
    shape = (num_kv_heads, total_pages, page_size, head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def paged_chunk_attention(q: jax.Array, k_pages: jax.Array,
                          v_pages: jax.Array, positions: jax.Array,
                          page_indices: jax.Array) -> jax.Array:
    """S queries per row over the row's FULL paged history.

    The paged analog of ops.attention.chunked_cache_attention's read
    side: query s of row b attends every cache index <= positions[b, s]
    — what speculative-decoding verification chunks need (the chunk's
    K/V must already be written via `write_kv_chunk`). Chunk sizes are
    small (draft_k + 1), so the gather-based XLA path is the right
    shape everywhere; the pallas decode kernel stays the S=1 fast path.

    q: [B, S, num_q_heads, head_dim]; positions: i32[B, S].
    Returns [B, S, num_q_heads, head_dim] (q.dtype).
    """
    head_dim = k_pages.shape[-1]
    max_len = page_indices.shape[1] * k_pages.shape[2]
    k_all, v_all = _gather_kv(q.shape[2], k_pages, v_pages, page_indices)

    scale = 1.0 / (head_dim ** 0.5)
    s = jnp.einsum('bshd,bthd->bhst', q.astype(jnp.float32),
                   k_all.astype(jnp.float32)) * scale
    mask = (jnp.arange(max_len)[None, None, :]
            <= positions[:, :, None])[:, None]              # [B,1,S,T]
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum('bhst,bthd->bshd', p, v_all.astype(jnp.float32))
    return out.astype(q.dtype)
