"""Attention dispatch: pallas TPU flash attention when profitable.

MXU-friendly attention for the recipe models. On TPU with long enough
sequences, uses the pallas flash-attention kernel (blockwise softmax,
O(S) memory, no S×S materialization in HBM); otherwise falls back to
`jax.nn.dot_product_attention` (XLA fuses the mask+softmax chain).

Layout convention: q/k/v are [batch, seq, heads, head_dim] (BSHD).
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

# Measured on v5e (GPT-2 124M, B=8 S=1024 H=12 D=64): the pallas flash
# kernel's fwd+bwd LOSES to XLA's fused attention by ~45ms/step (148 vs
# 103 ms — 24% vs 35% MFU); its O(S) memory only pays off once the S×S
# scores stop fitting in VMEM-friendly fusions. Dispatch to pallas only
# from 2k context up; override via SKYPILOT_TPU_FLASH_MIN_SEQ.
try:
    _FLASH_MIN_SEQ = int(
        os.environ.get('SKYPILOT_TPU_FLASH_MIN_SEQ') or 2048)
except ValueError:
    _FLASH_MIN_SEQ = 2048


@functools.lru_cache(maxsize=1)
def _pallas_flash_available() -> bool:
    if jax.default_backend() != 'tpu':
        return False
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention  # noqa: F401
        return True
    except ImportError:
        return False


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          *, causal: bool = True,
                          impl: str = 'auto') -> jax.Array:
    """q: [B,S,H,D]; k/v: [B,S,Hkv,D] (GQA allowed). Returns [B,S,H,D]."""
    assert q.ndim == 4 and k.ndim == 4 and v.ndim == 4, (q.shape, k.shape)
    if v.shape[-1] != q.shape[-1]:
        # Mismatched value dim (MLA: qk_head_dim != v_head_dim). Must
        # be decided BEFORE the ring/flash dispatch: both kernels
        # require equal q/k/v dims. einsum + f32 softmax fuses fine
        # under XLA — but on a seq-sharded mesh this forfeits the ring
        # path's O(S/shards) memory guarantee, so say so (trace-time).
        from skypilot_tpu.parallel import context as cp_context
        if cp_context.active_seq_mesh() is not None:
            import warnings
            warnings.warn(
                'context parallelism requested (seq-sharded mesh) but '
                f'v_head_dim={v.shape[-1]} != qk_head_dim={q.shape[-1]} '
                '(MLA): ring attention does not support unequal dims, '
                'falling back to materialized S x S scores under GSPMD '
                '— results are correct but per-shard attention memory '
                'is O(S), not O(S/shards).', stacklevel=2)
        return _unequal_dims_attention(q, k, v, causal=causal)
    # Context parallelism: a seq-sharded mesh switches to ring attention.
    from skypilot_tpu.parallel import context as cp_context
    seq_mesh = cp_context.active_seq_mesh()
    if seq_mesh is not None and impl in ('auto', 'ring'):
        from skypilot_tpu.ops import ring_attention as ra
        num_q_heads, num_kv_heads = q.shape[2], k.shape[2]
        if num_kv_heads != num_q_heads:
            rep = num_q_heads // num_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        heads_axis = 'tensor' if seq_mesh.shape.get('tensor', 1) > 1 else None
        return ra.ring_attention(q, k, v, mesh=seq_mesh, causal=causal,
                                 heads_axis=heads_axis)
    seq_len = q.shape[1]
    use_flash = (impl == 'flash' or
                 (impl == 'auto' and _pallas_flash_available() and
                  seq_len >= _FLASH_MIN_SEQ))
    if use_flash:
        out = _flash(q, k, v, causal=causal)
        if out is not None:
            return out
    # GQA: expand kv heads to q heads for the XLA path.
    num_q_heads, num_kv_heads = q.shape[2], k.shape[2]
    if num_kv_heads != num_q_heads:
        rep = num_q_heads // num_kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return jax.nn.dot_product_attention(q, k, v, is_causal=causal)


def _unequal_dims_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                            *, causal: bool) -> jax.Array:
    """Generic attention for v_head_dim != qk_head_dim (MLA)."""
    num_q_heads, num_kv_heads = q.shape[2], k.shape[2]
    if num_kv_heads != num_q_heads:
        rep = num_q_heads // num_kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum('bqhd,bkhd->bhqk', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        seq_q, seq_k = q.shape[1], k.shape[1]
        mask = (jnp.arange(seq_k)[None, :]
                <= jnp.arange(seq_q)[:, None])
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum('bhqk,bkhv->bqhv', p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _pallas_flash_kernel(q: jax.Array, k: jax.Array, v: jax.Array,
                         causal: bool) -> jax.Array:
    """Single-shard pallas flash attention ([B,S,H,D] in/out)."""
    from jax.experimental.pallas.ops.tpu import flash_attention as fa
    # pallas kernel wants [B,H,S,D]
    q_, k_, v_ = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    out = fa.flash_attention(q_, k_, v_, causal=causal, sm_scale=sm_scale)
    return jnp.swapaxes(out, 1, 2)


def _active_mesh():
    """The `with mesh:` context's mesh, or None.

    jax.interpreters.pxla.thread_resources is deprecated (0.8.2) with
    no public replacement for reading the context mesh yet; go through
    the underlying module directly.
    """
    try:
        from jax._src import mesh as mesh_mod
        mesh = mesh_mod.thread_resources.env.physical_mesh
    except (ImportError, AttributeError):  # jax internals moved
        from jax.interpreters import pxla
        mesh = pxla.thread_resources.env.physical_mesh
    return None if mesh.empty else mesh


def _flash(q: jax.Array, k: jax.Array, v: jax.Array, *,
           causal: bool,
           kernel=_pallas_flash_kernel) -> Optional[jax.Array]:
    """Sharding-safe flash attention; returns None when the operands
    cannot be cleanly shard_mapped (caller falls back to XLA)."""
    num_q_heads, num_kv_heads = q.shape[2], k.shape[2]
    mesh = _active_mesh()
    # Feasibility checks BEFORE the GQA expansion so the bail-out path
    # doesn't materialize a repeat the XLA fallback then redoes.
    batch_shards = 1
    batch_axes = []
    if mesh is not None and mesh.size > 1:
        for a in ('data', 'fsdp'):
            if mesh.shape.get(a, 1) > 1:
                batch_axes.append(a)
                batch_shards *= mesh.shape[a]
        if q.shape[0] % batch_shards != 0:
            return None  # caller falls back to the GSPMD-native XLA path
    if num_kv_heads != num_q_heads:
        rep = num_q_heads // num_kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if mesh is None or mesh.size == 1:
        return kernel(q, k, v, causal)
    # A pallas call is opaque to GSPMD: under a sharded jit it would be
    # REPLICATED onto every chip. shard_map it over the mesh instead —
    # batch rides the data/fsdp axes, heads ride tensor; causal masking
    # is per (batch, head) so shards are independent.
    heads_axis = ('tensor' if mesh.shape.get('tensor', 1) > 1 and
                  num_q_heads % mesh.shape['tensor'] == 0 else None)
    from skypilot_tpu.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    spec = P(tuple(batch_axes) if batch_axes else None, None, heads_axis,
             None)
    return shard_map(
        functools.partial(kernel, causal=causal), mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)(q, k, v)


def chunked_cache_attention(q: jax.Array, k_new: jax.Array,
                            v_new: jax.Array, cached_k: jax.Array,
                            cached_v: jax.Array, positions: jax.Array,
                            *, chunk_only: bool = False):
    """Multi-token cache attention at arbitrary PER-ROW offsets.

    Generalizes `cached_decode_attention` to S>=1 query chunks: writes
    this chunk's K/V at `positions[b, s]` (contiguous per row, starting
    at positions[:, 0]) and attends each query over every cache entry
    with index <= its absolute position. One op drives both chunked
    prefill (offset 0 — the old empty-cache special case) and
    speculative-decoding verification chunks (offset = current length),
    because the chunk is written BEFORE attending: any stale cache
    entries from a previous step's rejected drafts are overwritten
    before the mask can expose them. `chunk_only=True` is the prefill
    fast path: the caller guarantees the cache holds nothing below the
    offset, so attention stays chunk-local (S x S, flash-eligible)
    instead of scanning all T cache slots.

    q/k_new/v_new: [B, S, H|Hkv, D]; cached_k/v: [B, T, Hkv, D];
    positions: [B, S]. Returns (out [B,S,H,D], cached_k, cached_v).
    """
    dtype = cached_k.dtype
    max_len = cached_k.shape[1]
    start = positions[:, 0]

    def write_rows(cache_row, kv_rows, p):
        return jax.lax.dynamic_update_slice(cache_row, kv_rows, (p, 0, 0))

    cached_k = jax.vmap(write_rows)(cached_k, k_new.astype(dtype), start)
    cached_v = jax.vmap(write_rows)(cached_v, v_new.astype(dtype), start)
    if chunk_only:
        # PREFILL fast path (contract: nothing live in the cache below
        # the offset): attend only within the chunk — S x S, flash-
        # dispatchable — instead of S x T over the whole cache.
        out = dot_product_attention(q, k_new, v_new, causal=True)
        return out, cached_k, cached_v
    num_q_heads, num_kv_heads = q.shape[2], cached_k.shape[2]
    k_all, v_all = cached_k, cached_v
    if num_kv_heads != num_q_heads:
        rep = num_q_heads // num_kv_heads
        k_all = jnp.repeat(k_all, rep, axis=2)
        v_all = jnp.repeat(v_all, rep, axis=2)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum('bshd,bthd->bhst', q.astype(jnp.float32),
                   k_all.astype(jnp.float32)) * scale
    mask = (jnp.arange(max_len)[None, None, :]
            <= positions[:, :, None])[:, None]          # [B,1,S,T]
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum('bhst,bthd->bshd', p, v_all.astype(jnp.float32))
    return out.astype(q.dtype), cached_k, cached_v


def cached_decode_attention(q: jax.Array, k_new: jax.Array,
                            v_new: jax.Array, cached_k: jax.Array,
                            cached_v: jax.Array, pos: jax.Array):
    """One-token KV-cache attention with PER-ROW write positions.

    The single serving-cache contract shared by every model family
    (llama/mixtral/gpt): write this step's k/v at `pos[b]` in row b's
    cache, attend q over the cache masked to `k_idx <= pos[b]`
    (f32 softmax), with GQA expansion when q has more heads than the
    cache. Rows at different depths decode in one step — what the
    continuous-batching engine (models/batching.py) relies on.

    q/k_new/v_new: [B, 1, H|Hkv, D]; cached_k/v: [B, T, Hkv, D];
    pos: [B]. Returns (out [B, 1, H, D], cached_k, cached_v).
    """
    dtype = cached_k.dtype
    max_len = cached_k.shape[1]

    def write_row(cache_row, kv_row, p):
        return jax.lax.dynamic_update_slice(cache_row, kv_row, (p, 0, 0))

    cached_k = jax.vmap(write_row)(cached_k, k_new.astype(dtype), pos)
    cached_v = jax.vmap(write_row)(cached_v, v_new.astype(dtype), pos)
    num_q_heads, num_kv_heads = q.shape[2], cached_k.shape[2]
    k_all, v_all = cached_k, cached_v
    if num_kv_heads != num_q_heads:
        rep = num_q_heads // num_kv_heads
        k_all = jnp.repeat(k_all, rep, axis=2)
        v_all = jnp.repeat(v_all, rep, axis=2)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum('bqhd,bkhd->bhqk', q.astype(jnp.float32),
                   k_all.astype(jnp.float32)) * scale
    mask = (jnp.arange(max_len)[None, :] <= pos[:, None])[:, None, None, :]
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum('bhqk,bkhd->bqhd', p, v_all.astype(jnp.float32))
    return out.astype(q.dtype), cached_k, cached_v
