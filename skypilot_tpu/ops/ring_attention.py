"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context recipe op (task mandate; the reference launches user
ring-attention code — llm/ examples — but implements none; here it is
a framework op). Sequence (context) parallelism: q/k/v are sharded
along the mesh's `seq` axis; each step every device computes blockwise
attention of its local queries against the resident k/v block, then
rotates k/v one hop around the ring with `lax.ppermute` — ICI
neighbor-to-neighbor traffic, overlapping compute with the rotation,
O(S_local) memory per device. Online-softmax (flash-style) accumulation
in f32 keeps it exact.

Causality is by *global block position*: a k/v block that originated
downstream of the query shard is fully masked; the diagonal block uses
the triangular mask.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from skypilot_tpu.utils import jax_compat
from skypilot_tpu.utils.jax_compat import shard_map


def _online_block_update(o, m, l, s, v):
    """One flash-attention accumulation step.

    o: [B,Sq,H,D] f32 accumulator; m,l: [B,Sq,H] running max / denom;
    s: [B,Sq,H,Sk] scores; v: [B,Sk,H,D].
    """
    block_max = jnp.max(s, axis=-1)                       # [B,Sq,H]
    new_m = jnp.maximum(m, block_max)
    # Renormalize previous accumulator.
    correction = jnp.exp(m - new_m)                       # [B,Sq,H]
    p = jnp.exp(s - new_m[..., None])                     # [B,Sq,H,Sk]
    new_l = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum('bqhk,bkhd->bqhd', p, v.astype(jnp.float32))
    new_o = o * correction[..., None] + pv
    return new_o, new_m, new_l


def _ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                            axis_name: str, causal: bool,
                            vary_axes: Tuple[str, ...] = ()) -> jax.Array:
    """Runs on each shard: q,k,v are the LOCAL [B,Sl,H,D] blocks."""
    vary_axes = tuple(vary_axes) or (axis_name,)
    num_shards = jax_compat.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    batch, s_local, num_heads, head_dim = q.shape
    scale = 1.0 / (head_dim ** 0.5)
    q32 = q.astype(jnp.float32) * scale

    # Mark accumulators device-varying over every axis the inputs vary
    # on, so the fori_loop carry type stays stable once they mix with
    # per-shard data (jax>=0.9 spells pvary as pcast(to='varying');
    # pre-vma jax has no such type system and the shim is identity).
    def _vary(x):
        return jax_compat.pvary(x, vary_axes)

    o = _vary(jnp.zeros((batch, s_local, num_heads, head_dim), jnp.float32))
    m = _vary(jnp.full((batch, s_local, num_heads), -jnp.inf, jnp.float32))
    l = _vary(jnp.zeros((batch, s_local, num_heads), jnp.float32))

    if causal:
        tri = jnp.tril(jnp.ones((s_local, s_local), bool))  # [Sq,Sk]

    def body(step, carry):
        o, m, l, k_blk, v_blk = carry
        src = (my_idx - step) % num_shards  # which block k_blk came from
        s = jnp.einsum('bqhd,bkhd->bqhk', q32, k_blk.astype(jnp.float32))
        if causal:
            # Block-level causality + diagonal triangular mask.
            fully_visible = src < my_idx
            diagonal = src == my_idx
            mask = jnp.where(
                diagonal,
                tri[None, :, None, :],
                jnp.full((1, s_local, 1, s_local), fully_visible))
            s = jnp.where(mask, s, -jnp.inf)
        o, m, l = _online_block_update(o, m, l, s, v_blk)
        perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return o, m, l, k_blk, v_blk

    o, m, l, _, _ = lax.fori_loop(0, num_shards, body, (o, m, l, k, v))
    # Fully-masked rows (none under causal with left-to-right layout,
    # but guard anyway): l == 0 → output 0.
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return (o / safe_l[..., None]).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   mesh: Mesh, seq_axis: str = 'seq',
                   batch_axes: Tuple[str, ...] = ('data', 'fsdp'),
                   heads_axis: Optional[str] = 'tensor',
                   causal: bool = True) -> jax.Array:
    """Exact attention with q/k/v sharded along `seq_axis`.

    q/k/v: [B, S, H, D] global shapes; S must divide evenly by the seq
    axis size. GQA callers must pre-expand kv heads.
    """
    assert q.shape == k.shape == v.shape, (q.shape, k.shape)
    spec = P(batch_axes, seq_axis, heads_axis, None)
    vary_axes = tuple(batch_axes) + (seq_axis,)
    if heads_axis is not None:
        vary_axes += (heads_axis,)
    fn = shard_map(
        functools.partial(_ring_attention_sharded, axis_name=seq_axis,
                          causal=causal, vary_axes=vary_axes),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True) -> jax.Array:
    """Plain full attention (for numerical comparison in tests)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum('bqhd,bkhd->bqhk', q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        seq = q.shape[1]
        mask = jnp.tril(jnp.ones((seq, seq), bool))
        s = jnp.where(mask[None, :, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bqhk,bkhd->bqhd', p,
                      v.astype(jnp.float32)).astype(q.dtype)
