"""In-repo fused Pallas paged-attention + LoRA kernels (the int8 fast
path) and the impl-dispatch plumbing that selects between them.

WHY. Decode is memory-bound: per-chip tokens/s is HBM bytes/token or
nothing. The upstream pallas paged-attention kernel
(jax.experimental.pallas.ops.tpu.paged_attention) is bf16-only, so the
int8 KV pool — the config that doubled pool capacity — used to fall
back to the XLA gather route, which DEQUANTIZES IN HBM: it
materializes f32 copies of every gathered page (then GQA-expands
them) each step. Per-slot LoRA likewise paid one batched
gather+matmul chain per projection. The two kernels here close both
gaps:

  fused_paged_attention   reads int8 k/v pages plus their parallel
                          f32 scale rows straight from the pool and
                          dequantizes IN-REGISTER inside the kernel
                          body — HBM sees only the int8 bytes and the
                          scales, never a dequantized page. One grid
                          (batch, kv_heads, pages_per_seq) walks each
                          row's page table via scalar prefetch; online
                          softmax accumulates across the page walk in
                          VMEM scratch. Handles bf16 pools too, and
                          both block shapes the engine issues: S=1
                          decode and S>1 chunked prefill / speculative
                          verification chunks (`positions[b, s]` is the
                          per-query causal bound, exactly the XLA
                          reference's mask).
  fused_qkv_lora_delta    ONE pallas dispatch for the wq/wk/wv LoRA
                          deltas of a multi-tenant batch: adapter ids
                          ride scalar prefetch, each row's a/b factors
                          are gathered by BlockSpec index_maps, and the
                          three (x @ a) @ b chains run in one kernel
                          body instead of three separate gather+matmul
                          dispatches per layer.

DISPATCH. `resolve_impl(impl, quantized=...)` maps a requested impl to
the concrete route; 'auto' consults, in order: an explicit
`set_default_impl()` / `impl_scope()` override, the
SKYPILOT_TPU_PAGED_IMPL environment variable, then backend defaults
(TPU quantized -> 'fused'; TPU bf16 -> upstream 'kernel'; anything
else -> 'xla'). Unavailable routes degrade silently to 'xla' — the
reference path is always correct, just slower. `unavailable_reason()`
records WHY the compiled kernel path is off (mirroring
data/token_loader.native_unavailable_reason) so /stats and test skip
messages can say so.

INTERPRET-MODE CONTRACT. Every pallas_call here takes
`interpret=<kwarg>` (enforced repo-wide by `stpu check` rule SKY006),
so the kernels run on CPU under `impl='fused_interpret'` —
bit-tolerance pinned against the XLA reference in
tests/unit_tests/test_pallas_paged.py, with a deliberately perturbed
kernel (the `perturb` hook below) proving the pins are non-vacuous.

SHARDING. Under an active `with mesh:` context the attention wrapper
shard_maps over the PR 15 pool layout: kv-heads (and the grouped q
heads) ride `tensor` when divisible, everything else replicates; the
GQA-remainder rule (kv-heads not divisible by tensor -> replicated
pool) falls out as the unsharded call. Without a mesh context (the
GSPMD-propagation serving path) the call runs as a single program —
correct everywhere, though GSPMD treats it as an opaque replicated
region, so sharded-pool TPU deployments should enter the mesh context
before forcing 'fused'.

ROOFLINE. `bytes_per_token_model()` is the analytic HBM-traffic model
(pool reads + scale rows + XLA dequant materialization + amortized
weight reads + LoRA factor rows) that benchmarks/serve_bench.py emits
next to achieved tokens/s, scoring runs as a fraction of the modeled
HBM limit rather than vs yesterday's number.
"""
from __future__ import annotations

import contextlib
import functools
import os
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

ENV_VAR = 'SKYPILOT_TPU_PAGED_IMPL'

#: Accepted impl names: 'auto' resolves per backend/config; 'xla' is
#: the gather reference; 'kernel' the upstream bf16 pallas kernel;
#: 'fused' this module's compiled kernels; 'fused_interpret' the same
#: kernels in pallas interpret mode (runs anywhere, CPU included).
IMPLS: Tuple[str, ...] = ('auto', 'xla', 'kernel', 'fused',
                          'fused_interpret')

# -- availability probes (module-level cache + recorded reason) -------------
_probed = False
_import_error: Optional[str] = None


def _probe() -> None:
    global _probed, _import_error
    if _probed:
        return
    _probed = True
    try:
        from jax.experimental import pallas  # noqa: F401
        from jax.experimental.pallas import tpu  # noqa: F401
    except ImportError as e:  # no pallas in this jax build
        _import_error = f'pallas import failed: {e}'


def pallas_importable() -> bool:
    """True when the pallas + pallas-TPU modules import here (the
    floor for `fused_interpret`, which needs no TPU)."""
    _probe()
    return _import_error is None


def available() -> bool:
    """True when the COMPILED fused kernel path can run here (pallas
    imports and the default backend is TPU)."""
    return pallas_importable() and jax.default_backend() == 'tpu'


def unavailable_reason() -> Optional[str]:
    """None when `available()`; otherwise why the compiled kernel path
    is off — surfaced in /stats' storage section and test skips."""
    _probe()
    if _import_error is not None:
        return _import_error
    backend = jax.default_backend()
    if backend != 'tpu':
        return (f"backend is {backend!r}: the fused kernel compiles on "
                f"TPU only (impl='fused_interpret' still runs here)")
    return None


@functools.lru_cache(maxsize=1)
def upstream_available() -> bool:
    """Upstream bf16 pallas paged-attention kernel (`impl='kernel'`)."""
    if jax.default_backend() != 'tpu':
        return False
    try:
        from jax.experimental.pallas.ops.tpu.paged_attention import (  # noqa: F401
            paged_attention)
        return True
    except ImportError:
        return False


# -- impl selection ---------------------------------------------------------
_default_impl: Optional[str] = None


def _validate(impl: str) -> None:
    if impl not in IMPLS:
        raise ValueError(
            f'unknown paged-attention impl {impl!r} (choices: '
            f'{", ".join(IMPLS)}; also accepted via ${ENV_VAR})')


def default_impl() -> str:
    """The impl 'auto' resolves through: the `set_default_impl()`
    override, else $SKYPILOT_TPU_PAGED_IMPL, else 'auto' itself."""
    if _default_impl is not None:
        return _default_impl
    env = os.environ.get(ENV_VAR, '').strip()
    if env:
        _validate(env)
        return env
    return 'auto'


def set_default_impl(impl: Optional[str]) -> None:
    """Process-wide impl override (None clears it). Set BEFORE the
    first traced forward pass: dispatch resolves at trace time, so a
    change after jit caches are warm does not retrace."""
    if impl is not None:
        _validate(impl)
    global _default_impl
    _default_impl = impl


@contextlib.contextmanager
def impl_scope(impl: str):
    """Scoped `set_default_impl` — the test/bench A/B hook."""
    prev = _default_impl
    set_default_impl(impl)
    try:
        yield
    finally:
        set_default_impl(prev)


def resolve_impl(impl: str = 'auto', *, quantized: bool = False) -> str:
    """Concrete route for a requested impl: one of 'xla' | 'kernel' |
    'fused' | 'fused_interpret'.

    'auto' prefers the fused kernel for quantized pools on TPU and the
    upstream kernel for bf16 (matching the pre-fused fast path);
    unavailable routes degrade to 'xla', and 'kernel' degrades for
    quantized pools (the upstream kernel is bf16-only)."""
    _validate(impl)
    if impl == 'auto':
        impl = default_impl()
    if impl == 'auto':
        if not available():
            return 'xla'
        if quantized:
            return 'fused'
        return 'kernel' if upstream_available() else 'fused'
    if impl == 'kernel' and (quantized or not upstream_available()):
        return 'xla'
    if impl == 'fused' and not available():
        return 'xla'
    if impl == 'fused_interpret' and not pallas_importable():
        return 'xla'
    return impl


def lora_fusion_impl(quantized: bool = False) -> Optional[str]:
    """'fused' / 'fused_interpret' when the QKV LoRA fusion should
    engage under the current dispatch state, else None (models call
    this at trace time next to the attention dispatch)."""
    impl = resolve_impl('auto', quantized=quantized)
    return impl if impl in ('fused', 'fused_interpret') else None


# -- fused paged attention --------------------------------------------------
def _attention_kernel(quantized, sm_scale, page_size, pages_per_seq,
                      perturb, tbl_ref, pos_ref, q_ref, k_ref, v_ref,
                      *rest):
    """Grid (batch, kv_heads, pages_per_seq): one physical page of one
    kv head per step, online-softmax state in VMEM scratch."""
    import jax.experimental.pallas as pl
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, -jnp.inf, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    q = q_ref[0].astype(jnp.float32)            # [S, G, D]
    k = k_ref[0, 0].astype(jnp.float32)         # [page, D]
    v = v_ref[0, 0].astype(jnp.float32)
    if quantized:
        # In-register dequant: int8 page values * the page's f32
        # per-slot scale rows. No dequantized page ever exists in HBM.
        k = k * ks_ref[0][:, None]
        v = v * vs_ref[0][:, None]
    s = jnp.einsum('sgd,td->sgt', q, k) * sm_scale
    if perturb:
        # Non-vacuity hook: a deliberately wrong kernel for tests to
        # prove the parity pins actually bite. Scores are SCALED (a
        # temperature error) — an additive constant would be invisible
        # under softmax's shift invariance.
        s = s * (1.0 + perturb)
    t_idx = (p * page_size +
             jax.lax.broadcasted_iota(jnp.int32, s.shape, 2))
    pos = pos_ref[b]                            # [S] causal bounds
    s = jnp.where(t_idx <= pos[:, None, None], s, -jnp.inf)

    m_prev = m_ref[...]                         # [S, G]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    # All-masked rows keep m == -inf; shifting by 0 there keeps every
    # exp() argument finite-or--inf (exp(-inf) == 0, never a nan).
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.exp(m_prev - m_safe)
    w = jnp.exp(s - m_safe[..., None])
    l_ref[...] = l_ref[...] * alpha + jnp.sum(w, axis=-1)
    acc_ref[...] = (acc_ref[...] * alpha[..., None] +
                    jnp.einsum('sgt,td->sgd', w, v))
    m_ref[...] = m_new

    @pl.when(p == pages_per_seq - 1)
    def _finish():
        l = l_ref[...]
        l = jnp.where(l > 0, l, 1.0)            # fully-masked rows -> 0
        o_ref[0] = (acc_ref[...] / l[..., None]).astype(o_ref.dtype)


def _fused_call(q, k_pages, v_pages, positions, page_indices,
                k_scales, v_scales, *, interpret, perturb):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    batch, chunk, num_q_heads, head_dim = q.shape
    num_kv_heads, _, page_size, _ = k_pages.shape
    pages_per_seq = page_indices.shape[1]
    group = num_q_heads // num_kv_heads
    quantized = k_scales is not None
    sm_scale = 1.0 / (head_dim ** 0.5)
    kernel = functools.partial(_attention_kernel, quantized, sm_scale,
                               page_size, pages_per_seq, perturb)

    # Index maps see the scalar-prefetch refs (page table, positions):
    # the page walk gathers SCATTERED physical pages into VMEM blocks.
    def q_map(b, h, p, tbl, pos):
        return (b, 0, h, 0)

    def kv_map(b, h, p, tbl, pos):
        return (h, tbl[b, p], 0, 0)

    def scale_map(b, h, p, tbl, pos):
        return (tbl[b, p], 0)

    in_specs = [
        pl.BlockSpec((1, chunk, group, head_dim), q_map),
        pl.BlockSpec((1, 1, page_size, head_dim), kv_map),
        pl.BlockSpec((1, 1, page_size, head_dim), kv_map),
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        in_specs += [pl.BlockSpec((1, page_size), scale_map),
                     pl.BlockSpec((1, page_size), scale_map)]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, num_kv_heads, pages_per_seq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, chunk, group, head_dim), q_map),
        scratch_shapes=[
            pltpu.VMEM((chunk, group), jnp.float32),
            pltpu.VMEM((chunk, group), jnp.float32),
            pltpu.VMEM((chunk, group, head_dim), jnp.float32),
        ])
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(page_indices, positions.astype(jnp.int32), *operands)


def fused_paged_attention(q: jax.Array, k_pages: jax.Array,
                          v_pages: jax.Array, positions: jax.Array,
                          page_indices: jax.Array, *,
                          k_scales: Optional[jax.Array] = None,
                          v_scales: Optional[jax.Array] = None,
                          interpret: bool = False,
                          perturb: float = 0.0) -> jax.Array:
    """Fused paged attention over int8 or bf16 pools.

    q: [B, S, Hq, D]; positions: i32[B, S] — query s of row b attends
    every cache index <= positions[b, s] (decode is S=1 with
    positions = lengths - 1; chunks pass their absolute positions).
    k/v_pages: [Hkv, total_pages, page_size, D]; k/v_scales
    (f32[total_pages, page_size]) mark an int8 pool and are
    dequantized in-register. Returns [B, S, Hq, D] in q.dtype,
    matching `_reference_paged_attention` semantics.

    Under an active mesh context with a divisible kv-heads axis the
    call shard_maps over `tensor` (pool sharded, tables/scales
    replicated); otherwise — including the PR 15 GQA-remainder
    replicated-pool layout — it runs unsharded.
    """
    assert q.ndim == 4 and k_pages.ndim == 4, (q.shape, k_pages.shape)
    num_kv_heads = k_pages.shape[0]
    assert q.shape[2] % num_kv_heads == 0, (q.shape, k_pages.shape)
    call = functools.partial(_fused_call, interpret=interpret,
                             perturb=perturb)
    from skypilot_tpu.ops.attention import _active_mesh
    mesh = _active_mesh()
    tensor = mesh.shape.get('tensor', 1) if mesh is not None else 1
    if tensor <= 1 or num_kv_heads % tensor != 0:
        return call(q, k_pages, v_pages, positions, page_indices,
                    k_scales, v_scales)
    from jax.sharding import PartitionSpec as P
    from skypilot_tpu.utils.jax_compat import shard_map
    qspec = P(None, None, 'tensor', None)       # grouped q heads
    pool = P('tensor', None, None, None)        # kv-heads axis
    rep = P(None, None)
    if k_scales is None:
        fn = lambda q_, kp, vp, pos, tbl: call(q_, kp, vp, pos, tbl,
                                               None, None)
        in_specs = (qspec, pool, pool, rep, rep)
        args = (q, k_pages, v_pages, positions, page_indices)
    else:
        fn = call
        in_specs = (qspec, pool, pool, rep, rep, rep, rep)
        args = (q, k_pages, v_pages, positions, page_indices,
                k_scales, v_scales)
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=qspec, check_vma=False)(*args)


# -- fused QKV LoRA ---------------------------------------------------------
def _qkv_lora_kernel(ids_ref, x_ref, aq_ref, bq_ref, ak_ref, bk_ref,
                     av_ref, bv_ref, dq_ref, dk_ref, dv_ref):
    x = x_ref[0].astype(jnp.float32)            # [S, d_model]
    for a_ref, b_ref, o_ref in ((aq_ref, bq_ref, dq_ref),
                                (ak_ref, bk_ref, dk_ref),
                                (av_ref, bv_ref, dv_ref)):
        h = x @ a_ref[0].astype(jnp.float32)    # [S, r]
        o_ref[0] = h @ b_ref[0].astype(jnp.float32)


def fused_qkv_lora_delta(x: jax.Array, wq_factors: Dict,
                         wk_factors: Dict, wv_factors: Dict,
                         adapter_ids: jax.Array, *,
                         interpret: bool = False
                         ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """UNSCALED f32 LoRA deltas for wq/wk/wv in ONE pallas dispatch.

    x: [B, S, d_model]; each factors dict holds stacked
    a [N, d_in, r] / b [N, r, d_out]; adapter_ids i32[B] selects each
    row's adapter via scalar-prefetch index_maps (no gathered factor
    copies in HBM). Returns (dq, dk, dv) as f32 [B, S, d_out]; the
    caller applies `y + (scale * d).astype(y.dtype)` so numerics match
    `lora.apply_delta` — same (x @ a) @ b contraction order in f32.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    batch, chunk, d_model = x.shape

    def x_map(b, ids):
        return (b, 0, 0)

    def factor_map(b, ids):
        return (ids[b], 0, 0)

    in_specs = [pl.BlockSpec((1, chunk, d_model), x_map)]
    operands = [x]
    out_shapes = []
    out_specs = []
    for f in (wq_factors, wk_factors, wv_factors):
        a, b_fac = f['a'], f['b']
        _, d_in, rank = a.shape
        d_out = b_fac.shape[-1]
        in_specs += [pl.BlockSpec((1, d_in, rank), factor_map),
                     pl.BlockSpec((1, rank, d_out), factor_map)]
        operands += [a, b_fac]
        out_shapes.append(
            jax.ShapeDtypeStruct((batch, chunk, d_out), jnp.float32))
        out_specs.append(pl.BlockSpec((1, chunk, d_out), x_map))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(batch,),
        in_specs=in_specs, out_specs=out_specs)
    return pl.pallas_call(
        _qkv_lora_kernel, grid_spec=grid_spec, out_shape=out_shapes,
        interpret=interpret,
    )(adapter_ids.astype(jnp.int32), *operands)


def qkv_lora_dispatches_per_layer(impl: str) -> int:
    """Batched-LoRA dispatch count for the three QKV projections of
    one layer: the fused kernel folds them into ONE call; the unfused
    route issues one gather+matmul chain per projection."""
    return 1 if impl in ('fused', 'fused_interpret') else 3


# -- analytic HBM roofline --------------------------------------------------
def bytes_per_token_model(*, num_layers: int, num_kv_heads: int,
                          num_q_heads: int, head_dim: int,
                          page_size: int, pages_per_seq: int,
                          kv_elem_bytes: int, quantized: bool,
                          impl: str, weight_bytes: int = 0,
                          batch: int = 1,
                          lora_bytes_per_row: int = 0
                          ) -> Dict[str, float]:
    """Modeled HBM bytes one decode step moves PER SEQUENCE (= per
    generated token), from the engine's actual page geometry.

    Both routes walk the row's FULL page table every step (the length
    mask shapes the math, not the reads), so context traffic is
    static per config. Per layer:

      pool reads    2 * pages_per_seq * page_size * Hkv * D * elem
      scale rows    2 * pages_per_seq * page_size * 4        (int8)
      xla dequant   the gather route additionally materializes
                    dequantized + GQA-expanded [T, Hq, D] copies of k
                    and v in HBM — one write + one read each. This is
                    the term the fused kernel deletes.

    Whole-model terms: weight reads amortize over the decode batch
    (weights stream once per step); each row re-reads its adapter's
    LoRA factor rows (`lora_bytes_per_row` — identical bytes fused or
    not, the fusion saves dispatches, not factor traffic).
    """
    tokens_walked = pages_per_seq * page_size
    pool = (2 * tokens_walked * num_kv_heads * head_dim
            * kv_elem_bytes * num_layers)
    scales = (2 * tokens_walked * 4 * num_layers) if quantized else 0
    dequant = 0
    if impl == 'xla':
        elem = 4 if quantized else kv_elem_bytes
        dequant = (2 * 2 * tokens_walked * num_q_heads * head_dim
                   * elem * num_layers)
    weights = weight_bytes / max(batch, 1)
    total = pool + scales + dequant + weights + lora_bytes_per_row
    return {
        'impl': impl,
        'context_tokens_walked': tokens_walked,
        'kv_pool_bytes': pool,
        'kv_scale_bytes': scales,
        'dequant_materialize_bytes': dequant,
        'weight_bytes_amortized': round(weights, 1),
        'lora_bytes': lora_bytes_per_row,
        'total_bytes_per_token': round(total + 0.0, 1),
    }
