"""Fused blockwise LM-head + cross-entropy: no [B, S, V] logits, ever.

The training memory high-water mark of every recipe model is the
LM-head output — at Qwen2.5's 152k vocab the [B, S, V] logits tensor
dwarfs all activations combined and caps the per-chip batch
(parallel/train.py's naive `next_token_loss` materializes it twice:
forward logits + backward softmax). This op takes the final hidden
states [B, S, H] and the (possibly tied) head matrix instead, and
`lax.scan`s over vocab *chunks*: per chunk it forms [B, S, C] logits,
folds them into a running (max, sumexp) pair and the target-logit
gather, and discards them. A `jax.custom_vjp` makes the backward pass
blockwise too — softmax chunks are recomputed from the saved
logsumexp, so the residuals are just the hidden states (an activation
the model already keeps) and a [B, S] normalizer.

Peak temp memory for loss+backward drops from O(B*S*V) to
O(B*S*C) with C = the chunk size, autotuned at trace time from
{512, 1024, 2048, 4096} ∩ divisors(V) (largest candidate giving >= 4
chunks; when nothing divides V, the least-padding candidate is used
and the padded columns are masked out of the logsumexp). A vocab
small enough to fit in one chunk degenerates to the dense math —
identical compute AND identical numerics to the naive path, so tiny
smoke configs pay zero overhead.

Numerics: chunk matmuls run in the caller's compute dtype (bf16 on
the MXU) with f32 accumulation (`preferred_element_type`), and the
streaming logsumexp is f32 — the same precision contract as the naive
einsum + `jax.nn.logsumexp` path, so fp32 inputs match it to ~1e-7.
"""
from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Chunk-size candidates, largest first (bigger chunks amortize the
# per-chunk scan overhead; smaller ones cut peak memory further).
BLOCK_CANDIDATES = (4096, 2048, 1024, 512)


def pick_block(vocab_size: int) -> int:
    """Trace-time chunk autotune over {512..4096} ∩ divisors(V).

    Prefers the largest candidate that divides V AND yields >= 4
    chunks (a real memory win); falls back to the largest plain
    divisor, then to the candidate that wastes the least padding
    (padded columns are masked inside the op).
    """
    divisors = [c for c in BLOCK_CANDIDATES if vocab_size % c == 0]
    for c in divisors:
        if vocab_size // c >= 4:
            return c
    if divisors:
        return divisors[0]
    return min(BLOCK_CANDIDATES,
               key=lambda c: ((-vocab_size) % c, -c))


def find_lm_head(params) -> Tuple[Any, bool]:
    """Locate a recipe model's LM head in its top-level params.

    Returns (weight, vocab_in_rows): GPT ties the head to the token
    embedding `wte` [V, H]; the Llama/Mixtral/DeepSeek families carry
    an untied `lm_head` [H, V].
    """
    if 'lm_head' in params:
        return params['lm_head'], False
    if 'wte' in params:
        return params['wte'], True
    raise ValueError(
        "no LM head found in params (expected top-level 'lm_head' "
        "or tied 'wte')")


def _chunked(w: jax.Array, block: int, vocab: int
             ) -> Tuple[jax.Array, jax.Array]:
    """[V, H] head -> ([n_chunks, block, H] rows, [n_chunks] starts),
    zero-padding the vocab dim up to a chunk multiple."""
    n_chunks = -(-vocab // block)
    v_pad = n_chunks * block
    if v_pad != vocab:
        w = jnp.pad(w, ((0, v_pad - vocab), (0, 0)))
    return (w.reshape(n_chunks, block, w.shape[-1]),
            jnp.arange(n_chunks, dtype=jnp.int32) * block)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _blockwise_xent(block: int, vocab: int, x: jax.Array, w: jax.Array,
                    targets: jax.Array) -> jax.Array:
    """Per-token CE loss [B, T] from x [B, T, H], w [V, H] (vocab-major),
    targets [B, T] — without materializing [B, T, V]."""
    lse, tgt = _streaming_lse(block, vocab, x, w, targets)
    return lse - tgt


def _streaming_lse(block: int, vocab: int, x: jax.Array, w: jax.Array,
                   targets: jax.Array) -> Tuple[jax.Array, jax.Array]:
    w_chunks, starts = _chunked(w, block, vocab)
    b, t, _ = x.shape
    init = (jnp.full((b, t), -jnp.inf, jnp.float32),   # running max
            jnp.zeros((b, t), jnp.float32),            # running sumexp
            jnp.zeros((b, t), jnp.float32))            # target logit

    def body(carry, xs):
        m, s, tgt = carry
        w_c, start = xs
        logits = jnp.einsum('bth,ch->btc', x, w_c,
                            preferred_element_type=jnp.float32)
        valid = (start + jnp.arange(block)) < vocab
        logits = jnp.where(valid, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        # exp(-inf - finite) = 0 exactly, so the first chunk (m=-inf,
        # s=0) and padded columns fold in without special cases.
        s = (s * jnp.exp(m - m_new) +
             jnp.sum(jnp.exp(logits - m_new[..., None]), axis=-1))
        local = targets - start
        hit = (local >= 0) & (local < block)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, block - 1)[..., None],
            axis=-1)[..., 0]
        tgt = jnp.where(hit, picked, tgt)
        return (m_new, s, tgt), None

    (m, s, tgt), _ = jax.lax.scan(body, init, (w_chunks, starts))
    return m + jnp.log(s), tgt


def _blockwise_fwd(block, vocab, x, w, targets):
    lse, tgt = _streaming_lse(block, vocab, x, w, targets)
    # Residuals: inputs (kept alive anyway) + the [B, T] normalizer.
    # Chunk logits/softmax are recomputed blockwise in the backward.
    return lse - tgt, (x, w, targets, lse)


def _blockwise_bwd(block, vocab, res, g):
    x, w, targets, lse = res
    w_chunks, starts = _chunked(w, block, vocab)
    cd = x.dtype  # backward matmuls ride the same (MXU) compute dtype

    def body(dx, xs):
        w_c, start = xs
        logits = jnp.einsum('bth,ch->btc', x, w_c,
                            preferred_element_type=jnp.float32)
        valid = (start + jnp.arange(block)) < vocab
        # Padded columns: exp(logit - lse) would be spurious; mask.
        p = jnp.where(valid, jnp.exp(logits - lse[..., None]), 0.0)
        local = targets - start
        hit = (local >= 0) & (local < block)
        onehot = (local[..., None] == jnp.arange(block)) & hit[..., None]
        d_logits = ((p - onehot.astype(jnp.float32)) *
                    g[..., None]).astype(cd)
        dx = dx + jnp.einsum('btc,ch->bth', d_logits, w_c,
                             preferred_element_type=jnp.float32)
        dw_c = jnp.einsum('btc,bth->ch', d_logits, x,
                          preferred_element_type=jnp.float32)
        return dx, dw_c

    dx, dw_chunks = jax.lax.scan(
        body, jnp.zeros(x.shape, jnp.float32), (w_chunks, starts))
    dw = dw_chunks.reshape(-1, w.shape[-1])[:vocab]
    # Integer targets take a float0 cotangent (the JAX convention for
    # non-differentiable inputs).
    dt = np.zeros(targets.shape, jax.dtypes.float0)
    return dx.astype(x.dtype), dw.astype(w.dtype), dt


_blockwise_xent.defvjp(_blockwise_fwd, _blockwise_bwd)


def fused_next_token_loss(hidden: jax.Array, weight: jax.Array,
                          tokens: jax.Array, *,
                          vocab_in_rows: Optional[bool] = None,
                          block_size: Optional[int] = None,
                          compute_dtype: Optional[Any] = None
                          ) -> jax.Array:
    """Causal-LM loss straight from final hidden states.

    Drop-in replacement for `head-matmul + next_token_loss`: predicts
    tokens[:, 1:] from hidden[:, :-1] @ head, mean (lse - target
    logit), but blockwise over the vocab so no [B, S, V] array exists
    in either pass.

    Args:
      hidden: [B, S, H] final (already normed) hidden states.
      weight: LM head — [V, H] when `vocab_in_rows` (tied embedding,
        GPT's `wte`) else [H, V] (untied `lm_head`). Inferred from
        shape when unambiguous.
      tokens: [B, S] int token ids.
      block_size: vocab chunk; None = `pick_block(V)` at trace time.
      compute_dtype: matmul operand dtype (None = hidden.dtype); the
        accumulation/loss dtype is always f32.
    """
    h_dim = hidden.shape[-1]
    if vocab_in_rows is None:
        rows = weight.shape[-1] == h_dim
        cols = weight.shape[0] == h_dim
        if rows == cols:
            raise ValueError(
                f'ambiguous head orientation for shape {weight.shape} '
                f'with H={h_dim}; pass vocab_in_rows explicitly')
        vocab_in_rows = rows
    w = weight if vocab_in_rows else weight.T
    vocab = w.shape[0]
    cd = compute_dtype or hidden.dtype
    w = w.astype(cd)
    targets = tokens[:, 1:]
    block = int(block_size) if block_size else pick_block(vocab)
    if block >= vocab:
        # Single chunk: the dense math is the blockwise math. Let
        # plain AD handle it — no recompute-in-backward overhead for
        # smoke-sized vocabs. Full-S matmul then slice (the power-of-2
        # seq length vectorizes better than S-1), logits in the
        # compute dtype with the upcast fused into the f32 logsumexp
        # reduction — step-for-step the naive `head + next_token_loss`
        # math.
        logits = jnp.einsum('bsh,vh->bsv', hidden.astype(cd), w,
                            preferred_element_type=cd)
        logits = logits[:, :-1].astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, targets[..., None],
                                  axis=-1)[..., 0]
        return jnp.mean(lse - tgt)
    # Blockwise: the last position predicts nothing, so drop it BEFORE
    # the chunked matmuls (the naive path computes those logits and
    # throws them away; at 152k vocab that is real work).
    x = hidden[:, :-1].astype(cd)
    return jnp.mean(_blockwise_xent(block, vocab, x, w, targets))
