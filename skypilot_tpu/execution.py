"""Execution engine: the per-launch stage machine.

Reference: sky/execution.py (1023 LoC) — stages
OPTIMIZE→PROVISION→SYNC_WORKDIR→SYNC_FILE_MOUNTS→SETUP→EXEC→DOWN
(`sky/execution.py:48-60`), admin policy applied first, then walked
against the backend. `exec` is the fast path reusing an UP cluster.
"""
from __future__ import annotations

import enum
from typing import Any, Callable, List, Optional, Tuple

from skypilot_tpu import admin_policy
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu.backends import tpu_backend
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import timeline
from skypilot_tpu.utils import ux_utils
from skypilot_tpu.utils.status_lib import ClusterStatus


class Stage(enum.Enum):
    OPTIMIZE = enum.auto()
    PROVISION = enum.auto()
    SYNC_WORKDIR = enum.auto()
    SYNC_FILE_MOUNTS = enum.auto()
    SETUP = enum.auto()
    EXEC = enum.auto()
    DOWN = enum.auto()


def _as_dag(task_or_dag) -> dag_lib.Dag:
    if isinstance(task_or_dag, dag_lib.Dag):
        return task_or_dag
    dag = dag_lib.Dag()
    dag.add(task_or_dag)
    return dag


@timeline.event
def launch(
    task_or_dag,
    cluster_name: Optional[str] = None,
    *,
    dryrun: bool = False,
    stream_logs: bool = True,
    detach_run: bool = False,
    idle_minutes_to_autostop: Optional[int] = None,
    down: bool = False,
    retry_until_up: bool = False,
    no_setup: bool = False,
    optimize_target: 'optimizer_lib.OptimizeTarget' = (
        optimizer_lib.OptimizeTarget.COST),
    _quiet_optimizer: bool = False,
    _is_launched_by_jobs_controller: bool = False,
    _blocked_resources: Optional[set] = None,
    _pre_exec_hook: Optional[Callable[
        [tpu_backend.TpuVmResourceHandle], None]] = None,
) -> Tuple[Optional[int], Optional[tpu_backend.TpuVmResourceHandle]]:
    """Provision (if needed) + run a task. Returns (job_id, handle).

    Reference: sky/execution.py:683 `launch`.
    """
    dag = _as_dag(task_or_dag)
    if len(dag.tasks) != 1:
        raise exceptions.NotSupportedError(
            'launch() takes a single task; multi-task DAGs go through '
            'managed jobs (`jobs launch`).')
    if cluster_name is None:
        cluster_name = common_utils.fresh_cluster_name()
    common_utils.check_cluster_name_is_valid(cluster_name)

    if not dag.policy_applied:
        dag = admin_policy.apply(
            dag, admin_policy.RequestOptions(
                cluster_name=cluster_name,
                idle_minutes_to_autostop=idle_minutes_to_autostop,
                down=down, dryrun=dryrun))
    task = dag.tasks[0]
    backend = tpu_backend.TpuVmBackend()

    # --- reuse or provision -------------------------------------------------
    handle = None
    existing = global_state.get_cluster(cluster_name)
    if existing is not None and existing['status'] != ClusterStatus.STOPPED:
        handle = existing['handle']

    stages: List[Stage] = []
    if handle is None:
        stages.append(Stage.OPTIMIZE)
        stages.append(Stage.PROVISION)
    stages += [Stage.SYNC_WORKDIR, Stage.SYNC_FILE_MOUNTS]
    if not no_setup:
        stages.append(Stage.SETUP)
    stages.append(Stage.EXEC)
    if down and not detach_run:
        stages.append(Stage.DOWN)

    job_id: Optional[int] = None
    for stage in stages:
        if stage == Stage.OPTIMIZE:
            if any(r.cloud is None or not r.is_launchable()
                   for r in task.resources) or task.best_resources is None \
                    or _blocked_resources:
                # A caller-supplied blocklist (managed-jobs recovery)
                # must re-run the optimizer even when best_resources
                # is already set: the previous pick may be exactly
                # what got blocked (e.g. a blocked_cloud failure).
                optimizer_lib.Optimizer.optimize(
                    dag, minimize=optimize_target,
                    blocked_resources=_blocked_resources,
                    quiet=_quiet_optimizer)
        elif stage == Stage.PROVISION:
            to_provision = task.best_resources
            if to_provision is None:
                # resources were already concrete; pick any
                to_provision = next(iter(task.resources))
                feas = to_provision.cloud.get_feasible_launchable_resources(
                    to_provision, task.num_nodes)
                if not feas.resources_list:
                    raise exceptions.ResourcesUnavailableError(
                        f'{to_provision} is not launchable.')
                to_provision = feas.resources_list[0]
            handle = backend.provision(task, to_provision, dryrun=dryrun,
                                       stream_logs=stream_logs,
                                       cluster_name=cluster_name,
                                       retry_until_up=retry_until_up,
                                       blocked_resources=_blocked_resources)
            if dryrun:
                return None, None
            assert handle is not None
            if idle_minutes_to_autostop is not None:
                backend.set_autostop(handle, idle_minutes_to_autostop, down)
        elif stage == Stage.SYNC_WORKDIR:
            if dryrun:
                continue
            assert handle is not None
            backend.check_resources_fit_cluster(handle, task)
            if task.workdir is not None:
                backend.sync_workdir(handle, task.workdir)
        elif stage == Stage.SYNC_FILE_MOUNTS:
            if dryrun:
                continue
            if task.volumes:
                backend.mount_volumes(handle, task.volumes)
            if task.file_mounts or task.storage_mounts:
                backend.sync_file_mounts(handle, task.file_mounts,
                                         task.storage_mounts)
        elif stage == Stage.SETUP:
            if dryrun:
                continue
            backend.setup(handle, task)
        elif stage == Stage.EXEC:
            if _pre_exec_hook is not None and not dryrun:
                # Job-group members prepare the (possibly fresh)
                # cluster — peer hostname block, address publish —
                # BEFORE the user job starts, so a job resolving
                # peers at startup never races the injection
                # (matters on the recovery path, where provision and
                # submit happen inside one launch call).
                assert handle is not None
                _pre_exec_hook(handle)
            job_id = backend.execute(handle, task, detach_run=detach_run,
                                     dryrun=dryrun)
        elif stage == Stage.DOWN:
            backend.teardown(handle, terminate=True)
    return job_id, handle


@timeline.event
def exec(  # pylint: disable=redefined-builtin
    task_or_dag,
    cluster_name: str,
    *,
    dryrun: bool = False,
    detach_run: bool = False,
) -> Tuple[Optional[int], Optional[tpu_backend.TpuVmResourceHandle]]:
    """Fast path: run on an existing UP cluster, no provisioning.

    Reference: sky/execution.py:918 `exec` — stages
    [SYNC_WORKDIR, EXEC] against the cached handle.
    """
    dag = _as_dag(task_or_dag)
    task = dag.tasks[0]
    record = global_state.get_cluster(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} not found; `launch` it first.')
    if record['status'] != ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {record["status"].value}; '
            'exec needs an UP cluster.', cluster_status=record['status'])
    handle = record['handle']
    backend = tpu_backend.TpuVmBackend()
    backend.check_resources_fit_cluster(handle, task)
    if task.workdir is not None and not dryrun:
        backend.sync_workdir(handle, task.workdir)
    job_id = backend.execute(handle, task, detach_run=detach_run,
                             dryrun=dryrun)
    return job_id, handle
