"""Layered YAML config.

Reference: sky/skypilot_config.py — server config + user
`~/.sky/config.yaml` + project `.sky.yaml` + per-task `config:`
overrides, nested-key get, region-scoped lookups.

Layers here (later overrides earlier):
  1. server:   ~/.sky-tpu/config.yaml  (SKYPILOT_TPU_HOME aware)
  2. user:     $SKYPILOT_TPU_CONFIG (path) if set
  3. project:  ./.sky-tpu.yaml
  4. runtime overrides pushed via `override()` (per-request).
"""
from __future__ import annotations

import contextlib
import copy
import os
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

import yaml

from skypilot_tpu import constants

_local = threading.local()


def _load_yaml(path: str) -> Dict[str, Any]:
    path = os.path.expanduser(path)
    if not os.path.exists(path):
        return {}
    with open(path, 'r', encoding='utf-8') as f:
        out = yaml.safe_load(f) or {}
    if not isinstance(out, dict):
        raise ValueError(f'Config {path} must be a YAML mapping.')
    from skypilot_tpu.utils import schemas
    try:
        schemas.validate_config(out)
    except Exception as e:  # pylint: disable=broad-except
        raise ValueError(f'{path}: {e}') from e
    return out


def _deep_merge(base: Dict[str, Any], over: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _layers() -> List[Dict[str, Any]]:
    layers = [
        _load_yaml(os.path.join(constants.sky_home(), 'config.yaml')),
    ]
    env_path = os.environ.get('SKYPILOT_TPU_CONFIG')
    if env_path:
        layers.append(_load_yaml(env_path))
    layers.append(_load_yaml('.sky-tpu.yaml'))
    layers.extend(getattr(_local, 'overrides', []))
    return layers


def to_dict() -> Dict[str, Any]:
    merged: Dict[str, Any] = {}
    for layer in _layers():
        merged = _deep_merge(merged, layer)
    return merged


def get_nested(keys: Tuple[str, ...], default: Any = None,
               override_configs: Optional[Dict[str, Any]] = None) -> Any:
    config = to_dict()
    if override_configs:
        config = _deep_merge(config, override_configs)
    cur: Any = config
    for k in keys:
        if not isinstance(cur, dict) or k not in cur:
            return default
        cur = cur[k]
    return cur


def get_effective_region_config(cloud: str, region: Optional[str],
                                keys: Tuple[str, ...],
                                default: Any = None) -> Any:
    """cloud-scoped lookup with per-region override block.

    config: {gcp: {labels: ..., regions: {us-central2: {labels: ...}}}}
    Reference: skypilot_config.get_effective_region_config (:366).
    """
    base = get_nested((cloud,) + keys, default)
    if region is not None:
        regional = get_nested((cloud, 'regions', region) + keys, None)
        if regional is not None:
            if isinstance(base, dict) and isinstance(regional, dict):
                return _deep_merge(base, regional)
            return regional
    return base


@contextlib.contextmanager
def override(config: Dict[str, Any]) -> Iterator[None]:
    """Per-request config override (the executor wraps requests in this)."""
    if not hasattr(_local, 'overrides'):
        _local.overrides = []
    _local.overrides.append(copy.deepcopy(config))
    try:
        yield
    finally:
        _local.overrides.pop()


def has_overrides() -> bool:
    """True while a runtime `override()` context is active (per-request
    config, tests) — callers that cache file-layer reads must bypass
    their cache then."""
    return bool(getattr(_local, 'overrides', []))


def loaded_config_path() -> Optional[str]:
    path = os.path.join(constants.sky_home(), 'config.yaml')
    return path if os.path.exists(os.path.expanduser(path)) else None


def user_config_path() -> str:
    """The writable config layer (`stpu config set` / workspace switch)."""
    return os.path.expanduser(
        os.path.join(constants.sky_home(), 'config.yaml'))


def set_nested(keys: Tuple[str, ...], value: Any) -> str:
    """Set (or delete, with value=None) a nested key in the user config.

    Read-modify-write of the file layer only; runtime overrides and the
    project layer are untouched. Returns the path written. The result
    must still pass schema validation — a bad value is rejected before
    the file changes.
    """
    path = user_config_path()
    config = {}
    if os.path.exists(path):
        with open(path, 'r', encoding='utf-8') as f:
            config = yaml.safe_load(f) or {}
    cur = config
    for k in keys[:-1]:
        nxt = cur.get(k)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[k] = nxt
        cur = nxt
    if value is None:
        cur.pop(keys[-1], None)
    else:
        cur[keys[-1]] = value
    from skypilot_tpu.utils import schemas
    schemas.validate_config(config)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + '.tmp'
    with open(tmp, 'w', encoding='utf-8') as f:
        yaml.safe_dump(config, f, default_flow_style=False, sort_keys=False)
    os.replace(tmp, path)
    return path
