"""Core non-launch verbs: status, start/stop/down, queue, logs, cost.

Reference: sky/core.py (1967 LoC).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.agent import job_lib
from skypilot_tpu.backends import tpu_backend
from skypilot_tpu.utils import ux_utils
from skypilot_tpu.utils.status_lib import ClusterStatus


def _get_handle(cluster_name: str) -> tpu_backend.TpuVmResourceHandle:
    record = global_state.get_cluster(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    return record['handle']


def _refresh_one(record: Dict[str, Any]) -> Dict[str, Any]:
    """Reconcile recorded status with the provisioner's live view.

    Reference: backend_utils.refresh_cluster_status_handle — queries
    provisioner `query_instances` and fixes drift (e.g. autostopped or
    preempted clusters).
    """
    handle: tpu_backend.TpuVmResourceHandle = record['handle']
    try:
        statuses = provision_lib.query_instances(
            handle.provider_name, handle.cluster_name_on_cloud,
            handle.cluster_info.provider_config)
    except Exception:  # pylint: disable=broad-except
        return record
    if not statuses:
        # All instances gone: cluster was terminated externally.
        global_state.remove_cluster(record['name'], terminate=True)
        record['status'] = None
        return record
    values = set(statuses.values())
    if values == {'running'} and len(statuses) >= handle.num_hosts:
        new_status = ClusterStatus.UP
    elif 'running' not in values:
        new_status = ClusterStatus.STOPPED
    else:
        new_status = ClusterStatus.INIT
    if new_status != record['status']:
        global_state.set_cluster_status(record['name'], new_status)
        record['status'] = new_status
    return record


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False) -> List[Dict[str, Any]]:
    """Cluster records (reference: sky/core.py:112)."""
    records = global_state.get_clusters()
    if cluster_names:
        records = [r for r in records if r['name'] in cluster_names]
    if refresh:
        records = [_refresh_one(r) for r in records]
        records = [r for r in records if r['status'] is not None]
    return records


def start(cluster_name: str) -> None:
    """Restart a STOPPED cluster (reference: sky/core.py start)."""
    record = global_state.get_cluster(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    handle: tpu_backend.TpuVmResourceHandle = record['handle']
    from skypilot_tpu.provision import common as provision_common
    from skypilot_tpu.backends.tpu_backend import TpuVmBackend
    # Re-run the provisioner: run_instances resumes stopped nodes.
    config = provision_common.ProvisionConfig(
        provider_config=handle.cluster_info.provider_config,
        authentication_config={},
        count=handle.launched_nodes,
        tags={})
    provision_lib.run_instances(handle.provider_name,
                                handle.launched_resources.region or '',
                                handle.cluster_name_on_cloud, config)
    cluster_info = provision_lib.get_cluster_info(
        handle.provider_name, handle.launched_resources.region or '',
        handle.cluster_name_on_cloud, handle.cluster_info.provider_config)
    handle.cluster_info = cluster_info
    backend = TpuVmBackend()
    backend._bootstrap_runtime(handle)  # pylint: disable=protected-access
    global_state.add_or_update_cluster(cluster_name, handle,
                                       is_launch=False, ready=True)
    ux_utils.log(f'Cluster {cluster_name!r} restarted.')


def stop(cluster_name: str) -> None:
    handle = _get_handle(cluster_name)
    backend = tpu_backend.TpuVmBackend()
    backend.teardown(handle, terminate=False)
    ux_utils.log(f'Cluster {cluster_name!r} stopped.')


def down(cluster_name: str, purge: bool = False) -> None:
    handle = _get_handle(cluster_name)
    backend = tpu_backend.TpuVmBackend()
    backend.teardown(handle, terminate=True, purge=purge)
    ux_utils.log(f'Cluster {cluster_name!r} terminated.')


def autostop(cluster_name: str, idle_minutes: int,
             down_on_idle: bool = False) -> None:
    handle = _get_handle(cluster_name)
    backend = tpu_backend.TpuVmBackend()
    backend.set_autostop(handle,
                         None if idle_minutes < 0 else idle_minutes,
                         down_on_idle)


def queue(cluster_name: str,
          all_jobs: bool = False) -> List[Dict[str, Any]]:
    handle = _get_handle(cluster_name)
    jobs = handle.agent().get_jobs()
    if not all_jobs:
        jobs = jobs[:50]
    for j in jobs:
        j['status'] = j['status'].value
    return jobs


def cancel(cluster_name: str, job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> None:
    handle = _get_handle(cluster_name)
    backend = tpu_backend.TpuVmBackend()
    backend.cancel_jobs(handle, job_ids, cancel_all=all_jobs)


def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = True, tail: int = 0) -> int:
    handle = _get_handle(cluster_name)
    backend = tpu_backend.TpuVmBackend()
    return backend.tail_logs(handle, job_id, follow=follow, tail=tail)


def cost_report() -> List[Dict[str, Any]]:
    """Terminated-cluster cost history (reference: sky/core.py:1256)."""
    return global_state.get_cluster_history()


def storage_ls() -> List[str]:
    return global_state.get_storage_names()


def storage_delete(name: str) -> None:
    record = global_state.get_storage(name)
    if record is None:
        raise exceptions.StorageError(f'Storage {name!r} not found.')
    global_state.remove_storage(name)
