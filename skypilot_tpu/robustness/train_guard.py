"""Self-supervising trainer guards: the process-side half of the
managed-jobs recovery contract.

Three failure families cost a training job real money, and until now
the trainer could only die to all of them:

  - **Preemption**: GCE announces a spot reclaim (metadata
    `instance/preempted` flips TRUE, and/or SIGTERM lands) ~30s
    before the VM dies. A trainer that checkpoints inside that
    window loses ≤1 optimizer step; one that ignores it loses a full
    checkpoint interval.
  - **Numerical blowups**: a NaN/inf loss or a gradient-norm spike
    poisons the params the moment the optimizer applies it. The
    guarded step (parallel/train.py) detects it ON DEVICE and skips
    the update; after K consecutive bad steps the host rolls back to
    the last verified checkpoint.
  - **Hangs**: a deadlocked collective or a stalled data loader
    leaves the process alive-but-dead forever — the one failure the
    controller's liveness probes cannot see, because the agent and
    the process are both healthy. A step watchdog aborts past a
    per-phase deadline, dumping every thread's stack first.

Each path ends in a TYPED exit code (below) that
`agent/job_driver.py` maps to a typed job status and
`jobs/controller.py` maps to the recovery path (PREEMPTING →
RECOVERING → relaunch) instead of FAILED — so none of them consume
the user-failure restart budget.

All three paths are deterministically chaos-testable through the
fault registry (`train.preempt_notice`, `train.step`,
`train.data_next` in `faults.KNOWN_POINTS`); the fire-site context
carries `resume=<0|1>` so a plan can scope an injection to the first
launch and leave the recovered run alone.

Import-light on purpose: `agent/job_lib.py` imports the exit codes,
so nothing here may pull in jax (`requests` is imported lazily in
the metadata poller).
"""
from __future__ import annotations

import faulthandler
import math
import os
import signal
import sys
import threading
import time
from typing import Callable, Dict, Optional, TextIO, Tuple

from skypilot_tpu.robustness import faults

#: Trainer exited after a preemption notice, with a fresh checkpoint
#: on disk: the controller relaunches and the resumed run loses ≤1
#: optimizer step. 83/84 sit in the user-defined exit-code range and
#: collide with no shell/signal convention (126+ are shell reserved,
#: 128+n are signal deaths).
EXIT_PREEMPTED_GRACEFUL = 83
#: The step watchdog aborted a hung trainer (stuck collective or
#: stalled data loader) after dumping all thread stacks: the
#: controller relaunches instead of waiting forever.
EXIT_WATCHDOG_ABORT = 84

#: The default GCE preemption-notice endpoint; overridable for tests
#: and non-GCE substrates via STPU_PREEMPT_METADATA_URL.
GCE_PREEMPTED_URL = ('http://metadata.google.internal/computeMetadata'
                     '/v1/instance/preempted')
METADATA_URL_ENV = 'STPU_PREEMPT_METADATA_URL'

#: Consecutive metadata-probe failures before the poller stops
#: hitting the endpoint (not on GCE / no fake server) — the fault
#: point and the SIGTERM handler keep working regardless.
_METADATA_MAX_FAILURES = 5


class PreemptionNotice:
    """Watches for a preemption notice: GCE metadata poll + SIGTERM.

    `start()` spawns a daemon poll thread and (optionally) installs a
    SIGTERM handler; `notice` is a `threading.Event` the train loop
    checks once per step. Each poll fires the `train.preempt_notice`
    fault point — a `drop` rule is a synthetic notice, which is how
    the chaos tests drive this path without a metadata server.
    """

    def __init__(self, poll_interval_s: float = 5.0,
                 metadata_url: Optional[str] = None,
                 install_sigterm: bool = True,
                 ctx: Optional[Dict[str, str]] = None) -> None:
        self.poll_interval_s = poll_interval_s
        self.metadata_url = (metadata_url
                             or os.environ.get(METADATA_URL_ENV)
                             or GCE_PREEMPTED_URL)
        self.install_sigterm = install_sigterm
        self.ctx = dict(ctx or {})
        self.notice = threading.Event()
        self.reason: Optional[str] = None
        self.polls = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev_sigterm = None
        self._metadata_failures = 0

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        if self.install_sigterm:
            self._prev_sigterm = signal.signal(signal.SIGTERM,
                                               self._handle_sigterm)
        self._thread = threading.Thread(target=self._poll_loop,
                                        name='preempt-notice',
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- notice sources --------------------------------------------------
    def trigger(self, reason: str) -> None:
        """Latch the notice (first reason wins; later ones are
        no-ops). Signal-safe: only sets an Event and a string."""
        if not self.notice.is_set():
            self.reason = reason
            self.notice.set()
            from skypilot_tpu.observability import catalog
            catalog.counter(
                'skypilot_train_preempt_notices_total').inc()

    def _handle_sigterm(self, signum, frame):  # noqa: ARG002
        self.trigger('sigterm')

    def _poll_loop(self) -> None:
        while not self._stop.is_set() and not self.notice.is_set():
            self.polls += 1
            # Chaos: a drop rule here IS the preemption notice. The
            # resume flag in ctx lets a plan scope the injection to
            # the first launch (scope {"resume": "0"}), so the
            # recovered run is not re-preempted forever.
            if faults.point('train.preempt_notice',
                            **self.ctx) is faults.DROP:
                self.trigger('injected')
                break
            if self._probe_metadata():
                self.trigger('metadata')
                break
            self._stop.wait(self.poll_interval_s)

    def _probe_metadata(self) -> bool:
        if self._metadata_failures >= _METADATA_MAX_FAILURES:
            return False
        import requests
        try:
            resp = requests.get(self.metadata_url,
                                headers={'Metadata-Flavor': 'Google'},
                                timeout=(1, 2))
            self._metadata_failures = 0
            return resp.ok and resp.text.strip().upper() == 'TRUE'
        except requests.RequestException:
            # Not on GCE (or the fake server is gone): give up on the
            # endpoint after a few strikes; SIGTERM + injection still
            # cover the notice path.
            self._metadata_failures += 1
            if self._metadata_failures == _METADATA_MAX_FAILURES:
                print('preempt-notice: metadata endpoint '
                      f'{self.metadata_url} unreachable '
                      f'{_METADATA_MAX_FAILURES}x; polling stopped '
                      '(SIGTERM handling stays active)', flush=True)
            return False


class SpikeGuard:
    """Host-side bad-step policy: EMA spike threshold + rollback-K.

    The DEVICE decides whether a step was bad (non-finite loss/grad
    norm, or norm above the threshold this class provides) and skips
    the update on its own; this class consumes the fetched verdicts,
    maintains the grad-norm EMA the threshold derives from, and
    escalates to a rollback after `rollback_after` consecutive bad
    steps. Single-threaded by design — only the train loop calls it.
    """

    def __init__(self, spike_factor: float = 10.0,
                 warmup_steps: int = 10,
                 rollback_after: int = 3,
                 ema_beta: float = 0.98) -> None:
        if rollback_after < 1:
            raise ValueError('rollback_after must be >= 1')
        self.spike_factor = spike_factor
        self.warmup_steps = warmup_steps
        self.rollback_after = rollback_after
        self.ema_beta = ema_beta
        self._ema: Optional[float] = None
        self._good_steps = 0
        self.consecutive_bad = 0
        self.skipped_total = 0
        self.rollbacks = 0

    def threshold(self) -> float:
        """Grad-norm ceiling for the NEXT step (inf while warming
        up): the device flags `gnorm > threshold` as a spike."""
        if self._ema is None or self._good_steps < self.warmup_steps:
            return math.inf
        return self.spike_factor * self._ema

    def observe(self, step: int, loss: float, gnorm: float,
                bad: bool) -> str:
        """Consume one step's fetched (loss, gnorm, bad) verdict.
        Returns 'ok', 'skipped', or 'rollback' (the caller restores
        the last checkpoint and then calls `reset_after_rollback`)."""
        del step
        if bad:
            self.skipped_total += 1
            self.consecutive_bad += 1
            from skypilot_tpu.observability import catalog
            catalog.counter(
                'skypilot_train_guard_skipped_steps_total').inc()
            if self.consecutive_bad >= self.rollback_after:
                return 'rollback'
            return 'skipped'
        self.consecutive_bad = 0
        if math.isfinite(gnorm) and math.isfinite(loss):
            self._ema = (gnorm if self._ema is None else
                         self.ema_beta * self._ema +
                         (1.0 - self.ema_beta) * gnorm)
            self._good_steps += 1
        return 'ok'

    def reset_after_rollback(self) -> None:
        """Forget the (possibly poisoned) EMA and re-warm: the
        restored params' gradient scale may differ from the one the
        threshold latched onto."""
        self._ema = None
        self._good_steps = 0
        self.consecutive_bad = 0
        self.rollbacks += 1


class StepWatchdog:
    """Aborts a hung trainer: per-phase heartbeat with a deadline.

    The train loop calls `beat(phase)` at every phase transition
    (data fetch, step dispatch, commit); a background thread aborts
    the PROCESS when no beat lands within the phase's deadline —
    `faulthandler` dumps every thread's stack (the hung collective or
    blocked loader is right there in the abort output), the watchdog
    counter bumps, and `exit_fn` (default `os._exit`, the only exit
    that works under a wedged main thread) exits with
    EXIT_WATCHDOG_ABORT so the controller relaunches instead of
    waiting forever.
    """

    def __init__(self, deadline_s: float,
                 poll_interval_s: float = 0.25,
                 exit_fn: Optional[Callable[[int], None]] = None,
                 stream: Optional[TextIO] = None) -> None:
        if deadline_s <= 0:
            raise ValueError('watchdog deadline must be > 0')
        self.deadline_s = deadline_s
        self.poll_interval_s = poll_interval_s
        self.exit_fn = exit_fn if exit_fn is not None else os._exit
        self.stream = stream
        self.fired = False
        # One-tuple state so beat() is a single atomic assignment the
        # watchdog thread can never read half-updated.
        self._beat: Tuple[float, str, float] = (
            time.monotonic(), 'init', deadline_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name='step-watchdog',
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def beat(self, phase: str,
             deadline_s: Optional[float] = None) -> None:
        """Mark a phase transition; `deadline_s` overrides the base
        deadline for THIS phase (e.g. the first step's compile)."""
        self._beat = (time.monotonic(), phase,
                      deadline_s if deadline_s is not None
                      else self.deadline_s)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            at, phase, deadline = self._beat
            stalled = time.monotonic() - at
            if stalled > deadline:
                self._abort(phase, stalled, deadline)
                return

    def _abort(self, phase: str, stalled: float,
               deadline: float) -> None:
        self.fired = True
        from skypilot_tpu.observability import catalog
        catalog.counter('skypilot_train_watchdog_aborts_total').inc()
        stream = self.stream if self.stream is not None else sys.stderr
        print(f'step-watchdog: phase {phase!r} stalled '
              f'{stalled:.1f}s (deadline {deadline:.1f}s); dumping '
              f'thread stacks and aborting with exit code '
              f'{EXIT_WATCHDOG_ABORT}', file=stream, flush=True)
        try:
            faulthandler.dump_traceback(file=stream, all_threads=True)
            stream.flush()
        except (OSError, ValueError):
            pass  # a closed stream must not block the abort itself
        self.exit_fn(EXIT_WATCHDOG_ABORT)


class TrainSupervisor:
    """The train loop's one-stop guard bundle.

    Composes the preemption-notice watcher, the spike guard, and the
    step watchdog behind the handful of calls `recipes/train_lm.py`
    makes per step; each part can be disabled for tests. `ctx` is the
    fault-point fire-site context (e.g. `{'resume': '1'}` on a
    checkpoint-resumed run) shared by all three train points.
    """

    def __init__(self, *,
                 spike_factor: float = 10.0,
                 warmup_steps: int = 10,
                 rollback_after: int = 3,
                 watchdog_deadline_s: float = 300.0,
                 compile_deadline_s: float = 1800.0,
                 notice_poll_s: float = 5.0,
                 metadata_url: Optional[str] = None,
                 install_sigterm: bool = True,
                 exit_fn: Optional[Callable[[int], None]] = None,
                 watchdog_stream: Optional[TextIO] = None,
                 ctx: Optional[Dict[str, str]] = None) -> None:
        self.ctx = dict(ctx or {})
        self.guard = SpikeGuard(spike_factor=spike_factor,
                                warmup_steps=warmup_steps,
                                rollback_after=rollback_after)
        self.notice = PreemptionNotice(poll_interval_s=notice_poll_s,
                                       metadata_url=metadata_url,
                                       install_sigterm=install_sigterm,
                                       ctx=self.ctx)
        self.watchdog: Optional[StepWatchdog] = None
        if watchdog_deadline_s > 0:
            self.watchdog = StepWatchdog(watchdog_deadline_s,
                                         exit_fn=exit_fn,
                                         stream=watchdog_stream)
        self.compile_deadline_s = max(compile_deadline_s,
                                      watchdog_deadline_s)
        self._poisoned_steps = 0

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self.notice.start()
        if self.watchdog is not None:
            self.watchdog.start()

    def stop(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        self.notice.stop()

    # -- per-step hooks --------------------------------------------------
    @property
    def preempted(self) -> bool:
        return self.notice.notice.is_set()

    @property
    def preempt_reason(self) -> Optional[str]:
        return self.notice.reason

    def beat(self, phase: str, first_step: bool = False) -> None:
        if self.watchdog is not None:
            self.watchdog.beat(
                phase,
                self.compile_deadline_s if first_step else None)

    def data_point(self) -> None:
        """`train.data_next`: a delay rule here is a stalled data
        loader the watchdog must catch."""
        faults.point('train.data_next', **self.ctx)

    def step_ctl(self, step: int) -> Tuple[float, float]:
        """(max_grad_norm, loss_scale) for the guarded device step.

        Fires `train.step`; a `drop` rule poisons THIS step's loss
        with NaN (scale = NaN), driving the real on-device isfinite
        guard — the deterministic "injected NaN" of the chaos tests.
        """
        loss_scale = 1.0
        if faults.point('train.step', step=str(step),
                        **self.ctx) is faults.DROP:
            loss_scale = math.nan
            self._poisoned_steps += 1
            print(f'train-guard: injected NaN into step {step} '
                  f'(fault plan)', flush=True)
        return self.guard.threshold(), loss_scale

    def observe(self, step: int, loss: float, gnorm: float,
                bad: bool) -> str:
        verdict = self.guard.observe(step, loss, gnorm, bad)
        if verdict != 'ok':
            print(f'train-guard: step {step} bad '
                  f'(loss={loss:.6g} grad_norm={gnorm:.6g}); '
                  f'{"rolling back" if verdict == "rollback" else "update skipped"} '
                  f'[{self.guard.consecutive_bad} consecutive]',
                  flush=True)
        return verdict

    def summary(self) -> Dict[str, int]:
        return {
            'skipped_steps': self.guard.skipped_total,
            'rollbacks': self.guard.rollbacks,
            'poisoned_steps': self._poisoned_steps,
            'preempt_notice': int(self.preempted),
        }
