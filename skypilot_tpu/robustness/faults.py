"""Deterministic fault injection: named points in live code paths.

The chaos-test backbone: production code declares *injection points*
(`faults.point('engine.decode_step')`) that are no-ops by default —
one module-global read per call, no plan parsing, no locking. Under a
*fault plan* (JSON via the `STPU_FAULT_PLAN` env var, a `--fault-plan`
CLI flag, or `install_plan()` from tests) a point deterministically
perturbs the code path: raise an exception, delay, or report `DROP`
so the site can skip the guarded operation (e.g. treat a monitor
probe as lost).

Plans are SEEDED: probabilistic triggers draw from per-rule
`random.Random` instances derived from the plan seed, so a chaos run
replays bit-identically. Counting triggers (`every_nth`, `at`,
`after`, `times`) need no randomness at all.

Plan format (see docs/guides.md "Serving robustness"):

    {
      "seed": 42,
      "rules": [
        {"point": "engine.decode_step", "action": "raise",
         "exc": "RuntimeError", "message": "injected poison step",
         "after": 3, "times": 1},
        {"point": "jobs.monitor_probe", "action": "drop",
         "times": 8},
        {"point": "http.handler", "action": "delay",
         "delay_s": 0.05, "prob": 0.25},
        {"point": "jobs.preempt_storm",
         "scope": {"zone": "us-east5-b"},
         "start_range": [40.0, 60.0], "duration_s": 120.0}
      ]
    }

Rule semantics: every `point(name, **ctx)` call increments each
matching rule's hit counter (first call = hit 1). A rule with a
`scope` (e.g. `{"zone": "us-east5-b"}`) only matches calls whose
fire-site context carries every scoped key with that exact value —
scope-mismatched calls do not count as hits, so a scoped rule's
counters see only its own stream. A rule with a *window* (`start_s`
or seeded `start_range: [lo, hi]`, plus `duration_s`) only matches
inside `[start, start + duration)` seconds after plan install
(measured on the plan's clock — `install_plan(..., clock=...)` lets
a simulator drive virtual time). Within its matching stream a rule
fires when hits > `after` (default 0), its trigger matches
(`every_nth`: every Nth eligible hit; `at`: exact hit numbers;
`prob`: seeded coin flip; none: every eligible hit), and it has
fired fewer than `times` (default unlimited) times. Rules evaluate
in plan order: `delay` fires and evaluation continues, `drop` and
`raise` end it.

`jobs.preempt_storm` is a *derived* point — no production code calls
it. A rule naming it models a zone-wide spot preemption storm: it is
installed against `jobs.monitor_probe` with `action: drop` and a
REQUIRED window, so every matching job's liveness probes vanish for
the (seeded) storm window and the whole fleet walks the real
grace -> recover path at once. `windows()` exposes the resolved
storm geometry so a fleet simulator can align cluster death with
probe loss.

The point-name catalog is closed (`KNOWN_POINTS`): a plan naming an
unknown point fails at install, not by silently never firing.
"""
from __future__ import annotations

import builtins
import importlib
import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Union

# Every injection point wired into the codebase, with the behavior a
# firing rule perturbs (also rendered in docs/internals.md).
KNOWN_POINTS: Dict[str, str] = {
    'engine.decode_step':
        'continuous-batching engine, start of one decode round '
        '(before the device dispatch and before the round consumes '
        'RNG — a raised fault retries the round with identical '
        'outputs)',
    'engine.prefill_chunk':
        'engine, start of one prefill-chunk dispatch for one slot (a '
        'raised fault fails only that slot\'s request)',
    'engine.device_get':
        'engine, before fetching sampled tokens from the device '
        '(delay here models host/device interconnect stalls)',
    'jobs.monitor_probe':
        'managed-job controller, before each agent liveness probe '
        '(DROP makes the probe count as unreachable — a synthetic '
        'preemption; fire-site context carries zone=<zone> and '
        'job=<job id> for scoped rules)',
    'jobs.preempt_storm':
        'derived point: a windowed drop rule on jobs.monitor_probe '
        'scoped to a set of jobs (e.g. {"zone": ...}) — one rule '
        'models a zone-wide spot storm hitting every job placed '
        'there during a seeded time window',
    'jobs.launch':
        'recovery-strategy executor, before each cluster launch '
        'attempt (raise ResourcesUnavailableError to exercise '
        'retry/backoff/failover)',
    'http.handler':
        'inference HTTP server, start of each POST handler',
    'kv.handoff':
        'prefill-role inference server, start of each prefill->'
        'decode KV page-chain handoff (raise OR drop fails the '
        'transfer: the prefill replica falls back to serving the '
        'request locally from its already-warm pages — the '
        'disaggregation degradation path, never an error to the '
        'client)',
    'adapters.load':
        'adapter registry (inference/adapters.py), inside each LoRA '
        'artifact load into the device store — raise OR drop makes '
        'the load fail as AdapterLoadError (HTTP 503) for that '
        'request only; the engine, the base model, and every other '
        'adapter keep serving; fire-site context carries '
        'adapter=<name> for scoped rules',
    'fleet.tick':
        'replica-plane fleet controller, start of each control-loop '
        'tick (a raised fault exercises the tick-error fuse: 3 '
        'consecutive failures flip the controller-degraded gauge; '
        'a SIGKILL-shaped chaos run restarts the controller and '
        'adopts the fleet from the journal)',
    'checkpoint.save':
        'CheckpointManager.save, before the orbax save is issued',
    'checkpoint.restore':
        'CheckpointManager.restore, before integrity verification '
        'and the orbax read (raise to model unreadable checkpoint '
        'storage; manifest-verification fallback is separate and '
        'driven by on-disk corruption)',
    'train.preempt_notice':
        'trainer preemption-notice poll loop (train_guard.py) — a '
        'DROP is a synthetic preemption notice: the trainer '
        'checkpoints NOW and exits with the typed code 83 the '
        'managed-jobs controller maps to recovery; fire-site '
        'context carries resume=<0|1> so a scoped rule can preempt '
        'only the first launch',
    'train.step':
        'train loop, before each optimizer-step dispatch — a DROP '
        'poisons that step\'s loss with NaN (through the REAL '
        'on-device isfinite guard: update skipped, rollback after K '
        'consecutive); context: step=<n>, resume=<0|1>',
    'train.data_next':
        'train loop, start of each batch fetch — a delay models a '
        'stalled data loader: the step watchdog dumps all thread '
        'stacks and aborts with the typed code 84 past its '
        'deadline; context: resume=<0|1>',
    'serve.preempt_notice':
        'serving preemption-notice poll loop (http_server.'
        'ServePreemptionNotice + the stub replica) — a DROP is a '
        'synthetic spot preemption: the replica mass-evacuates every '
        'active KV chain to peers and drains inside the grace '
        'window; fire-site context carries zone=<zone>, so a '
        'windowed scoped rule is a zone-wide decode-pool storm '
        '(examples/fault_plans/decode_zone_storm.json)',
    'kv.migrate':
        'inference server, start of each live session migration '
        'ship (the /kv/migrate POST of an evacuated chain + '
        'continuation request) — raise OR drop fails the ship: the '
        'session finishes locally on the pages the evacuation '
        'promoted into the prefix cache, never an error to the '
        'client; context carries reason=<drain|preempt|rebalance>',
}

#: Sentinel returned by `point()` when a drop rule fires; sites that
#: support dropping compare with `is`.
DROP = object()


class InjectedFault(Exception):
    """Default exception type raised by `action: raise` rules."""


def _resolve_exc(name: Optional[str]):
    """Exception class from a builtin name or dotted path."""
    if not name:
        return InjectedFault
    if '.' in name:
        module_name, attr = name.rsplit('.', 1)
        cls = getattr(importlib.import_module(module_name), attr)
    else:
        cls = getattr(builtins, name, None)
    if not (isinstance(cls, type) and
            issubclass(cls, BaseException)):
        raise ValueError(f'fault plan: exc {name!r} is not an '
                         f'exception type')
    return cls


#: Derived points: not called by production code; a rule naming one
#: is rewritten at parse time onto the real point it perturbs, with
#: the listed defaults forced.
_DERIVED_POINTS: Dict[str, Dict[str, Any]] = {
    'jobs.preempt_storm': {
        'target': 'jobs.monitor_probe',
        'action': 'drop',
        'window_required': True,
    },
}


class FaultRule:
    """One parsed rule; owns its hit/fired counters and seeded rng."""

    _ACTIONS = ('raise', 'delay', 'drop')

    def __init__(self, spec: Dict[str, Any], index: int,
                 seed: int) -> None:
        self.point = spec.get('point')
        if self.point not in KNOWN_POINTS:
            raise ValueError(
                f'fault plan: unknown point {self.point!r}; known '
                f'points: {sorted(KNOWN_POINTS)}')
        derived = _DERIVED_POINTS.get(self.point, {})
        #: The point this rule is evaluated at (== `point` unless
        #: derived); plans index rules by target, stats by `point`.
        self.target = derived.get('target', self.point)
        self.action = spec.get('action', derived.get('action', 'raise'))
        if self.action not in self._ACTIONS:
            raise ValueError(f'fault plan: unknown action '
                             f'{self.action!r} (use one of '
                             f'{self._ACTIONS})')
        self.exc = _resolve_exc(spec.get('exc'))
        self.message = str(spec.get('message', f'injected fault at '
                                               f'{self.point}'))
        self.delay_s = float(spec.get('delay_s', 0.0))
        self.every_nth = spec.get('every_nth')
        self.at = [int(x) for x in spec.get('at', [])]
        self.after = int(spec.get('after', 0))
        self.times = spec.get('times')
        self.prob = spec.get('prob')
        self.scope = dict(spec.get('scope') or {})
        for key, value in self.scope.items():
            if not isinstance(key, str) or not isinstance(value, str):
                raise ValueError(
                    f'fault plan: scope must map string keys to '
                    f'string values, got {self.scope!r}')
        # Per-rule deterministic stream: same plan -> same firings.
        # The window draw (if any) consumes the first value, so
        # `prob` streams stay aligned whether or not a range is set.
        self._rng = random.Random(f'{seed}:{index}:{self.point}')
        self.start_s: Optional[float] = None
        self.duration_s: Optional[float] = None
        start_range = spec.get('start_range')
        if start_range is not None:
            lo, hi = (float(start_range[0]), float(start_range[1]))
            self.start_s = self._rng.uniform(lo, hi)
        elif spec.get('start_s') is not None:
            self.start_s = float(spec['start_s'])
        if spec.get('duration_s') is not None:
            self.duration_s = float(spec['duration_s'])
        if (self.start_s is None) != (self.duration_s is None):
            raise ValueError(
                f'fault plan: rule {index} ({self.point}) has a '
                f'partial window — set both start_s/start_range and '
                f'duration_s, or neither')
        if derived.get('window_required') and self.start_s is None:
            raise ValueError(
                f'fault plan: {self.point} requires a window '
                f'(start_s or start_range, plus duration_s)')
        self.hits = 0
        self.fired = 0

    def matches(self, ctx: Dict[str, str], elapsed_s: float) -> bool:
        """Eligibility filters that precede hit counting: a call
        outside the rule's scope or time window is invisible to it."""
        if self.start_s is not None and not (
                self.start_s <= elapsed_s <
                self.start_s + self.duration_s):
            return False
        for key, value in self.scope.items():
            if ctx.get(key) != value:
                return False
        return True

    def check(self) -> bool:
        """Register one hit; True when the rule fires this hit.
        Caller holds the plan lock."""
        self.hits += 1
        if self.times is not None and self.fired >= int(self.times):
            return False
        if self.hits <= self.after:
            return False
        eligible = self.hits - self.after
        if self.at:
            fire = self.hits in self.at
        elif self.every_nth:
            fire = eligible % int(self.every_nth) == 0
        elif self.prob is not None:
            fire = self._rng.random() < float(self.prob)
        else:
            fire = True
        if fire:
            self.fired += 1
        return fire


class FaultPlan:
    """A parsed plan: rules indexed by target point, thread-safe
    firing. `clock` (default `time.monotonic`) anchors rule windows:
    elapsed time is measured from plan construction, so a fleet
    simulator can pass its virtual clock and replay storms in
    virtual seconds."""

    def __init__(self, spec: Dict[str, Any], clock=None) -> None:
        self.seed = int(spec.get('seed', 0))
        rules = spec.get('rules')
        if not isinstance(rules, list) or not rules:
            raise ValueError('fault plan: "rules" must be a '
                             'non-empty list')
        self._clock = clock if clock is not None else time.monotonic
        self._epoch = self._clock()
        self._lock = threading.Lock()
        self._by_point: Dict[str, List[FaultRule]] = {}
        for i, rule_spec in enumerate(rules):
            rule = FaultRule(rule_spec, i, self.seed)
            self._by_point.setdefault(rule.target, []).append(rule)

    def elapsed(self) -> float:
        return self._clock() - self._epoch

    def windows(self, point_name: str) -> List[Dict[str, Any]]:
        """Resolved {scope, start_s, end_s, action} for every
        windowed rule evaluated at `point_name` (storm geometry —
        the fleet simulator aligns cluster death with probe loss
        from this)."""
        out = []
        for rule in self._by_point.get(point_name, []):
            if rule.start_s is None:
                continue
            out.append({'scope': dict(rule.scope),
                        'start_s': rule.start_s,
                        'end_s': rule.start_s + rule.duration_s,
                        'action': rule.action})
        return out

    def fire(self, name: str,
             ctx: Optional[Dict[str, str]] = None) -> Optional[object]:
        rules = self._by_point.get(name)
        if not rules:
            return None
        ctx = ctx or {}
        elapsed = self.elapsed()
        delay = 0.0
        outcome: Optional[object] = None
        raise_rule: Optional[FaultRule] = None
        with self._lock:
            for rule in rules:
                if not rule.matches(ctx, elapsed):
                    continue
                if not rule.check():
                    continue
                if rule.action == 'delay':
                    delay += rule.delay_s
                    continue
                if rule.action == 'drop':
                    outcome = DROP
                    break
                raise_rule = rule
                break
        # Sleep/raise outside the lock: a delayed point must not
        # serialize every other thread's injection checks.
        if delay > 0.0:
            time.sleep(delay)
        if raise_rule is not None:
            raise raise_rule.exc(raise_rule.message)
        return outcome

    def stats(self) -> Dict[str, Dict[str, int]]:
        """{point: {hits, fired}} aggregated over the point's rules.
        Derived rules (jobs.preempt_storm) report under their OWN
        name, not the target they were installed against."""
        grouped: Dict[str, List[FaultRule]] = {}
        with self._lock:
            for rules in self._by_point.values():
                for r in rules:
                    grouped.setdefault(r.point, []).append(r)
            return {name: {'hits': max(r.hits for r in rules),
                           'fired': sum(r.fired for r in rules)}
                    for name, rules in grouped.items()}


_plan: Optional[FaultPlan] = None
_install_lock = threading.Lock()


def install_plan(spec: Union[None, str, Dict[str, Any], FaultPlan],
                 clock=None) -> Optional[FaultPlan]:
    """Install the process-wide plan. `spec` is a dict, a JSON string,
    a path to a JSON file, an already-built FaultPlan, or None
    (clears). `clock` (ignored for a pre-built FaultPlan) anchors
    rule windows — see FaultPlan. Returns the installed plan."""
    global _plan
    if spec is None:
        with _install_lock:
            _plan = None
        return None
    if isinstance(spec, FaultPlan):
        plan = spec
    else:
        if isinstance(spec, str):
            text = spec
            if not spec.lstrip().startswith('{'):
                with open(spec, 'r', encoding='utf-8') as f:
                    text = f.read()
            try:
                spec = json.loads(text)
            except json.JSONDecodeError as e:
                raise ValueError(f'fault plan: invalid JSON: {e}') \
                    from e
        plan = FaultPlan(spec, clock=clock)
    with _install_lock:
        _plan = plan
    return plan


def clear() -> None:
    install_plan(None)


def get_plan() -> Optional[FaultPlan]:
    return _plan


def active() -> bool:
    return _plan is not None


def point(name: str, **ctx: str) -> Optional[object]:
    """THE injection point. No plan installed: returns None after one
    global read (the zero-cost default every production call site
    pays). With a plan: may raise, sleep, or return `DROP`. Keyword
    args are the fire-site context scoped rules match against (e.g.
    `point('jobs.monitor_probe', zone='us-east5-b', job='12')`)."""
    plan = _plan
    if plan is None:
        return None
    return plan.fire(name, ctx)


def windows(point_name: str) -> List[Dict[str, Any]]:
    """Resolved windows of the installed plan's rules at
    `point_name`; empty with no plan."""
    plan = _plan
    return plan.windows(point_name) if plan is not None else []


def stats() -> Dict[str, Dict[str, int]]:
    plan = _plan
    return plan.stats() if plan is not None else {}


# Operators enable chaos on a live process tree via the environment
# (serve replicas, spawned job controllers); loaded once at import.
_env_spec = os.environ.get('STPU_FAULT_PLAN')
if _env_spec:
    install_plan(_env_spec)
