"""Fleet-scale managed-jobs simulator: N controllers, virtual time.

The missing half of the chaos story: PR 5 proved ONE job survives
ONE injected preemption; this proves the CONTROL PLANE survives a
zone-wide spot storm hitting hundreds of concurrent jobs — through
the REAL code: each simulated job runs an actual
`jobs.controller.JobController` monitor loop driving an actual
`recovery_strategy.StrategyExecutor` (grace windows, zone-labeled
preemption counters, recovery-event timestamps, jittered launch
backoff, retry deadlines), with only the cloud stubbed out.

Three substitutions make N=500 tractable, deterministic, and
cloud-free:

  1. VIRTUAL TIME. A lockstep scheduler runs every controller on
     its own thread but releases exactly ONE at a time; `time.time`
     / `time.monotonic` / `time.sleep` inside the jobs modules are
     rerouted to the `SimClock`, which jumps straight to the next
     earliest wake-up. 500 jobs x minutes of polling simulate in
     seconds of wall time, and the interleaving is a pure function
     of (seed, plan) — the property the fleet bench's byte-identical
     JSON contract rests on.

  2. A STUB LAUNCH BACKEND. `execution.launch` is replaced by a
     placement stub that holds the (virtual) launch duration, tracks
     relaunch concurrency (the thundering-herd signal), assigns
     zones from a seeded distribution, and books cluster segments
     for cost accounting. Everything ABOVE it — retry loops,
     backoff, deadlines, blocked-resource bookkeeping — is the
     production path.

  3. A STUB AGENT. Probes hit an in-memory agent that models a
     checkpointed training workload: progress accrues while the
     cluster is up, rolls back to the last checkpoint on preemption
     (the lost steps/tokens the bench reports), and reports
     SUCCEEDED when the work is done. Cluster death follows the
     installed fault plan's storm windows
     (`faults.windows('jobs.monitor_probe')`), so probe loss and
     capacity loss agree by construction.

Used by `benchmarks/fleet_bench.py` (the N=500 storm bench emitting
`BENCH_fleet_*.json`) and the tier-1 N=20 smoke test.
"""
from __future__ import annotations

import json
import os
import random
import tempfile
import threading
from typing import Any, Dict, List, Optional, Tuple

import requests

from skypilot_tpu.robustness import faults

_DEFAULT_HORIZON_S = 4 * 3600.0


class SimTimeout(Exception):
    """Virtual time ran past the horizon — a job is stuck in a
    recover/poll loop the scenario never lets finish. Raised inside
    the worker so the controller's own containment turns it into
    FAILED_CONTROLLER instead of hanging the simulation."""


class _Worker:
    __slots__ = ('wid', 'go', 'yielded', 'wake_at', 'done', 'thread',
                 'error')

    def __init__(self, wid: int, wake_at: float) -> None:
        self.wid = wid
        self.go = threading.Event()
        self.yielded = threading.Event()
        self.wake_at = wake_at
        self.done = False
        self.thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None


class SimClock:
    """Deterministic lockstep virtual clock.

    Worker threads call `sleep`, which parks the thread and hands
    control back to the coordinator; the coordinator releases the
    worker with the earliest wake-up (ties by worker id) and
    advances `now` to it. Exactly one worker runs at any instant, so
    shared state needs no locking and the schedule is reproducible.
    """

    def __init__(self, horizon_s: float = _DEFAULT_HORIZON_S) -> None:
        self.now = 0.0
        self.horizon_s = horizon_s
        self._by_ident: Dict[int, _Worker] = {}

    # -- the time.* surface rerouted into the sim ----------------------
    def time(self) -> float:
        return self.now

    def monotonic(self) -> float:
        return self.now

    def sleep(self, seconds: float) -> None:
        worker = self._by_ident[threading.get_ident()]
        wake_at = self.now + max(float(seconds), 0.0)
        if wake_at > self.horizon_s:
            raise SimTimeout(
                f'virtual time {wake_at:.0f}s past the '
                f'{self.horizon_s:.0f}s horizon')
        worker.wake_at = wake_at
        worker.yielded.set()
        worker.go.wait()
        worker.go.clear()

    # -- coordinator ---------------------------------------------------
    def register(self, worker: _Worker) -> None:
        """Called on the WORKER's thread before it first runs, so the
        ident mapping exists before any sleep()."""
        self._by_ident[threading.get_ident()] = worker

    def run_all(self, workers: List[_Worker]) -> None:
        live = [w for w in workers if not w.done]
        while live:
            nxt = min(live, key=lambda w: (w.wake_at, w.wid))
            self.now = max(self.now, nxt.wake_at)
            nxt.go.set()
            if not nxt.yielded.wait(timeout=300):
                raise RuntimeError(
                    f'fleet sim wedged: worker {nxt.wid} neither '
                    f'slept nor finished within 300s of wall time')
            nxt.yielded.clear()
            live = [w for w in workers if not w.done]


class _TimeShim:
    """Drop-in for the `time` module inside the jobs modules."""

    def __init__(self, clock: SimClock) -> None:
        self._clock = clock

    def time(self) -> float:
        return self._clock.time()

    def monotonic(self) -> float:
        return self._clock.monotonic()

    def sleep(self, seconds: float) -> None:
        self._clock.sleep(seconds)


class _SimJob:
    """Bookkeeping for one simulated managed job's cluster + work."""

    def __init__(self, job_id: int, cluster_name: str, work_s: float,
                 ckpt_every_s: float, rng: random.Random) -> None:
        self.job_id = job_id
        self.cluster_name = cluster_name
        self.work_s = work_s
        self.ckpt_every_s = ckpt_every_s
        self.rng = rng
        # Cluster segment currently billed/running.
        self.zone: Optional[str] = None
        self.seg_start = 0.0
        self.dead_at: Optional[float] = None
        self.base = 0.0          # checkpointed progress (seconds)
        self.launches = 0
        self.lost_s = 0.0
        self.preemptions = 0
        self.segments: List[Tuple[str, float, float]] = []
        self.completed_at: Optional[float] = None

    def progress(self, now: float) -> float:
        if self.zone is None:
            return self.base
        end = now if self.dead_at is None else min(now, self.dead_at)
        return min(self.work_s,
                   self.base + max(0.0, end - self.seg_start))

    def cluster_dead(self, now: float) -> bool:
        return self.dead_at is not None and now >= self.dead_at


class FleetSim:
    """One reproducible fleet run; see module docstring.

    Determinism contract: `run()` output is a pure function of the
    constructor arguments. Every random draw (placement, jittered
    backoff, storm start, probabilistic launch failures) comes from
    a rng seeded by (seed, purpose, job) — no wall clock, no global
    RNG.
    """

    def __init__(self,
                 num_jobs: int,
                 plan_spec: Dict[str, Any],
                 seed: int = 0,
                 accelerator: str = 'tpu-v5e-16',
                 work_s: float = 120.0,
                 ckpt_every_s: float = 30.0,
                 launch_duration_s: float = 4.0,
                 storm_frac: float = 0.6,
                 jitter: bool = True,
                 step_time_s: float = 1.0,
                 tokens_per_step: float = 8192.0,
                 horizon_s: float = _DEFAULT_HORIZON_S,
                 launch_deadline_s: float = 1800.0) -> None:
        self.num_jobs = int(num_jobs)
        self.plan_spec = plan_spec
        self.seed = int(seed)
        self.accelerator = accelerator
        self.work_s = float(work_s)
        self.ckpt_every_s = float(ckpt_every_s)
        self.launch_duration_s = float(launch_duration_s)
        self.storm_frac = float(storm_frac)
        self.jitter = bool(jitter)
        self.step_time_s = float(step_time_s)
        self.tokens_per_step = float(tokens_per_step)
        self.horizon_s = float(horizon_s)
        self.launch_deadline_s = float(launch_deadline_s)

        from skypilot_tpu.catalog import gcp_catalog
        self.zones = gcp_catalog.get_tpu_zones(accelerator)
        if not self.zones:
            raise ValueError(f'no catalog zones for {accelerator!r}')

        self.clock = SimClock(horizon_s=self.horizon_s)
        self._jobs: Dict[str, _SimJob] = {}
        # Relaunch-concurrency timeline: (virtual_t, +1/-1) deltas
        # for launches that FOLLOW a preemption (initial placement
        # excluded — the herd under test is the recovery herd).
        self._relaunch_deltas: List[Tuple[float, int]] = []
        self._agent_ids = 0

    # -- stubbed cloud --------------------------------------------------
    def _storm_windows(self) -> List[Dict[str, Any]]:
        return [w for w in faults.windows('jobs.monitor_probe')
                if w['action'] == 'drop']

    def _death_time(self, zone: str, up_since: float
                    ) -> Optional[float]:
        """When a cluster in `zone` (up from `up_since`) gets
        preempted, per the installed plan's storm windows; None =
        survives. A window scoped to another zone is ignored; an
        unscoped window hits every zone."""
        deaths = []
        for w in self._storm_windows():
            scoped = w['scope'].get('zone')
            if scoped is not None and scoped != zone:
                continue
            if w['end_s'] <= up_since:
                continue
            deaths.append(max(w['start_s'], up_since))
        return min(deaths) if deaths else None

    def _place(self, job: _SimJob, relaunch: bool) -> str:
        storm_zones = {w['scope'].get('zone')
                       for w in self._storm_windows()}
        storm_zones.discard(None)
        if not relaunch and storm_zones:
            # Seeded initial skew toward the storm zone(s): the bench
            # controls how much of the fleet the storm hits.
            if job.rng.random() < self.storm_frac:
                return job.rng.choice(sorted(storm_zones))
            pool = [z for z in self.zones if z not in storm_zones]
            return job.rng.choice(pool or self.zones)
        if relaunch:
            # Preemptions cluster by zone capacity: recovery avoids
            # the zone that just died (the strategy layer's
            # eager-next-region intuition, applied by the stub
            # provisioner's zone picker).
            pool = [z for z in self.zones if z != job.zone]
            return job.rng.choice(pool or self.zones)
        return job.rng.choice(self.zones)

    def _sim_launch(self, task, cluster_name=None, **kwargs):
        """Stands in for `execution.launch` under the real
        `_launch_with_retries`."""
        del task, kwargs
        job = self._jobs[cluster_name]
        now = self.clock.now
        relaunch = job.launches > 0
        if relaunch:
            self._relaunch_deltas.append((now, +1))
        try:
            # Provisioning occupies virtual time — this is what makes
            # concurrent attempts OVERLAP and the herd measurable.
            self.clock.sleep(self.launch_duration_s)
        finally:
            if relaunch:
                self._relaunch_deltas.append((self.clock.now, -1))
        now = self.clock.now
        if relaunch and job.zone is not None and \
                job.dead_at is not None:
            # Close out the lost cluster: bill it up to its death,
            # roll progress back to the last checkpoint.
            self.segments_close(job, job.dead_at)
            at_death = job.progress(job.dead_at)
            rolled = (at_death // self.ckpt_every_s) * \
                self.ckpt_every_s
            job.lost_s += at_death - rolled
            job.base = rolled
            job.preemptions += 1
        job.zone = self._place(job, relaunch=relaunch)
        job.seg_start = now
        job.dead_at = self._death_time(job.zone, now)
        job.launches += 1
        self._agent_ids += 1
        return self._agent_ids, object()

    @staticmethod
    def segments_close(job: _SimJob, end: float) -> None:
        job.segments.append((job.zone, job.seg_start, end))

    def _make_agent(self, job: _SimJob):
        sim = self

        class _Agent:

            def get_job(self, agent_job_id):
                del agent_job_id
                now = sim.clock.now
                if job.cluster_dead(now):
                    raise requests.RequestException(
                        'simulated preemption: cluster gone')
                if job.progress(now) >= job.work_s:
                    if job.completed_at is None:
                        job.completed_at = (job.seg_start +
                                            (job.work_s - job.base))
                        sim.segments_close(job, job.completed_at)
                    from skypilot_tpu.agent import job_lib
                    return {'status': job_lib.JobStatus.SUCCEEDED}
                from skypilot_tpu.agent import job_lib
                return {'status': job_lib.JobStatus.RUNNING}

        return _Agent()

    # -- run ------------------------------------------------------------
    def run(self) -> Dict[str, Any]:
        from skypilot_tpu.jobs import controller as ctrl_mod
        from skypilot_tpu.jobs import recovery_strategy as rs
        from skypilot_tpu.jobs import state
        from skypilot_tpu import execution
        from skypilot_tpu.utils import ux_utils

        shim = _TimeShim(self.clock)
        home = tempfile.mkdtemp(prefix='fleet-sim-')
        saved_env = os.environ.get('SKYPILOT_TPU_HOME')
        saved = {
            'ctrl_time': ctrl_mod.time, 'rs_time': rs.time,
            'state_time': state.time, 'launch': execution.launch,
            'quiet': ux_utils._QUIET, 'plan': faults.get_plan(),
        }
        os.environ['SKYPILOT_TPU_HOME'] = home
        ctrl_mod.time = shim
        rs.time = shim
        state.time = shim
        execution.launch = self._sim_launch
        ux_utils._QUIET = True
        faults.install_plan(
            faults.FaultPlan(self.plan_spec, clock=self.clock.time))
        try:
            return self._run_inner(ctrl_mod, state)
        finally:
            faults.install_plan(saved['plan'])
            ctrl_mod.time = saved['ctrl_time']
            rs.time = saved['rs_time']
            state.time = saved['state_time']
            execution.launch = saved['launch']
            ux_utils._QUIET = saved['quiet']
            if saved_env is None:
                os.environ.pop('SKYPILOT_TPU_HOME', None)
            else:
                os.environ['SKYPILOT_TPU_HOME'] = saved_env

    def _run_inner(self, ctrl_mod, state) -> Dict[str, Any]:
        stagger_rng = random.Random(f'{self.seed}:stagger')
        poll_s = ctrl_mod._POLL_SECONDS
        workers: List[_Worker] = []
        controllers = []
        task_config = {
            'name': 'fleet-sim',
            'run': 'true',
            'resources': {
                'cloud': 'gcp',
                'accelerators': self.accelerator,
                'use_spot': True,
                'job_recovery': {
                    'strategy': 'failover',
                    'launch_deadline_seconds': self.launch_deadline_s,
                },
            },
        }
        for i in range(self.num_jobs):
            job_id = state.submit_job(
                name=f'fleet-{i}', task_config=task_config,
                strategy='failover', max_restarts_on_errors=0,
                user='fleet-sim')
            record = state.get_job(job_id)
            sim_job = _SimJob(
                job_id, record['cluster_name'], self.work_s,
                self.ckpt_every_s,
                rng=random.Random(f'{self.seed}:job:{i}'))
            self._jobs[record['cluster_name']] = sim_job
            ctrl = ctrl_mod.JobController(job_id)
            ctrl.executor.jitter = self.jitter
            # String seeds everywhere: random.Random(str) hashes via
            # sha512 (stable across processes), while tuple seeds
            # fall back to the per-process salted hash() and would
            # silently break the byte-identical-JSON contract.
            ctrl.executor.rng = random.Random(
                f'{self.seed}:backoff:{i}')
            ctrl._agent = (lambda j=sim_job: self._make_agent(j))
            ctrl._zone = (lambda j=sim_job: j.zone)
            controllers.append(ctrl)
            workers.append(_Worker(
                i, wake_at=stagger_rng.uniform(0.0, poll_s)))

        def _body(worker: _Worker, ctrl) -> None:
            self.clock.register(worker)
            worker.go.wait()
            worker.go.clear()
            try:
                ctrl.run()
            except BaseException as e:  # noqa: BLE001
                worker.error = e
            finally:
                worker.done = True
                worker.yielded.set()

        for worker, ctrl in zip(workers, controllers):
            worker.thread = threading.Thread(
                target=_body, args=(worker, ctrl), daemon=True)
            worker.thread.start()
        self.clock.run_all(workers)
        for worker in workers:
            worker.thread.join(timeout=60)
        errors = [w.error for w in workers if w.error is not None]
        if errors:
            raise RuntimeError(
                f'{len(errors)} fleet-sim workers crashed outside '
                f'the controller: {errors[:3]!r}')
        return self._summarize(state)

    # -- reporting ------------------------------------------------------
    def _summarize(self, state) -> Dict[str, Any]:
        from skypilot_tpu.catalog import gcp_catalog
        jobs = state.get_jobs()
        statuses: Dict[str, int] = {}
        for rec in jobs:
            key = rec['status'].value
            statuses[key] = statuses.get(key, 0) + 1
        events = state.get_recovery_events()
        latencies = sorted(
            e['recovered_at'] - e['preempted_at'] for e in events
            if e['recovered_at'] is not None)
        open_events = sum(1 for e in events
                          if e['recovered_at'] is None)
        by_id = {j.job_id: j for j in self._jobs.values()}
        hit = [j for j in by_id.values() if j.preemptions > 0]
        hit_recovered = [
            j for j in hit
            if state.get_job(j.job_id)['status'] ==
            state.ManagedJobStatus.SUCCEEDED]
        zone_preemptions: Dict[str, int] = {}
        for e in events:
            z = e['zone'] or 'unknown'
            zone_preemptions[z] = zone_preemptions.get(z, 0) + 1

        cost = 0.0
        for j in by_id.values():
            for zone, start, end in j.segments:
                hourly = gcp_catalog.get_accelerator_hourly_cost(
                    self.accelerator, 1, use_spot=True, zone=zone)
                cost += hourly * max(0.0, end - start) / 3600.0

        hist, max_inflight = self._concurrency_histogram()
        steps_lost = sum(j.lost_s for j in by_id.values()) / \
            self.step_time_s
        summary = {
            'num_jobs': self.num_jobs,
            'seed': self.seed,
            'jitter': self.jitter,
            'accelerator': self.accelerator,
            'work_s': self.work_s,
            'ckpt_every_s': self.ckpt_every_s,
            'launch_duration_s': self.launch_duration_s,
            'storm_windows': self._storm_windows(),
            'final_statuses': dict(sorted(statuses.items())),
            'storm_hit_jobs': len(hit),
            'storm_hit_recovered': len(hit_recovered),
            'preemptions_total': sum(j.preemptions
                                     for j in by_id.values()),
            'preemptions_by_zone': dict(
                sorted(zone_preemptions.items())),
            'recovery_events': len(events),
            'recovery_events_open': open_events,
            'recovery_latency_s': {
                'p50': _pct(latencies, 50.0),
                'p95': _pct(latencies, 95.0),
                'p99': _pct(latencies, 99.0),
                'max': latencies[-1] if latencies else None,
            },
            'steps_lost': steps_lost,
            'tokens_lost': steps_lost * self.tokens_per_step,
            'relaunch_concurrency': {
                'max': max_inflight,
                'histogram': hist,
            },
            'sim_cost_usd': cost,
            'virtual_duration_s': self.clock.now,
        }
        return _round_floats(summary)

    def _concurrency_histogram(self
                               ) -> Tuple[Dict[str, float], int]:
        """{inflight_level: virtual seconds spent there} over the
        relaunch timeline, plus the peak level."""
        deltas = sorted(self._relaunch_deltas)
        hist: Dict[str, float] = {}
        level = 0
        peak = 0
        prev_t: Optional[float] = None
        for t, d in deltas:
            if prev_t is not None and level > 0 and t > prev_t:
                key = str(level)
                hist[key] = hist.get(key, 0.0) + (t - prev_t)
            level += d
            peak = max(peak, level)
            prev_t = t
        return ({k: hist[k] for k in sorted(hist, key=int)}, peak)


def _pct(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _round_floats(obj, ndigits: int = 3):
    """Stable presentation (and a visual guard against wall-clock
    values leaking in: every float in the summary is virtual-time or
    catalog-derived, so rounding loses nothing that matters)."""
    if isinstance(obj, float):
        return round(obj, ndigits)
    if isinstance(obj, dict):
        return {k: _round_floats(v, ndigits) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_round_floats(v, ndigits) for v in obj]
    return obj


def default_storm_plan(zone: str = 'us-east5-b',
                       seed: int = 2026) -> Dict[str, Any]:
    """The canonical fleet-bench scenario (also committed as
    examples/fault_plans/zone_storm.json): a zone-wide spot storm in
    a seeded window, under a hard capacity crunch — EVERY launch
    attempt inside the crunch window fails (a melting zone's
    replacement capacity takes minutes to free up across the fleet),
    so when capacity returns, every affected controller's retry
    timer is what decides whether the relaunches arrive as a
    thundering herd or a spread-out trickle. That is exactly the
    regime `Backoff(jitter=True)` exists for, and what the fleet
    bench's relaunch-concurrency histogram measures. The crunch
    window [40, 150] covers any storm start drawn from [40, 60]
    plus its 90s duration, and is comfortably shorter than the
    backoff ladder's 10-attempt span, so no job can exhaust its
    retry budget inside it."""
    return {
        'seed': seed,
        'rules': [
            {'point': 'jobs.preempt_storm',
             'scope': {'zone': zone},
             'start_range': [40.0, 60.0],
             'duration_s': 90.0},
            {'point': 'jobs.launch', 'action': 'raise',
             'exc': 'skypilot_tpu.exceptions.'
                    'ResourcesUnavailableError',
             'message': 'spot capacity crunch after zone storm',
             'start_s': 40.0,
             'duration_s': 110.0},
        ],
    }
