"""Robustness toolkit: deterministic fault injection + the error
types the hardened serving/jobs paths raise (deadlines, load
shedding, engine death). See docs/internals.md for the
injection-point catalog and docs/guides.md for the operator knobs."""
from skypilot_tpu.robustness import faults
from skypilot_tpu.robustness.errors import (DeadlineExceededError,
                                            EngineDeadError,
                                            QueueSaturatedError)

__all__ = ['faults', 'DeadlineExceededError', 'EngineDeadError',
           'QueueSaturatedError']
