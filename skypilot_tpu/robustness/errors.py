"""Robustness error types shared across the serving and jobs planes.

Dependency-free on purpose: `models/batching.py` (the engine),
`inference/http_server.py` (the HTTP status mapping), and the chaos
tests all import these, and none of them should pull in the other
layers to do so.
"""
from __future__ import annotations


class DeadlineExceededError(Exception):
    """A request outlived its deadline: expired while queued, or
    reaped mid-decode by the engine's deadline sweep. The HTTP layer
    maps this to 504."""


class QueueSaturatedError(Exception):
    """Admission control shed this request: the engine's bounded
    queue (`max_queue_requests` / `max_queue_tokens`) is full. The
    HTTP layer maps this to 429 with a Retry-After hint."""

    def __init__(self, message: str, retry_after_s: float = 1.0
                 ) -> None:
        super().__init__(message)
        self.retry_after_s = retry_after_s


class EngineDeadError(Exception):
    """The engine's scheduler thread died: the engine fails fast
    (submit raises, pending futures resolve with this) instead of
    hanging clients; `/readyz` flips to 503."""


class AdapterNotFoundError(Exception):
    """The request named a model/adapter the serving process does not
    have: not the base model and not in the adapter registry's
    inventory. The HTTP layer maps this to the OpenAI-style 404
    error object (code `model_not_found`)."""


class AdapterLoadError(Exception):
    """A registered adapter failed to load onto the device (corrupt
    artifact, shape/rank mismatch with the serving store, or an
    injected `adapters.load` fault). The request fails 503 — the
    engine and every other adapter keep serving."""


class SessionMigratedError(Exception):
    """The engine evacuated this request's slot (drain, preemption
    notice, or rebalancing): the future resolves with this instead of
    a result, carrying everything the HTTP layer needs to finish the
    session elsewhere — the committed token sequence (prompt +
    generated so far), the remaining generation budget, the sampling
    knobs, and the packed KV page chain covering the committed full
    pages. The HTTP thread that owns the client connection ships the
    chain to a peer and proxies the response tail; any failure falls
    back to resubmitting locally against the promoted (still-warm)
    pages — never a client-visible error."""

    def __init__(self, record: dict) -> None:
        super().__init__(
            f'session migrated after '
            f'{len(record.get("tokens") or []) - int(record.get("prompt_len", 0))}'
            f' generated tokens (reason: {record.get("reason", "")})')
        self.record = record


class CheckpointNotFoundError(Exception):
    """No checkpoint exists to restore (empty/absent directory, or
    an explicitly requested step that was never written). Typed —
    never an `assert`, which vanishes under `python -O` — so the
    recovery path can distinguish "start fresh" from "data lost"."""


class CheckpointCorruptionError(Exception):
    """A checkpoint failed its sha256 manifest verification (torn
    write, truncated upload, bit rot). Raised per-step during
    restore; the manager falls back to the newest step that DOES
    verify, and raises this only when every candidate is corrupt —
    one bad write must cost one checkpoint interval of progress,
    not the job."""
