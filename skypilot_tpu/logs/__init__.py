"""External log shipping: streaming aggregators + bucket archives.

Reference: sky/logs/__init__.py:11-21 — `logs.store` selects a
fluent-bit-based agent (GCP Cloud Logging / AWS CloudWatch) installed
on every host at provision time. This build supports BOTH forms under
one config key:

    logs:
      store: gcp            # stream to Cloud Logging (fluent-bit)
      # store: aws          # stream to CloudWatch Logs
      # store: gs://bucket  # archive finished jobs' log dirs (rsync)

Bucket/path stores are handled by the job driver after each job
(`agent/job_driver._ship_logs`); `gcp`/`aws` install a fluent-bit
tail -> cloud-logging pipeline via `get_aggregator()` at instance
setup, so logs stream live, survive host loss, and land in the
cloud's native log explorer with cluster/job/rank labels.
"""
from __future__ import annotations

from typing import Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_config
from skypilot_tpu.logs.aggregator import (CloudwatchAggregator,
                                          LoggingAggregator,
                                          StackdriverAggregator)

AGGREGATOR_STORES = ('gcp', 'aws')


def get_aggregator() -> Optional[LoggingAggregator]:
    """The configured streaming aggregator, or None (bucket stores and
    unset config both return None — the driver handles buckets)."""
    store = sky_config.get_nested(('logs', 'store'))
    if store is None or str(store) not in AGGREGATOR_STORES:
        return None
    if store == 'gcp':
        return StackdriverAggregator(
            sky_config.get_nested(('logs', 'gcp')) or {})
    if store == 'aws':
        return CloudwatchAggregator(
            sky_config.get_nested(('logs', 'aws')) or {})
    raise exceptions.SkyError(f'invalid logs.store {store!r}')
