"""Fluent-bit log aggregators: tail agent job logs → cloud logging.

Reference: sky/logs/agent.py (FluentbitAgent) + gcp.py/aws.py. The
TPU-native differences:
- the tail glob covers BOTH the combined `run.log` and the per-rank
  `rank-<i>.log` files the gang driver writes, and the path regex
  lifts (job_id, rank) into log labels — a 64-host slice's logs
  arrive queryable by rank;
- setup is idempotent and runs as one command list through the
  ordinary command runners (no separate credential mount machinery:
  TPU VMs authenticate Cloud Logging via the metadata server by
  default, a service-account key file is the explicit override).
"""
from __future__ import annotations

import shlex
from typing import Any, Dict, List

from skypilot_tpu import constants

_CONF_DIR = '~/.sky-tpu-agent/fluentbit'
# run.log + rank-N.log under <home>/job_logs/<job_id>/
_LOG_GLOB = f'{constants.SKY_REMOTE_HOME}/job_logs/*/*.log'
_TAG_REGEX = (r'/job_logs/(?<job_id>\d+)/'
              r'(?<file>(run|rank-\d+))\.log$')

_INSTALL_FLUENTBIT = (
    'command -v fluent-bit >/dev/null 2>&1 || '
    '[ -x /opt/fluent-bit/bin/fluent-bit ] || '
    '(curl -fsSL https://raw.githubusercontent.com/fluent/fluent-bit/'
    'master/install.sh | sh) || '
    '(sudo apt-get update -y && sudo apt-get install -y fluent-bit)')

_FLUENTBIT_BIN = ('$(command -v fluent-bit || '
                  'echo /opt/fluent-bit/bin/fluent-bit)')


class LoggingAggregator:
    """Base: the INPUT/parser half is shared; outputs differ."""

    def __init__(self, config: Dict[str, Any]) -> None:
        self.config = dict(config or {})

    # -- per-store ----------------------------------------------------------
    def output_config(self, cluster_name: str) -> str:
        raise NotImplementedError

    def precheck_command(self) -> str:
        """Fails fast with a clear message when credentials are
        impossible (better than fluent-bit retry loops)."""
        return 'true'

    # -- shared -------------------------------------------------------------
    def fluentbit_config(self, cluster_name: str) -> str:
        """Classic-mode fluent-bit config: tail + path-label lifting +
        the store's OUTPUT section."""
        return f"""\
[SERVICE]
    flush        5
    daemon       off
    parsers_file parsers.conf

[INPUT]
    name             tail
    path             {_LOG_GLOB}
    tag_regex        {_TAG_REGEX}
    tag              job.<job_id>.<file>
    refresh_interval 5
    skip_long_lines  on

[FILTER]
    name   modify
    match  job.*
    add    cluster {cluster_name}

{self.output_config(cluster_name)}
"""

    def setup_commands(self, cluster_name: str) -> List[str]:
        """Idempotent: install, write config, (re)start the shipper."""
        conf = self.fluentbit_config(cluster_name)
        return [
            self.precheck_command(),
            _INSTALL_FLUENTBIT,
            f'mkdir -p {_CONF_DIR}',
            f'printf %s {shlex.quote(conf)} > {_CONF_DIR}/fluentbit.conf',
            # Resolve ~ (fluent-bit does not) and restart the daemon.
            f'sed -i "s|~|$HOME|g" {_CONF_DIR}/fluentbit.conf',
            f'pkill -f "fluent-bit.*{_CONF_DIR}" 2>/dev/null || true',
            f'nohup {_FLUENTBIT_BIN} -c {_CONF_DIR}/fluentbit.conf '
            f'> {_CONF_DIR}/fluentbit.log 2>&1 &',
        ]


class StackdriverAggregator(LoggingAggregator):
    """GCP Cloud Logging (reference: sky/logs/gcp.py). TPU VMs carry
    metadata-server credentials; `credentials_file` overrides for
    hosts outside GCP."""

    def precheck_command(self) -> str:
        cred = self.config.get('credentials_file')
        if cred:
            return (f'export GOOGLE_APPLICATION_CREDENTIALS={cred}; '
                    f'grep -q service_account {cred} || '
                    f'(echo "logs.gcp.credentials_file must be a '
                    f'service-account key" && exit 1)')
        return ('curl -s -m 2 http://metadata.google.internal '
                '>/dev/null || (echo "no GCP metadata server; set '
                'logs.gcp.credentials_file to a service-account key" '
                '&& exit 1)')

    def output_config(self, cluster_name: str) -> str:
        project = self.config.get('project_id', '')
        project_line = f'\n    export_to_project_id {project}' \
            if project else ''
        return f"""\
[OUTPUT]
    name      stackdriver
    match     job.*
    resource  global
    severity_key severity
    labels    cluster={cluster_name}{project_line}"""


class CloudwatchAggregator(LoggingAggregator):
    """AWS CloudWatch Logs (reference: sky/logs/aws.py)."""

    def output_config(self, cluster_name: str) -> str:
        region = self.config.get('region', 'us-east-1')
        group = self.config.get('log_group_name', 'skypilot-logs')
        stream_prefix = self.config.get('log_stream_prefix',
                                        f'{cluster_name}-')
        return f"""\
[OUTPUT]
    name              cloudwatch_logs
    match             job.*
    region            {region}
    log_group_name    {group}
    log_stream_prefix {stream_prefix}
    auto_create_group true"""
