"""Azure catalog: VM sizes, GPU accelerators, prices.

Reference: sky/catalog/azure_catalog.py — pandas over the hosted CSV
mirror. Same shape as `aws_catalog`; Azure availability zones are
numeric ('1'/'2'/'3') per region and the snapshot carries zonal rows
(prices are uniform across a region's zones), so zone-scoped failover
patterns (provision/failover_patterns.py ZonalAllocationFailed etc.)
have real zones to walk.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import pandas as pd

from skypilot_tpu.catalog import common


def _vm_df() -> pd.DataFrame:
    return common.read_catalog('azure_vms.csv')


def list_accelerators(
        name_filter: Optional[str] = None,
        region_filter: Optional[str] = None,
        case_sensitive: bool = False,
) -> Dict[str, List[common.InstanceTypeInfo]]:
    df = _vm_df()
    # Zonal rows duplicate (type, region): one entry per pair.
    df = df.drop_duplicates(subset=['InstanceType', 'Region'])
    acc_df = df[df['AcceleratorName'].notna()]
    if name_filter is not None:
        acc_df = acc_df[acc_df['AcceleratorName'].str.contains(
            name_filter, case=case_sensitive, regex=True)]
    if region_filter is not None:
        acc_df = acc_df[acc_df['Region'] == region_filter]
    result: Dict[str, List[common.InstanceTypeInfo]] = {}
    for _, row in acc_df.iterrows():
        info = common.InstanceTypeInfo(
            cloud='Azure',
            instance_type=row['InstanceType'],
            accelerator_name=row['AcceleratorName'],
            accelerator_count=float(row['AcceleratorCount']),
            cpu_count=row['vCPUs'],
            memory=row['MemoryGiB'],
            price=float(row['Price']),
            spot_price=float(row['SpotPrice']),
            region=row['Region'],
        )
        result.setdefault(row['AcceleratorName'], []).append(info)
    return result


def get_hourly_cost(instance_type: str, use_spot: bool,
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
    df = _vm_df()
    df = df[df['InstanceType'] == instance_type]
    if region is not None:
        df = df[df['Region'] == region]
    if zone is not None:
        df = df[df['AvailabilityZone'].astype(str) == str(zone)]
    if df.empty:
        raise ValueError(f'Unknown Azure instance type {instance_type!r} '
                         f'in region={region}.')
    col = 'SpotPrice' if use_spot else 'Price'
    return float(df[col].dropna().min())


def get_vcpus_mem_from_instance_type(
        instance_type: str) -> Tuple[Optional[float], Optional[float]]:
    df = _vm_df()
    df = df[df['InstanceType'] == instance_type]
    if df.empty:
        return None, None
    return float(df['vCPUs'].iloc[0]), float(df['MemoryGiB'].iloc[0])


def get_instance_type_for_cpus_mem(
        cpus: Optional[str], memory: Optional[str]) -> Optional[str]:
    df = _vm_df()
    df = df[df['AcceleratorName'].isna()]
    return common.get_instance_type_for_cpus_mem_impl(df, cpus, memory)


def get_default_instance_type(cpus: Optional[str] = None,
                              memory: Optional[str] = None) -> Optional[str]:
    if cpus is None and memory is None:
        cpus = '8+'
        memory = 'x4'
    return get_instance_type_for_cpus_mem(cpus, memory)


def get_accelerators_from_instance_type(
        instance_type: str) -> Optional[Dict[str, int]]:
    df = _vm_df()
    df = df[(df['InstanceType'] == instance_type)
            & df['AcceleratorName'].notna()]
    if df.empty:
        return None
    row = df.iloc[0]
    return {row['AcceleratorName']: int(row['AcceleratorCount'])}


def get_instance_type_for_accelerator(
        acc_name: str, acc_count: int) -> Optional[List[str]]:
    df = _vm_df()
    df = df[(df['AcceleratorName'] == acc_name)
            & (df['AcceleratorCount'] == acc_count)
            & df['InstanceType'].notna()]
    if df.empty:
        return None
    return sorted(df['InstanceType'].unique())



def validate_region_zone(region: Optional[str], zone: Optional[str]):
    df = _vm_df()
    if region is not None and region not in set(df['Region']):
        raise ValueError(f'Invalid region {region!r} for Azure; valid: '
                         f'{sorted(df["Region"].unique())}')
    if zone is not None:
        zdf = df
        if region is not None:
            zdf = df[df['Region'] == region]
        valid = set(zdf['AvailabilityZone'].dropna().astype(str))
        if str(zone) not in valid:
            raise ValueError(
                f'Invalid zone {zone!r} for Azure'
                f'{f" region {region}" if region else ""}: valid zones '
                f'are {sorted(valid)}.')
    return region, zone


def get_zones(region: str, instance_type: Optional[str] = None
              ) -> List[str]:
    """Zones of `region` carrying the offering, sorted — the zonal
    failover walk order."""
    df = _vm_df()
    df = df[df['Region'] == region]
    if instance_type is not None:
        df = df[df['InstanceType'] == instance_type]
    return sorted(df['AvailabilityZone'].dropna().astype(str).unique())



def regions_by_price(use_spot: bool = False,
                     instance_type: Optional[str] = None,
                     acc_name: Optional[str] = None) -> List[str]:
    """Regions with the offering, cheapest first (failover walk order)."""
    return common.regions_by_price_impl(_vm_df(), use_spot,
                                        instance_type=instance_type,
                                        acc_name=acc_name)
