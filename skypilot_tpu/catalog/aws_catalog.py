"""AWS catalog: EC2 instance types, GPU/Trainium accelerators, prices.

Reference: sky/catalog/aws_catalog.py — pandas over the hosted CSV
mirror. Same shape as `gcp_catalog` minus the TPU table; the bundled
snapshot covers the GPU training/serving families (p3/p4/p5/g4/g5),
Trainium/Inferentia, and the m6i/c6i/r6i CPU ladder.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import pandas as pd

from skypilot_tpu.catalog import common


def _vm_df() -> pd.DataFrame:
    return common.read_catalog('aws_vms.csv')


def list_accelerators(
        name_filter: Optional[str] = None,
        region_filter: Optional[str] = None,
        case_sensitive: bool = False,
) -> Dict[str, List[common.InstanceTypeInfo]]:
    df = _vm_df()
    acc_df = df[df['AcceleratorName'].notna()]
    if name_filter is not None:
        acc_df = acc_df[acc_df['AcceleratorName'].str.contains(
            name_filter, case=case_sensitive, regex=True)]
    if region_filter is not None:
        acc_df = acc_df[acc_df['Region'] == region_filter]
    result: Dict[str, List[common.InstanceTypeInfo]] = {}
    for _, row in acc_df.iterrows():
        info = common.InstanceTypeInfo(
            cloud='AWS',
            instance_type=row['InstanceType'],
            accelerator_name=row['AcceleratorName'],
            accelerator_count=float(row['AcceleratorCount']),
            cpu_count=row['vCPUs'],
            memory=row['MemoryGiB'],
            price=float(row['Price']),
            spot_price=float(row['SpotPrice']),
            region=row['Region'],
        )
        result.setdefault(row['AcceleratorName'], []).append(info)
    return result


def get_hourly_cost(instance_type: str, use_spot: bool,
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
    df = _vm_df()
    df = df[df['InstanceType'] == instance_type]
    if region is not None:
        df = df[df['Region'] == region]
    if zone is not None:
        df = df[df['AvailabilityZone'] == zone]
    if df.empty:
        raise ValueError(f'Unknown AWS instance type {instance_type!r} '
                         f'in region={region}.')
    col = 'SpotPrice' if use_spot else 'Price'
    return float(df[col].dropna().min())


def get_vcpus_mem_from_instance_type(
        instance_type: str) -> Tuple[Optional[float], Optional[float]]:
    df = _vm_df()
    df = df[df['InstanceType'] == instance_type]
    if df.empty:
        return None, None
    return float(df['vCPUs'].iloc[0]), float(df['MemoryGiB'].iloc[0])


def get_instance_type_for_cpus_mem(
        cpus: Optional[str], memory: Optional[str]) -> Optional[str]:
    # CPU-only choices: exclude accelerator hosts.
    df = _vm_df()
    df = df[df['AcceleratorName'].isna()]
    return common.get_instance_type_for_cpus_mem_impl(df, cpus, memory)


def get_default_instance_type(cpus: Optional[str] = None,
                              memory: Optional[str] = None) -> Optional[str]:
    if cpus is None and memory is None:
        cpus = '8+'
        memory = 'x4'
    return get_instance_type_for_cpus_mem(cpus, memory)


def get_accelerators_from_instance_type(
        instance_type: str) -> Optional[Dict[str, int]]:
    df = _vm_df()
    df = df[(df['InstanceType'] == instance_type)
            & df['AcceleratorName'].notna()]
    if df.empty:
        return None
    row = df.iloc[0]
    return {row['AcceleratorName']: int(row['AcceleratorCount'])}


def get_instance_type_for_accelerator(
        acc_name: str, acc_count: int) -> Optional[List[str]]:
    df = _vm_df()
    df = df[(df['AcceleratorName'] == acc_name)
            & (df['AcceleratorCount'] == acc_count)
            & df['InstanceType'].notna()]
    if df.empty:
        return None
    return sorted(df['InstanceType'].unique())



def zones_for_instance_type(instance_type: str,
                            region: Optional[str] = None) -> List[str]:
    df = _vm_df()
    df = df[df['InstanceType'] == instance_type]
    if region is not None:
        df = df[df['Region'] == region]
    return sorted(df['AvailabilityZone'].unique())


def validate_region_zone(region: Optional[str], zone: Optional[str]):
    # AWS zones are `<region><letter>` (us-east-1a), so the generic
    # `rsplit('-')` region inference doesn't apply; validate against
    # the catalog's (Region, AvailabilityZone) pairs directly.
    df = _vm_df()
    if region is not None and region not in set(df['Region']):
        raise ValueError(f'Invalid region {region!r} for AWS; valid: '
                         f'{sorted(df["Region"].unique())}')
    if zone is not None:
        zdf = df[df['AvailabilityZone'] == zone]
        if zdf.empty:
            raise ValueError(f'Invalid zone {zone!r} for AWS.')
        zone_region = zdf['Region'].iloc[0]
        if region is not None and zone_region != region:
            raise ValueError(f'Zone {zone!r} is not in region {region!r}.')
        region = zone_region
    return region, zone



def regions_by_price(use_spot: bool = False,
                     instance_type: Optional[str] = None,
                     acc_name: Optional[str] = None) -> List[str]:
    """Regions with the offering, cheapest first (failover walk order)."""
    return common.regions_by_price_impl(_vm_df(), use_spot,
                                        instance_type=instance_type,
                                        acc_name=acc_name)
