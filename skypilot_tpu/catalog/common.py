"""Catalog infrastructure: pandas over bundled data + mirror refresh.

Reference pattern: sky/catalog/common.py:245 — pandas DataFrames
loaded from CSVs fetched from a hosted mirror with a local TTL cache.
This build bundles a pricing/region snapshot in-package (zero-egress
environments keep working); `fetch_remote_catalog` refreshes a CSV
from the mirror into `~/.sky-tpu/catalogs/<schema>/`, and
`read_catalog` prefers a refreshed copy over the bundled snapshot.
`stpu check` triggers a best-effort refresh of every bundled catalog.
"""
from __future__ import annotations

import io
import os
import sys
import time
from typing import Callable, Dict, List, NamedTuple, Optional

import pandas as pd

_CATALOG_DIR = os.path.join(os.path.dirname(__file__), 'data')
# Bump when a catalog's column contract changes: refreshed copies are
# namespaced per schema so an old cache can never poison a new binary.
_SCHEMA_VERSION = 'v1'

_df_cache: Dict[str, pd.DataFrame] = {}


def _mirror_url() -> Optional[str]:
    """Refresh is opt-in: unset SKYPILOT_CATALOG_MIRROR disables it.

    The bundled snapshot uses this project's own filenames/columns, so
    pointing at a mirror means hosting files as <mirror>/<schema>/
    <filename> (any static file server works). There is no default
    mirror — a hardcoded URL that does not actually carry our layout
    would just generate doomed 404 requests on every `stpu check`.
    """
    return os.environ.get('SKYPILOT_CATALOG_MIRROR') or None


def _cache_dir() -> str:
    return os.path.expanduser(
        os.environ.get('SKYPILOT_CATALOG_CACHE', '~/.sky-tpu/catalogs'))


def _refreshed_path(filename: str) -> str:
    return os.path.join(_cache_dir(), _SCHEMA_VERSION, filename)


def fetch_remote_catalog(filename: str, *, ttl_hours: float = 24.0,
                         timeout: float = 10.0,
                         verbose: bool = False) -> Optional[str]:
    """Refresh one catalog CSV from the configured mirror.

    Returns the local cached path on success (or when a fresh-enough
    copy already exists), None when no mirror is configured, the
    mirror is unreachable, or the payload fails schema validation —
    callers fall back to the bundled snapshot either way, so this is
    always safe to attempt. Failures are silent unless `verbose`.
    """
    def _log(msg: str) -> None:
        if verbose:
            print(f'catalog refresh: {filename}: {msg}', file=sys.stderr)

    mirror = _mirror_url()
    if mirror is None:
        return None
    dest = _refreshed_path(filename)
    if os.path.exists(dest) and \
            time.time() - os.path.getmtime(dest) < ttl_hours * 3600:
        return dest
    url = f'{mirror}/{_SCHEMA_VERSION}/{filename}'
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            data = resp.read().decode('utf-8')
    except Exception as e:  # pylint: disable=broad-except
        _log(str(e))
        return None
    # Schema gate: a refreshed file must carry at least the bundled
    # snapshot's columns, or every consumer downstream breaks.
    try:
        new_df = pd.read_csv(io.StringIO(data))
    except Exception as e:  # pylint: disable=broad-except
        _log(f'unparsable payload ({e})')
        return None
    bundled = os.path.join(_CATALOG_DIR, filename)
    if os.path.exists(bundled):
        need = set(pd.read_csv(bundled, nrows=0).columns)
        if not need <= set(new_df.columns):
            _log(f'mirror copy is missing columns '
                 f'{sorted(need - set(new_df.columns))}; keeping the '
                 f'bundled snapshot')
            return None
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    tmp = f'{dest}.tmp.{os.getpid()}'
    with open(tmp, 'w', encoding='utf-8') as f:
        f.write(data)
    os.replace(tmp, dest)
    _df_cache.pop(filename, None)
    return dest


def refresh_catalogs(*, ttl_hours: float = 24.0, timeout: float = 10.0,
                     verbose: bool = False) -> List[str]:
    """Best-effort refresh of every bundled catalog; returns the
    filenames actually refreshed (or already fresh). No-op (empty
    list) when SKYPILOT_CATALOG_MIRROR is unset."""
    if _mirror_url() is None:
        return []
    refreshed = []
    for filename in sorted(os.listdir(_CATALOG_DIR)):
        if not filename.endswith('.csv'):
            continue
        if fetch_remote_catalog(filename, ttl_hours=ttl_hours,
                                timeout=timeout, verbose=verbose):
            refreshed.append(filename)
    return refreshed


class InstanceTypeInfo(NamedTuple):
    """One catalog row surfaced to the optimizer.

    Reference: sky/catalog/common.py InstanceTypeInfo.
    """
    cloud: str
    instance_type: Optional[str]
    accelerator_name: Optional[str]
    accelerator_count: float
    cpu_count: Optional[float]
    memory: Optional[float]
    price: float
    spot_price: float
    region: str


def read_catalog(filename: str,
                 generator: Optional[Callable[[], pd.DataFrame]] = None
                 ) -> pd.DataFrame:
    """Load a catalog DataFrame: a mirror-refreshed copy when present
    AND newer than the bundled CSV (so a package upgrade's corrected
    snapshot beats a stale cache from a dead mirror), else the bundled
    CSV, else a generator."""
    if filename in _df_cache:
        return _df_cache[filename]
    refreshed = _refreshed_path(filename)
    path = os.path.join(_CATALOG_DIR, filename)
    if os.path.exists(refreshed) and (
            not os.path.exists(path) or
            os.path.getmtime(refreshed) >= os.path.getmtime(path)):
        df = pd.read_csv(refreshed)
    elif os.path.exists(path):
        df = pd.read_csv(path)
    elif generator is not None:
        df = generator()
    else:
        raise FileNotFoundError(f'No bundled catalog {filename!r}')
    _df_cache[filename] = df
    return df


def clear_cache() -> None:
    _df_cache.clear()


def get_instance_type_for_cpus_mem_impl(
        df: pd.DataFrame, cpus: Optional[str],
        memory_gb_or_ratio: Optional[str]) -> Optional[str]:
    """Cheapest instance type satisfying cpu/memory constraints.

    `cpus`/`memory` accept '8', '8+' forms; memory also 'x<N>' meaning
    N GiB per vCPU (reference: sky/catalog/common.py
    get_instance_type_for_cpus_mem_impl).
    """
    df = df[df['AcceleratorName'].isna()] if 'AcceleratorName' in df else df
    df = df.drop_duplicates(subset=['InstanceType'])
    if cpus is not None:
        c = str(cpus)
        if c.endswith('+'):
            df = df[df['vCPUs'] >= float(c[:-1])]
        else:
            df = df[df['vCPUs'] == float(c)]
    if memory_gb_or_ratio is not None:
        m = str(memory_gb_or_ratio)
        if m.startswith('x'):
            df = df[df['MemoryGiB'] >= df['vCPUs'] * float(m[1:])]
        elif m.endswith('+'):
            df = df[df['MemoryGiB'] >= float(m[:-1])]
        else:
            df = df[df['MemoryGiB'] == float(m)]
    if df.empty:
        return None
    df = df.sort_values(by=['Price', 'vCPUs'])
    return df['InstanceType'].iloc[0]


def regions_by_price_impl(df: pd.DataFrame, use_spot: bool,
                          instance_type: Optional[str] = None,
                          acc_name: Optional[str] = None) -> List[str]:
    """Regions carrying the offering, CHEAPEST FIRST (ties break by
    name). Failover loops walk this order so the first successful
    provision is also the cheapest available one — the reference gets
    this from its price-sorted candidate list."""
    if instance_type is not None:
        df = df[df['InstanceType'] == instance_type]
    if acc_name is not None:
        df = df[df['AcceleratorName'] == acc_name]
    col = 'SpotPrice' if use_spot else 'Price'
    df = df.dropna(subset=[col])
    if df.empty:
        return []
    grouped = df.groupby('Region')[col].min()
    return sorted(grouped.index, key=lambda r: (grouped[r], r))


def validate_region_zone_impl(df: pd.DataFrame, cloud_name: str,
                              region: Optional[str], zone: Optional[str]):
    """Validate that region/zone exist in the catalog; returns (region, zone)."""
    if region is not None:
        if region not in df['Region'].unique():
            raise ValueError(
                f'Invalid region {region!r} for {cloud_name}; valid: '
                f'{sorted(df["Region"].unique())}')
    if zone is not None:
        zones = df['AvailabilityZone'].dropna().unique()
        if zone not in zones:
            raise ValueError(
                f'Invalid zone {zone!r} for {cloud_name}.')
        inferred_region = zone.rsplit('-', 1)[0]
        if region is not None and inferred_region != region:
            raise ValueError(
                f'Zone {zone!r} is not in region {region!r}.')
        region = inferred_region
    return region, zone
