"""Catalog infrastructure: pandas over bundled data.

Reference pattern: sky/catalog/common.py — pandas DataFrames loaded
from CSVs fetched from a hosted mirror with local caching. This build
bundles a pricing/region snapshot in-package (zero-egress environment);
the hosted-mirror refresh hook is `fetch_remote_catalog`, gated on
network availability.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, NamedTuple, Optional

import pandas as pd

_CATALOG_DIR = os.path.join(os.path.dirname(__file__), 'data')
_HOSTED_CATALOG_URL = os.environ.get(
    'SKYPILOT_CATALOG_MIRROR',
    'https://raw.githubusercontent.com/skypilot-org/skypilot-catalog/master/catalogs')

_df_cache: Dict[str, pd.DataFrame] = {}


class InstanceTypeInfo(NamedTuple):
    """One catalog row surfaced to the optimizer.

    Reference: sky/catalog/common.py InstanceTypeInfo.
    """
    cloud: str
    instance_type: Optional[str]
    accelerator_name: Optional[str]
    accelerator_count: float
    cpu_count: Optional[float]
    memory: Optional[float]
    price: float
    spot_price: float
    region: str


def read_catalog(filename: str,
                 generator: Optional[Callable[[], pd.DataFrame]] = None
                 ) -> pd.DataFrame:
    """Load a catalog DataFrame from the bundled CSV or a generator."""
    if filename in _df_cache:
        return _df_cache[filename]
    path = os.path.join(_CATALOG_DIR, filename)
    if os.path.exists(path):
        df = pd.read_csv(path)
    elif generator is not None:
        df = generator()
    else:
        raise FileNotFoundError(f'No bundled catalog {filename!r}')
    _df_cache[filename] = df
    return df


def clear_cache() -> None:
    _df_cache.clear()


def get_instance_type_for_cpus_mem_impl(
        df: pd.DataFrame, cpus: Optional[str],
        memory_gb_or_ratio: Optional[str]) -> Optional[str]:
    """Cheapest instance type satisfying cpu/memory constraints.

    `cpus`/`memory` accept '8', '8+' forms; memory also 'x<N>' meaning
    N GiB per vCPU (reference: sky/catalog/common.py
    get_instance_type_for_cpus_mem_impl).
    """
    df = df[df['AcceleratorName'].isna()] if 'AcceleratorName' in df else df
    df = df.drop_duplicates(subset=['InstanceType'])
    if cpus is not None:
        c = str(cpus)
        if c.endswith('+'):
            df = df[df['vCPUs'] >= float(c[:-1])]
        else:
            df = df[df['vCPUs'] == float(c)]
    if memory_gb_or_ratio is not None:
        m = str(memory_gb_or_ratio)
        if m.startswith('x'):
            df = df[df['MemoryGiB'] >= df['vCPUs'] * float(m[1:])]
        elif m.endswith('+'):
            df = df[df['MemoryGiB'] >= float(m[:-1])]
        else:
            df = df[df['MemoryGiB'] == float(m)]
    if df.empty:
        return None
    df = df.sort_values(by=['Price', 'vCPUs'])
    return df['InstanceType'].iloc[0]


def validate_region_zone_impl(df: pd.DataFrame, cloud_name: str,
                              region: Optional[str], zone: Optional[str]):
    """Validate that region/zone exist in the catalog; returns (region, zone)."""
    if region is not None:
        if region not in df['Region'].unique():
            raise ValueError(
                f'Invalid region {region!r} for {cloud_name}; valid: '
                f'{sorted(df["Region"].unique())}')
    if zone is not None:
        zones = df['AvailabilityZone'].dropna().unique()
        if zone not in zones:
            raise ValueError(
                f'Invalid zone {zone!r} for {cloud_name}.')
        inferred_region = zone.rsplit('-', 1)[0]
        if region is not None and inferred_region != region:
            raise ValueError(
                f'Zone {zone!r} is not in region {region!r}.')
        region = inferred_region
    return region, zone
