"""GCP catalog: TPU slices first-class, plus host VMs and common GPUs.

Reference: sky/catalog/gcp_catalog.py — pandas over hosted CSVs with
TPU prices kept separately from host VMs (`:255-277,509-556`). This
build instead *generates* the TPU table from the topology model
(`utils/tpu_utils.py`) × a per-version price/region snapshot, so every
standard slice shape is present with host/ICI metadata, and bundles a
VM/GPU snapshot CSV.

Prices are an approximation snapshot of public GCP list prices
(per-chip-hour for TPUs), refreshable via the hosted-mirror hook.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import pandas as pd

from skypilot_tpu.catalog import common
from skypilot_tpu.utils import tpu_utils

# version -> ($/chip-hr on-demand, $/chip-hr spot, zones)
_TPU_PRICING: Dict[str, Tuple[float, float, List[str]]] = {
    'v2': (1.31, 0.44, ['us-central1-b', 'us-central1-c', 'europe-west4-a',
                        'asia-east1-c']),
    'v3': (2.00, 0.66, ['us-central1-a', 'us-central1-b', 'europe-west4-a']),
    'v4': (3.22, 1.13, ['us-central2-b']),
    'v5e': (1.20, 0.54, ['us-central1-a', 'us-west4-a', 'us-east1-d',
                         'us-east5-b', 'europe-west4-b', 'asia-southeast1-b']),
    'v5p': (4.20, 1.89, ['us-east5-a', 'us-central2-b', 'europe-west4-b']),
    'v6e': (2.70, 1.22, ['us-east5-b', 'us-central2-b', 'europe-west4-a',
                         'asia-northeast1-b', 'us-south1-a']),
}

# Spot preemption rate snapshot, preemptions per instance-hour per
# zone. Approximation of observed churn: big-pod zones under heavy
# reservation pressure (us-central2-b, us-east5-a) preempt spot
# capacity far more often than the quieter regional zones. This is
# the `PreemptionRate` column of the bundled TPU catalog; the
# optimizer turns it into a risk-adjusted effective price
# (jobs/policy.py) so spot placement stops chasing list price into
# the stormiest zones.
_ZONE_PREEMPTION_RATE: Dict[str, float] = {
    'us-central2-b': 0.55,
    'us-east5-a': 0.45,
    'us-east5-b': 0.30,
    'us-central1-a': 0.20,
    'us-central1-b': 0.25,
    'us-central1-c': 0.25,
    'us-west4-a': 0.15,
    'us-east1-d': 0.18,
    'us-south1-a': 0.10,
    'europe-west4-a': 0.12,
    'europe-west4-b': 0.16,
    'asia-east1-c': 0.22,
    'asia-northeast1-b': 0.14,
    'asia-southeast1-b': 0.08,
}
_DEFAULT_PREEMPTION_RATE = 0.25

# Max slice size available per zone (chips) — models that only a few
# zones carry the biggest pods.
_ZONE_MAX_CHIPS: Dict[str, int] = {
    'us-central2-b': 4096,
    'us-east5-a': 8192,
    'us-east5-b': 256,
    'us-central1-a': 256,
    'us-central1-b': 512,
    'us-central1-c': 512,
    'us-west4-a': 256,
    'us-east1-d': 256,
    'us-south1-a': 256,
    'europe-west4-a': 1024,
    'europe-west4-b': 1024,
    'asia-east1-c': 512,
    'asia-northeast1-b': 256,
    'asia-southeast1-b': 256,
}


def _generate_tpu_df() -> pd.DataFrame:
    rows = []
    for version, (price, spot_price, zones) in _TPU_PRICING.items():
        for suffix in tpu_utils.standard_slice_sizes(version):
            name = f'tpu-{version}-{suffix}'
            spec = tpu_utils.get_slice_spec(name)
            for zone in zones:
                if spec.num_chips > _ZONE_MAX_CHIPS.get(zone, 256):
                    continue
                region = zone.rsplit('-', 1)[0]
                rows.append({
                    'InstanceType': None,
                    'AcceleratorName': name,
                    'AcceleratorCount': 1.0,
                    'vCPUs': float(spec.host_vm_shape()[0] * spec.num_hosts),
                    'MemoryGiB': float(spec.host_vm_shape()[1] * spec.num_hosts),
                    'Price': round(price * spec.num_chips, 2),
                    'SpotPrice': round(spot_price * spec.num_chips, 2),
                    'Region': region,
                    'AvailabilityZone': zone,
                    'NumChips': spec.num_chips,
                    'NumHosts': spec.num_hosts,
                    'Topology': spec.topology_str,
                    'PreemptionRate': _ZONE_PREEMPTION_RATE.get(
                        zone, _DEFAULT_PREEMPTION_RATE),
                })
    return pd.DataFrame(rows)


def _tpu_df() -> pd.DataFrame:
    return common.read_catalog('gcp_tpus.csv', _generate_tpu_df)


def _vm_df() -> pd.DataFrame:
    return common.read_catalog('gcp_vms.csv')


# ---------------------------------------------------------------------------
# Query interface used by clouds/gcp.py and the optimizer
# ---------------------------------------------------------------------------
def list_accelerators(
        name_filter: Optional[str] = None,
        region_filter: Optional[str] = None,
        case_sensitive: bool = False,
) -> Dict[str, List[common.InstanceTypeInfo]]:
    dfs = [_tpu_df(), _vm_df()]
    result: Dict[str, List[common.InstanceTypeInfo]] = {}
    for df in dfs:
        acc_df = df[df['AcceleratorName'].notna()]
        if name_filter is not None:
            acc_df = acc_df[acc_df['AcceleratorName'].str.contains(
                name_filter, case=case_sensitive, regex=True)]
        if region_filter is not None:
            acc_df = acc_df[acc_df['Region'] == region_filter]
        for _, row in acc_df.iterrows():
            info = common.InstanceTypeInfo(
                cloud='GCP',
                instance_type=row['InstanceType'] if isinstance(
                    row['InstanceType'], str) else None,
                accelerator_name=row['AcceleratorName'],
                accelerator_count=float(row['AcceleratorCount']),
                cpu_count=row['vCPUs'],
                memory=row['MemoryGiB'],
                price=float(row['Price']),
                spot_price=float(row['SpotPrice']),
                region=row['Region'],
            )
            result.setdefault(row['AcceleratorName'], []).append(info)
    return result


def get_tpu_zones(acc_name: str) -> List[str]:
    df = _tpu_df()
    df = df[df['AcceleratorName'] == acc_name]
    return sorted(df['AvailabilityZone'].unique())


def get_vm_zones(instance_type: Optional[str] = None,
                 acc_name: Optional[str] = None,
                 region: Optional[str] = None) -> List[str]:
    """Zones (from the catalog, not synthesized) carrying a VM/GPU
    offering, optionally filtered to one region."""
    df = _vm_df()
    if instance_type is not None:
        df = df[df['InstanceType'] == instance_type]
    if acc_name is not None:
        df = df[df['AcceleratorName'] == acc_name]
    if region is not None:
        df = df[df['Region'] == region]
    return sorted(df['AvailabilityZone'].dropna().unique())


def regions_by_price(use_spot: bool = False,
                     instance_type: Optional[str] = None,
                     acc_name: Optional[str] = None) -> List[str]:
    """Regions with the offering, cheapest first (TPU or VM table)."""
    if acc_name is not None and tpu_utils.is_tpu(acc_name):
        return common.regions_by_price_impl(_tpu_df(), use_spot,
                                            acc_name=acc_name)
    return common.regions_by_price_impl(_vm_df(), use_spot,
                                        instance_type=instance_type,
                                        acc_name=acc_name)


def get_accelerator_hourly_cost(acc_name: str, count: int, use_spot: bool,
                                region: Optional[str] = None,
                                zone: Optional[str] = None) -> float:
    if tpu_utils.is_tpu(acc_name):
        df = _tpu_df()
    else:
        df = _vm_df()
    df = df[df['AcceleratorName'] == acc_name]
    if region is not None:
        df = df[df['Region'] == region]
    if zone is not None:
        df = df[df['AvailabilityZone'] == zone]
    if df.empty:
        raise ValueError(
            f'No pricing for accelerator {acc_name!r} in '
            f'region={region} zone={zone}.')
    col = 'SpotPrice' if use_spot else 'Price'
    prices = df[col].dropna()
    if prices.empty:
        raise ValueError(f'No {"spot " if use_spot else ""}pricing for '
                         f'{acc_name!r}.')
    return float(prices.min()) * count


def get_hourly_cost(instance_type: str, use_spot: bool,
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
    df = _vm_df()
    df = df[df['InstanceType'] == instance_type]
    if region is not None:
        df = df[df['Region'] == region]
    if zone is not None:
        df = df[df['AvailabilityZone'] == zone]
    if df.empty:
        raise ValueError(f'Unknown instance type {instance_type!r} '
                         f'in region={region}.')
    col = 'SpotPrice' if use_spot else 'Price'
    return float(df[col].dropna().min())


def get_vcpus_mem_from_instance_type(
        instance_type: str) -> Tuple[Optional[float], Optional[float]]:
    df = _vm_df()
    df = df[df['InstanceType'] == instance_type]
    if df.empty:
        return None, None
    return float(df['vCPUs'].iloc[0]), float(df['MemoryGiB'].iloc[0])


def get_instance_type_for_cpus_mem(
        cpus: Optional[str], memory: Optional[str]) -> Optional[str]:
    return common.get_instance_type_for_cpus_mem_impl(_vm_df(), cpus, memory)


def get_default_instance_type(cpus: Optional[str] = None,
                              memory: Optional[str] = None) -> Optional[str]:
    if cpus is None and memory is None:
        cpus = '8+'
        memory = 'x4'  # >= 4 GiB / vCPU, reference default
    return get_instance_type_for_cpus_mem(cpus, memory)


def get_accelerators_from_instance_type(
        instance_type: str) -> Optional[Dict[str, int]]:
    df = _vm_df()
    df = df[(df['InstanceType'] == instance_type)
            & df['AcceleratorName'].notna()]
    if df.empty:
        return None
    row = df.iloc[0]
    return {row['AcceleratorName']: int(row['AcceleratorCount'])}

def get_instance_type_for_accelerator(
        acc_name: str, acc_count: int) -> Optional[List[str]]:
    """GPU accelerators on GCP attach to specific VM families (a2/g2)."""
    df = _vm_df()
    df = df[(df['AcceleratorName'] == acc_name)
            & (df['AcceleratorCount'] == acc_count)
            & df['InstanceType'].notna()]
    if df.empty:
        return None
    return sorted(df['InstanceType'].unique())


def validate_region_zone(region: Optional[str], zone: Optional[str]):
    df = pd.concat([_tpu_df()[['Region', 'AvailabilityZone']],
                    _vm_df()[['Region', 'AvailabilityZone']]])
    return common.validate_region_zone_impl(df, 'GCP', region, zone)


def regions() -> List[str]:
    df = pd.concat([_tpu_df()[['Region']], _vm_df()[['Region']]])
    return sorted(df['Region'].unique())


def get_preemption_rate(acc_name: str,
                        region: Optional[str] = None,
                        zone: Optional[str] = None) -> Optional[float]:
    """Spot preemption rate (preemptions/hour) for a TPU offering,
    minimized over the matching zones (the zone spot placement would
    prefer). None when the catalog carries no rate data (e.g. a
    mirror-refreshed copy predating the column)."""
    if not tpu_utils.is_tpu(acc_name):
        return None
    df = _tpu_df()
    if 'PreemptionRate' not in df.columns:
        return None
    df = df[df['AcceleratorName'] == acc_name]
    if region is not None:
        df = df[df['Region'] == region]
    if zone is not None:
        df = df[df['AvailabilityZone'] == zone]
    rates = df['PreemptionRate'].dropna()
    if rates.empty:
        return None
    return float(rates.min())


def spot_zone_economics(
        acc_name: str,
        region: Optional[str] = None,
        zone: Optional[str] = None) -> List[Tuple[str, float, float]]:
    """(zone, spot_price, preemption_rate) per matching zone, sorted
    by RISK-ADJUSTED price (price x effective_cost_multiplier(rate),
    ties by zone name) — the order spot placement should walk.
    Zones without rate data rank by raw price (rate treated as 0).
    """
    from skypilot_tpu.jobs import policy
    if not tpu_utils.is_tpu(acc_name):
        return []
    df = _tpu_df()
    df = df[df['AcceleratorName'] == acc_name]
    if region is not None:
        df = df[df['Region'] == region]
    if zone is not None:
        df = df[df['AvailabilityZone'] == zone]
    df = df.dropna(subset=['SpotPrice'])
    out: List[Tuple[str, float, float]] = []
    for _, row in df.iterrows():
        rate = row.get('PreemptionRate')
        rate = float(rate) if pd.notna(rate) else 0.0
        out.append((str(row['AvailabilityZone']),
                    float(row['SpotPrice']), rate))
    out.sort(key=lambda zpr: (
        zpr[1] * policy.effective_cost_multiplier(zpr[2]), zpr[0]))
    return out


def get_tpu_slice_meta(acc_name: str) -> Dict[str, object]:
    """Hosts/chips/topology metadata for a TPU type (optimizer display)."""
    spec = tpu_utils.get_slice_spec(acc_name)
    return {
        'num_chips': spec.num_chips,
        'num_hosts': spec.num_hosts,
        'chips_per_host': spec.chips_per_host,
        'topology': spec.topology_str,
    }
