"""Optimizer: choose (cloud, region/zone, hardware) per task.

Reference: sky/optimizer.py (1805 LoC) — per-task candidate enumeration
(`_fill_in_launchable_resources` asking each enabled cloud for
feasible launchable resources), chain DAGs solved by DP over
inter-task egress cost (sky/optimizer.py:429), general DAGs by CBC ILP
(sky/optimizer.py:490). This build solves BOTH exactly with one pure-
python algorithm: min-sum variable elimination over the task graph
(unary factors = per-task objective, pairwise factors = per-edge
egress). On a chain it degenerates to exactly the reference's DP; on
general DAGs it is exponential only in treewidth (a diamond is
treewidth 2), so typical pipelines solve in microseconds with no ILP
dependency.

Objectives (reference OptimizeTarget): COST minimizes dollars
(runtime x hourly price + egress $); TIME minimizes estimated seconds
(per-candidate `task.estimate_runtime(resources)` + transfer time).

TPU-first: candidates for a TPU slice carry hosts/ICI topology, and
cost comparison includes per-chip spot pricing across zones.
"""
from __future__ import annotations

import collections
import enum
from typing import Dict, List, Optional, Set, Tuple

from skypilot_tpu import check as check_lib
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu.utils import ux_utils
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


# The default per-task runtime estimate (1 hour — hourly-price
# comparison) lives in Task.estimate_runtime.
# Cross-cloud transfer bandwidth assumed for TIME egress modeling
# (reference: sky/optimizer.py egress time uses a constant Gbps link).
_EGRESS_GBPS = 1.0


class Optimizer:

    @classmethod
    def optimize(cls, dag: dag_lib.Dag,
                 minimize: OptimizeTarget = OptimizeTarget.COST,
                 blocked_resources: Optional[
                     Set[resources_lib.Resources]] = None,
                 quiet: bool = False) -> dag_lib.Dag:
        """Fill in task.best_resources for every task in the dag."""
        dag.validate()
        per_task = {}
        for task in dag.get_sorted_tasks():
            candidates = cls._enumerate_candidates(task, blocked_resources)
            if not candidates:
                fuzzy = cls._fuzzy_candidates(task)
                hint = (f' Try: {", ".join(fuzzy[:6])}.' if fuzzy else '')
                raise exceptions.ResourcesUnavailableError(
                    f'No launchable resources satisfy the request for task '
                    f'{task.name or "<unnamed>"}: '
                    f'{sorted(str(r) for r in task.resources)}.{hint}')
            per_task[task] = candidates

        choice = cls._optimize_exact(dag, per_task, minimize,
                                     blocked_resources)

        for task, (resources, cost) in choice.items():
            task.best_resources = resources
            task.estimated_cost = cost  # type: ignore[attr-defined]
        if not quiet:
            cls._print_table(dag, per_task, choice)
        return dag

    # ------------------------------------------------------------------
    @classmethod
    def optimize_group(
        cls, tasks: List[task_lib.Task],
        minimize: OptimizeTarget = OptimizeTarget.COST,
        blocked_resources: Optional[Set[resources_lib.Resources]] = None,
        quiet: bool = False,
    ) -> Optional[Tuple[str, str]]:
        """ONE joint placement for a job group: the same cloud+region
        for every member (reference: sky/optimizer.py:1037
        optimize_job_group / _optimize_same_infra — the SAME_INFRA
        constraint keeps RL actor/learner pairs and disaggregated
        serving on intra-region links).

        Pins each task's best_resources to the chosen (cloud, region)
        and returns it; returns None when no common infra exists
        (caller falls back to independent placement, matching the
        reference's fallback).
        """
        # task -> {(cloud, region): (candidate_pinned_to_region, objective)}
        per_task: List[Tuple[task_lib.Task, Dict]] = []
        for task in tasks:
            candidates = cls._enumerate_candidates(task, blocked_resources)
            if not candidates:
                fuzzy = cls._fuzzy_candidates(task)
                hint = (f' Try: {", ".join(fuzzy[:6])}.' if fuzzy else '')
                raise exceptions.ResourcesUnavailableError(
                    f'No launchable resources satisfy the request for '
                    f'group member {task.name or "<unnamed>"}.{hint}')
            infra_map: Dict[Tuple[str, str],
                            Tuple[resources_lib.Resources, float]] = {}
            for cand, _, seconds in candidates:
                cloud = cand.cloud
                try:
                    regions = cloud.regions_with_offering(
                        cand.instance_type, cand.accelerators,
                        cand.use_spot, cand.region, cand.zone)
                except Exception:  # pylint: disable=broad-except
                    continue
                for region in regions:
                    pinned = cand.copy(region=region.name)
                    try:
                        hourly = pinned.get_hourly_cost()
                    except ValueError:
                        continue
                    if minimize == OptimizeTarget.TIME:
                        objective = seconds
                    else:
                        objective = (hourly * task.num_nodes *
                                     seconds / 3600.0)
                    key = (cloud.canonical_name(), region.name)
                    if key not in infra_map or \
                            objective < infra_map[key][1]:
                        infra_map[key] = (pinned, objective)
            per_task.append((task, infra_map))

        common = set(per_task[0][1])
        for _, infra_map in per_task[1:]:
            common &= set(infra_map)
        if not common:
            return None
        best = min(common,
                   key=lambda k: (sum(m[k][1] for _, m in per_task), k))
        for task, infra_map in per_task:
            task.best_resources = infra_map[best][0]
        if not quiet:
            total = sum(m[best][1] for _, m in per_task)
            unit = 'h' if minimize == OptimizeTarget.TIME else '$'
            names = ', '.join(t.name or '<unnamed>' for t, _ in per_task)
            ux_utils.log(
                f'Job group placement: {best[0]}/{best[1]} for all '
                f'{len(per_task)} members ({names}) — joint estimate '
                f'{total:.2f}{unit}.')
        return best

    # ------------------------------------------------------------------
    @classmethod
    def _enumerate_candidates(
        cls, task: task_lib.Task,
        blocked_resources: Optional[Set[resources_lib.Resources]],
    ) -> List[Tuple[resources_lib.Resources, float, float]]:
        """All launchable (resources, est_cost, est_seconds) triples.

        Reference: sky/optimizer.py:1671 _fill_in_launchable_resources.
        """
        import skypilot_tpu.clouds  # noqa: F401
        enabled = check_lib.get_cached_enabled_clouds()
        out: List[Tuple[resources_lib.Resources, float, float]] = []
        for requested in task.resources:
            if requested.cloud is not None:
                cloud_names = [requested.cloud.canonical_name()]
            else:
                cloud_names = enabled
            for cloud_name in cloud_names:
                if cloud_name not in enabled:
                    continue
                cloud_cls = CLOUD_REGISTRY.get(cloud_name)
                if cloud_cls is None:
                    continue
                cloud = cloud_cls()
                try:
                    feasibility = cloud.get_feasible_launchable_resources(
                        requested, task.num_nodes)
                except (ValueError, exceptions.InvalidResourcesError,
                        exceptions.InvalidTaskYAMLError):
                    continue  # request not expressible on this cloud
                for cand in feasibility.resources_list:
                    if cls._is_blocked(cand, blocked_resources):
                        continue
                    try:
                        hourly = cand.get_hourly_cost()
                    except ValueError:
                        continue
                    seconds = task.estimate_runtime(cand)
                    cost = hourly * task.num_nodes * seconds / 3600.0
                    # 'ordered' preference: higher priority wins ties by
                    # a tiny cost discount so ordering is respected among
                    # equal-cost candidates.
                    if cand.priority:
                        cost *= 1.0 - 1e-6 * cand.priority
                    out.append((cand, cost, seconds))
        return out

    @staticmethod
    def _is_blocked(candidate: resources_lib.Resources,
                    blocked: Optional[Set[resources_lib.Resources]]) -> bool:
        if not blocked:
            return False
        for b in blocked:
            if b.less_demanding_than(candidate):
                return True
        return False

    @classmethod
    def _fuzzy_candidates(cls, task: task_lib.Task) -> List[str]:
        import skypilot_tpu.clouds  # noqa: F401
        out: List[str] = []
        for requested in task.resources:
            for cloud_name in check_lib.get_cached_enabled_clouds():
                cloud_cls = CLOUD_REGISTRY.get(cloud_name)
                if cloud_cls is None:
                    continue
                if requested.cloud is not None and \
                        requested.cloud.canonical_name() != cloud_name:
                    continue
                try:
                    feasibility = \
                        cloud_cls().get_feasible_launchable_resources(
                            requested, task.num_nodes)
                except (ValueError, exceptions.InvalidResourcesError,
                        exceptions.InvalidTaskYAMLError):
                    # A request pinned to another cloud's region/pool is
                    # simply infeasible here, not an error.
                    continue
                out.extend(feasibility.fuzzy_candidate_list)
        return sorted(set(out))

    # ------------------------------------------------------------------
    @classmethod
    def _spot_effective(
        cls, task: task_lib.Task, cand: resources_lib.Resources,
        cost: float, seconds: float,
        blocked: Optional[Set[resources_lib.Resources]],
    ) -> Optional[Tuple[resources_lib.Resources, float, float]]:
        """Risk-adjust + zone-pin one spot candidate.

        Spot capacity is not fungible across zones: the catalog's
        `PreemptionRate` column says how often each zone actually
        takes the capacity back, and jobs/policy.py turns that rate
        into an effective-cost multiplier (checkpoint tax + expected
        lost progress + relaunch time, at the Young-optimal cadence).
        Walk the cloud's risk-ranked zones, skip blocked ones, and
        return the candidate PINNED to the first surviving zone with
        its cost scored on `price x multiplier` — so placement stops
        chasing list price into the stormiest zone and the launch
        actually targets the zone the score assumed. Returns None
        when every zone with the offering is blocked; non-spot (or
        rate-less) candidates pass through untouched.
        """
        # getattr guards: the solver is also exercised with abstract
        # (non-Resources) candidates in the brute-force fuzz tests.
        if not getattr(cand, 'use_spot', False) or \
                getattr(cand, 'cloud', None) is None:
            return (cand, cost, seconds)
        econ = cand.cloud.spot_zone_economics(cand)
        if not econ:
            return (cand, cost, seconds)
        from skypilot_tpu.jobs import policy
        for zone, hourly, rate in econ:
            pinned = (cand if cand.zone is not None else
                      cand.copy(zone=zone))
            if cls._is_blocked(pinned, blocked):
                continue
            eff = (hourly * policy.effective_cost_multiplier(rate) *
                   task.num_nodes * seconds / 3600.0)
            if cand.priority:
                eff *= 1.0 - 1e-6 * cand.priority
            return (pinned, eff, seconds)
        return None

    @classmethod
    def _optimize_exact(
        cls, dag: dag_lib.Dag,
        per_task: Dict[task_lib.Task,
                       List[Tuple[resources_lib.Resources, float, float]]],
        minimize: OptimizeTarget,
        blocked_resources: Optional[
            Set[resources_lib.Resources]] = None,
    ) -> Dict[task_lib.Task, Tuple[resources_lib.Resources, float]]:
        """Exact joint placement by min-sum variable elimination.

        Minimizes sum_t obj(t, x_t) + sum_(u,v in edges) egress(x_u, x_v)
        over all joint assignments. Replaces both of the reference's
        solvers — the chain DP (sky/optimizer.py:429) falls out as the
        treewidth-1 case, and general DAGs get the exact answer the
        reference needs CBC ILP for (sky/optimizer.py:490). Runtime is
        O(n * d^(w+1)) for treewidth w — microseconds for pipelines.

        Spot candidates are first risk-adjusted + zone-pinned via
        `_spot_effective` (the COST objective ranks them on
        preemption-aware effective price); `per_task` is updated IN
        PLACE so callers displaying the candidate table see the
        pinned zones and the chosen entry by identity.
        """
        for t, cands in per_task.items():
            adjusted = [
                entry for entry in
                (cls._spot_effective(t, cand, cost, seconds,
                                     blocked_resources)
                 for cand, cost, seconds in cands)
                if entry is not None
            ]
            if not adjusted:
                raise exceptions.ResourcesUnavailableError(
                    f'All zones carrying the requested spot '
                    f'resources for task {t.name or "<unnamed>"} '
                    f'are blocked.')
            per_task[t] = adjusted
        tasks = dag.get_sorted_tasks()
        tid = {t: i for i, t in enumerate(tasks)}
        use_time = minimize == OptimizeTarget.TIME
        domains = {tid[t]: len(per_task[t]) for t in tasks}

        # Factors: (scope_tuple, table) where table maps an assignment
        # tuple (aligned with scope order) -> value.
        factors = []
        for t in tasks:
            unary = {(k,): (c[2] if use_time else c[1])
                     for k, c in enumerate(per_task[t])}
            factors.append(((tid[t],), unary))
        for u, v in dag.graph.edges:
            table = {
                (ui, vi): cls._egress(ucand[0], vcand[0], v, use_time)
                for ui, ucand in enumerate(per_task[u])
                for vi, vcand in enumerate(per_task[v])
            }
            if any(table.values()):
                factors.append(((tid[u], tid[v]), table))

        # Min-degree elimination order over the moralized graph.
        import itertools
        neighbors = {i: set() for i in domains}
        for scope, _ in factors:
            for a in scope:
                neighbors[a].update(b for b in scope if b != a)
        order = []
        remaining = set(domains)
        while remaining:
            var = min(remaining, key=lambda x: len(neighbors[x] & remaining))
            order.append(var)
            live = neighbors[var] & remaining
            for a in live:       # moralize: connect var's neighbors
                neighbors[a].update(live - {a})
            remaining.remove(var)

        # Eliminate in order, recording argmins for backtracking.
        argmin_stack = []  # (var, scope_rest, {rest_assignment: best_k})
        for var in order:
            touching = [f for f in factors if var in f[0]]
            factors = [f for f in factors if var not in f[0]]
            rest = tuple(sorted({a for scope, _ in touching
                                 for a in scope if a != var}))
            new_table = {}
            arg_table = {}
            for assign in itertools.product(
                    *(range(domains[a]) for a in rest)):
                ctx = dict(zip(rest, assign))
                best_val, best_k = None, 0
                for k in range(domains[var]):
                    ctx[var] = k
                    total = 0.0
                    for scope, table in touching:
                        total += table[tuple(ctx[a] for a in scope)]
                    if best_val is None or total < best_val:
                        best_val, best_k = total, k
                new_table[assign] = best_val
                arg_table[assign] = best_k
            argmin_stack.append((var, rest, arg_table))
            if rest:
                factors.append((rest, new_table))
            # else: fully eliminated component; its min is a constant.

        # Backtrack in reverse elimination order.
        assignment: Dict[int, int] = {}
        for var, rest, arg_table in reversed(argmin_stack):
            key = tuple(assignment[a] for a in rest)
            assignment[var] = arg_table[key]

        choice: Dict[task_lib.Task,
                     Tuple[resources_lib.Resources, float]] = {}
        for t in tasks:
            cand, cost, _seconds = per_task[t][assignment[tid[t]]]
            choice[t] = (cand, cost)
        return choice

    @staticmethod
    def _egress(src: resources_lib.Resources,
                dst: resources_lib.Resources,
                task: task_lib.Task, use_time: bool) -> float:
        """Edge factor: $ (COST) or seconds (TIME) to move `task`'s
        inputs between the two placements.

        Reference: sky/optimizer.py:75-104. Zero within a cloud.
        """
        if src.cloud is None or dst.cloud is None:
            return 0.0
        if src.cloud.is_same_cloud(dst.cloud):
            return 0.0
        gigabytes = task.estimated_inputs_gigabytes or 0.0
        if use_time:
            return gigabytes * 8.0 / _EGRESS_GBPS
        return src.cloud.get_egress_cost(gigabytes)

    # ------------------------------------------------------------------
    @classmethod
    def _print_table(cls, dag, per_task, choice) -> None:
        try:
            from rich.console import Console
            from rich.table import Table
        except ImportError:
            return
        console = Console(stderr=True)
        for task in dag.get_sorted_tasks():
            table = Table(title=f'Optimizer: task '
                                f'{task.name or "<unnamed>"} '
                                f'(x{task.num_nodes} nodes)')
            for col in ('infra', 'hardware', 'spot', '$/hr', 'λ/hr',
                        'chosen'):
                table.add_column(col)
            best = choice[task][0]
            seen = set()
            rows = sorted(per_task[task], key=lambda rc: rc[1])
            for cand, *_ in rows[:8]:
                key = repr(cand)
                if key in seen:
                    continue
                seen.add(key)
                spec = cand.slice_spec
                hw = (f'{cand.tpu_accelerator_name} '
                      f'[{spec.num_hosts}h {spec.topology_str}]'
                      if spec else (cand.instance_type or '-'))
                rate = ''
                if cand.use_spot and cand.cloud is not None:
                    econ = cand.cloud.spot_zone_economics(cand)
                    if econ:
                        rate = f'{econ[0][2]:.2f}'
                table.add_row(
                    cand.infra.formatted_str(), hw,
                    'yes' if cand.use_spot else '',
                    f'{cand.get_hourly_cost() * task.num_nodes:.2f}',
                    rate,
                    '✓' if cand == best else '')
            console.print(table)


def optimize(dag: dag_lib.Dag, **kwargs) -> dag_lib.Dag:
    return Optimizer.optimize(dag, **kwargs)
