"""Optimizer: choose (cloud, region/zone, hardware) per task by cost.

Reference: sky/optimizer.py (1805 LoC) — per-task candidate enumeration
(`_fill_in_launchable_resources` asking each enabled cloud for
feasible launchable resources), chain DAGs solved by DP over
inter-task egress cost, general DAGs by ILP. This build keeps the
candidate-enumeration + chain-DP shape (no ILP dependency in the
image; general DAGs fall back to per-task greedy, which is exact when
egress is zero — the common case here since GCS-to-TPU traffic is
intra-cloud).

TPU-first: candidates for a TPU slice carry hosts/ICI topology, and
cost comparison includes per-chip spot pricing across zones.
"""
from __future__ import annotations

import collections
import enum
from typing import Dict, List, Optional, Set, Tuple

from skypilot_tpu import check as check_lib
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu.utils.registry import CLOUD_REGISTRY


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


# Assumed runtime when a task has no time estimate (1 hour), matching
# the reference's behavior of comparing hourly prices.
_DEFAULT_RUNTIME_SECONDS = 3600.0


class Optimizer:

    @classmethod
    def optimize(cls, dag: dag_lib.Dag,
                 minimize: OptimizeTarget = OptimizeTarget.COST,
                 blocked_resources: Optional[
                     Set[resources_lib.Resources]] = None,
                 quiet: bool = False) -> dag_lib.Dag:
        """Fill in task.best_resources for every task in the dag."""
        dag.validate()
        per_task = {}
        for task in dag.get_sorted_tasks():
            candidates = cls._enumerate_candidates(task, blocked_resources)
            if not candidates:
                fuzzy = cls._fuzzy_candidates(task)
                hint = (f' Try: {", ".join(fuzzy[:6])}.' if fuzzy else '')
                raise exceptions.ResourcesUnavailableError(
                    f'No launchable resources satisfy the request for task '
                    f'{task.name or "<unnamed>"}: '
                    f'{sorted(str(r) for r in task.resources)}.{hint}')
            per_task[task] = candidates

        if dag.is_chain():
            choice = cls._optimize_chain_dp(dag, per_task, minimize)
        else:
            choice = {t: min(c, key=lambda rc: rc[1])
                      for t, c in per_task.items()}

        for task, (resources, cost) in choice.items():
            task.best_resources = resources
            task.estimated_cost = cost  # type: ignore[attr-defined]
        if not quiet:
            cls._print_table(dag, per_task, choice)
        return dag

    # ------------------------------------------------------------------
    @classmethod
    def _enumerate_candidates(
        cls, task: task_lib.Task,
        blocked_resources: Optional[Set[resources_lib.Resources]],
    ) -> List[Tuple[resources_lib.Resources, float]]:
        """All launchable (resources, est_cost) pairs across enabled clouds.

        Reference: sky/optimizer.py:1671 _fill_in_launchable_resources.
        """
        import skypilot_tpu.clouds  # noqa: F401
        enabled = check_lib.get_cached_enabled_clouds()
        runtime = task.estimated_runtime or _DEFAULT_RUNTIME_SECONDS
        out: List[Tuple[resources_lib.Resources, float]] = []
        for requested in task.resources:
            if requested.cloud is not None:
                cloud_names = [requested.cloud.canonical_name()]
            else:
                cloud_names = enabled
            for cloud_name in cloud_names:
                if cloud_name not in enabled:
                    continue
                cloud_cls = CLOUD_REGISTRY.get(cloud_name)
                if cloud_cls is None:
                    continue
                cloud = cloud_cls()
                try:
                    feasibility = cloud.get_feasible_launchable_resources(
                        requested, task.num_nodes)
                except (ValueError, exceptions.InvalidResourcesError,
                        exceptions.InvalidTaskYAMLError):
                    continue  # request not expressible on this cloud
                for cand in feasibility.resources_list:
                    if cls._is_blocked(cand, blocked_resources):
                        continue
                    try:
                        hourly = cand.get_hourly_cost()
                    except ValueError:
                        continue
                    cost = hourly * task.num_nodes * runtime / 3600.0
                    # 'ordered' preference: higher priority wins ties by
                    # a tiny cost discount so ordering is respected among
                    # equal-cost candidates.
                    if cand.priority:
                        cost *= 1.0 - 1e-6 * cand.priority
                    out.append((cand, cost))
        return out

    @staticmethod
    def _is_blocked(candidate: resources_lib.Resources,
                    blocked: Optional[Set[resources_lib.Resources]]) -> bool:
        if not blocked:
            return False
        for b in blocked:
            if b.less_demanding_than(candidate):
                return True
        return False

    @classmethod
    def _fuzzy_candidates(cls, task: task_lib.Task) -> List[str]:
        import skypilot_tpu.clouds  # noqa: F401
        out: List[str] = []
        for requested in task.resources:
            for cloud_name in check_lib.get_cached_enabled_clouds():
                cloud_cls = CLOUD_REGISTRY.get(cloud_name)
                if cloud_cls is None:
                    continue
                if requested.cloud is not None and \
                        requested.cloud.canonical_name() != cloud_name:
                    continue
                try:
                    feasibility = \
                        cloud_cls().get_feasible_launchable_resources(
                            requested, task.num_nodes)
                except (ValueError, exceptions.InvalidResourcesError,
                        exceptions.InvalidTaskYAMLError):
                    # A request pinned to another cloud's region/pool is
                    # simply infeasible here, not an error.
                    continue
                out.extend(feasibility.fuzzy_candidate_list)
        return sorted(set(out))

    # ------------------------------------------------------------------
    @classmethod
    def _optimize_chain_dp(
        cls, dag: dag_lib.Dag,
        per_task: Dict[task_lib.Task,
                       List[Tuple[resources_lib.Resources, float]]],
        minimize: OptimizeTarget,
    ) -> Dict[task_lib.Task, Tuple[resources_lib.Resources, float]]:
        """DP over the chain with inter-task egress cost.

        Reference: sky/optimizer.py:429 (_optimize_by_dp).
        """
        tasks = dag.get_sorted_tasks()
        # dp[candidate_idx] = (total_cost, parent_idx)
        prev_dp: List[Tuple[float, Optional[int]]] = []
        for i, task in enumerate(tasks):
            cands = per_task[task]
            dp: List[Tuple[float, Optional[int]]] = []
            for _, (cand, cost) in enumerate(cands):
                if i == 0:
                    dp.append((cost, None))
                    continue
                best = None
                best_parent = None
                prev_cands = per_task[tasks[i - 1]]
                for pi, (pcand, _) in enumerate(prev_cands):
                    egress = cls._egress_cost(pcand, cand, task)
                    total = prev_dp[pi][0] + cost + egress
                    if best is None or total < best:
                        best, best_parent = total, pi
                dp.append((best if best is not None else cost, best_parent))
            prev_dp = dp
            per_task[task] = cands  # unchanged; clarity
            setattr(task, '_dp', dp)

        # Backtrack.
        choice: Dict[task_lib.Task,
                     Tuple[resources_lib.Resources, float]] = {}
        idx = min(range(len(prev_dp)), key=lambda j: prev_dp[j][0])
        for task in reversed(tasks):
            dp = getattr(task, '_dp')
            cand, cost = per_task[task][idx]
            choice[task] = (cand, cost)
            parent = dp[idx][1]
            delattr(task, '_dp')
            if parent is not None:
                idx = parent
        return choice

    @staticmethod
    def _egress_cost(src: resources_lib.Resources,
                     dst: resources_lib.Resources,
                     task: task_lib.Task) -> float:
        """$ to move this task's inputs between the two placements.

        Reference: sky/optimizer.py:75-104. Zero within a cloud.
        """
        if src.cloud is None or dst.cloud is None:
            return 0.0
        if src.cloud.is_same_cloud(dst.cloud):
            return 0.0
        gigabytes = getattr(task, 'estimated_inputs_gigabytes', None) or 0.0
        return src.cloud.get_egress_cost(gigabytes)

    # ------------------------------------------------------------------
    @classmethod
    def _print_table(cls, dag, per_task, choice) -> None:
        try:
            from rich.console import Console
            from rich.table import Table
        except ImportError:
            return
        console = Console(stderr=True)
        for task in dag.get_sorted_tasks():
            table = Table(title=f'Optimizer: task '
                                f'{task.name or "<unnamed>"} '
                                f'(x{task.num_nodes} nodes)')
            for col in ('infra', 'hardware', 'spot', '$/hr', 'chosen'):
                table.add_column(col)
            best = choice[task][0]
            seen = set()
            rows = sorted(per_task[task], key=lambda rc: rc[1])
            for cand, _ in rows[:8]:
                key = repr(cand)
                if key in seen:
                    continue
                seen.add(key)
                spec = cand.slice_spec
                hw = (f'{cand.tpu_accelerator_name} '
                      f'[{spec.num_hosts}h {spec.topology_str}]'
                      if spec else (cand.instance_type or '-'))
                table.add_row(
                    cand.infra.formatted_str(), hw,
                    'yes' if cand.use_spot else '',
                    f'{cand.get_hourly_cost() * task.num_nodes:.2f}',
                    '✓' if cand == best else '')
            console.print(table)


def optimize(dag: dag_lib.Dag, **kwargs) -> dag_lib.Dag:
    return Optimizer.optimize(dag, **kwargs)
