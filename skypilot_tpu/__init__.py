"""skypilot_tpu: a TPU-native sky orchestrator.

SkyPilot-equivalent capability set (see SURVEY.md), rebuilt TPU-first:
GCP TPU slices as native accelerators with ICI-topology-aware
placement, agent-mesh gang execution with JAX multi-host bootstrap
(no Ray), managed jobs with preemption recovery, and serving.

Public API mirrors the reference's `sky/__init__.py` re-exports.
"""
from skypilot_tpu import exceptions
from skypilot_tpu.dag import Dag
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task

__version__ = '0.1.0'

# Lazy server-side verbs (importing them pulls backends; keep import
# light for client-only use).


def __getattr__(name):
    import importlib
    if name in ('launch', 'exec'):
        execution = importlib.import_module('skypilot_tpu.execution')
        return getattr(execution, name)
    if name in ('status', 'start', 'stop', 'down', 'autostop', 'queue',
                'cancel', 'tail_logs', 'cost_report', 'storage_ls',
                'storage_delete'):
        core = importlib.import_module('skypilot_tpu.core')
        return getattr(core, name)
    if name == 'optimize':
        optimizer = importlib.import_module('skypilot_tpu.optimizer')
        return optimizer.optimize
    if name == 'check':
        # `sky.check` is the module (matching the reference); its main
        # entry point is `sky.check.check()`.
        return importlib.import_module('skypilot_tpu.check')
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')


__all__ = [
    'Dag', 'Resources', 'Task', 'exceptions', 'launch', 'exec', 'status',
    'start', 'stop', 'down', 'autostop', 'queue', 'cancel', 'tail_logs',
    'cost_report', 'check', 'optimize',
]
