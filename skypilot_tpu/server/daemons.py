"""API-server background maintenance daemons.

Reference: sky/server/daemons.py:1-40 — the reference runs periodic
internal request daemons (cluster-status refresh, managed-jobs status
refresh, volume refresh) with log rotation. Here a single maintenance
thread multiplexes the periodic work (one thread, monotonic next-due
bookkeeping) so the API server converges on reality even when nobody
polls:

- **cluster status reconcile** (`core.status(refresh=True)`): a
  cluster preempted/stopped/terminated behind our back flips out of
  UP in the DB without anyone calling `stpu status --refresh`.
- **controller liveness sweep**: re-runs the jobs scheduler kick and
  the serve controller reconcile normally done at server startup, so
  controllers that die mid-flight are respawned within one tick.
- **request GC**: terminal request rows + their log files are dropped
  after a retention window, bounding requests.db and the log dir.
- **stale-request requeue**: requests claimed by a replica that
  stopped heartbeating go back to PENDING for a live replica.

Multi-replica: the jobs are LEADER-ONLY, gated by an advisory lock
(Postgres pg_try_advisory_lock across hosts; flock on the single-host
sqlite deployment) — two replicas must not double-reconcile clusters
or double-GC. A non-leader keeps retrying acquisition each poll, so
leadership fails over within one tick of the leader dying (both lock
flavors release on process exit). Beats the reference's
charts/skypilot/values.yaml:22-23 "replicas > 1 is not well tested".
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from skypilot_tpu.utils import ux_utils

DEFAULT_STATUS_INTERVAL = 300.0
DEFAULT_LIVENESS_INTERVAL = 120.0
DEFAULT_GC_INTERVAL = 3600.0
DEFAULT_REQUEST_RETENTION = 3 * 24 * 3600.0
DEFAULT_STALE_REQUEUE_INTERVAL = 15.0


def _refresh_cluster_status() -> None:
    from skypilot_tpu import core
    core.status(refresh=True)


def _sweep_controllers() -> None:
    from skypilot_tpu.jobs import scheduler as jobs_scheduler
    from skypilot_tpu.serve import core as serve_core
    jobs_scheduler.maybe_schedule_next_jobs()
    serve_core.reconcile_controllers()


class ServerDaemons:
    """One maintenance thread running each periodic job on its own
    interval. Job failures are logged and never kill the thread."""

    def __init__(self,
                 status_interval: float = DEFAULT_STATUS_INTERVAL,
                 liveness_interval: float = DEFAULT_LIVENESS_INTERVAL,
                 gc_interval: float = DEFAULT_GC_INTERVAL,
                 request_retention: float = DEFAULT_REQUEST_RETENTION,
                 stale_requeue_interval: float =
                 DEFAULT_STALE_REQUEUE_INTERVAL,
                 poll: float = 1.0,
                 leader_lock=None) -> None:
        from skypilot_tpu.server.requests import executor
        self._poll = poll
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Leader election across API-server replicas: only the lock
        # holder runs the jobs. None (tests/legacy) = always leader.
        self._leader_lock = leader_lock
        # [name, interval, fn, next_due] (mutable: next_due advances).
        # First run happens one full interval after start — startup
        # already did a reconcile pass. An interval <= 0 disables that
        # job alone (the others keep running).
        now = time.monotonic()
        self._jobs: List[list] = [
            ['cluster-status-refresh', status_interval,
             _refresh_cluster_status, now + status_interval],
            ['controller-liveness', liveness_interval, _sweep_controllers,
             now + liveness_interval],
            ['request-gc', gc_interval,
             lambda: executor.gc_requests(request_retention),
             now + gc_interval],
            ['stale-request-requeue', stale_requeue_interval,
             executor.requeue_stale_requests,
             now + stale_requeue_interval],
        ]
        self._jobs = [j for j in self._jobs if j[1] > 0]

    @property
    def is_leader(self) -> bool:
        if self._leader_lock is None:
            return True
        return self._leader_lock.try_acquire()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name='server-daemons', daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def tick_all(self) -> None:
        """Run every job once, now (tests + `stpu api sweep`)."""
        for job in self._jobs:
            self._run_one(job)

    def _run_one(self, job) -> None:
        name, interval, fn = job[0], job[1], job[2]
        try:
            fn()
        except Exception as e:  # pylint: disable=broad-except
            ux_utils.log(f'daemon {name} failed: {e!r}')
        job[3] = time.monotonic() + interval

    def _loop(self) -> None:
        while not self._stop.wait(self._poll):
            try:
                leader = self.is_leader
            except Exception as e:  # pylint: disable=broad-except
                # A leadership-check failure (DB outage) must not kill
                # the maintenance thread; treat as not-leader.
                ux_utils.log(f'daemon leader check failed: {e!r}')
                leader = False
            if not leader:
                # Keep next_dues advancing so a fresh leader does not
                # immediately fire every job at once.
                now = time.monotonic()
                for job in self._jobs:
                    if now >= job[3]:
                        job[3] = now + job[1]
                continue
            now = time.monotonic()
            for job in self._jobs:
                if now >= job[3]:
                    self._run_one(job)
