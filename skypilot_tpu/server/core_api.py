"""Server-side request entrypoints: JSON payload → core functions.

Reference analog: the functions named in
`executor.schedule_request_async(..., func=execution.launch)` — here
they take JSON-serializable args (task config dicts) since payloads
cross the HTTP + process boundary.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import core
from skypilot_tpu import execution
from skypilot_tpu import task as task_lib


def launch(task_config: Dict[str, Any],
           cluster_name: Optional[str] = None,
           dryrun: bool = False,
           detach_run: bool = True,
           idle_minutes_to_autostop: Optional[int] = None,
           down: bool = False,
           retry_until_up: bool = False,
           no_setup: bool = False,
           optimize_target: str = 'cost',
           env_overrides: Optional[Dict[str, str]] = None,
           secret_overrides: Optional[Dict[str, str]] = None
           ) -> Dict[str, Any]:
    from skypilot_tpu import exceptions
    from skypilot_tpu import optimizer as optimizer_lib
    try:
        optimizer_lib.OptimizeTarget(optimize_target)
    except ValueError as e:
        raise exceptions.InvalidTaskYAMLError(
            f'optimize_target must be one of '
            f'{[t.value for t in optimizer_lib.OptimizeTarget]}; '
            f'got {optimize_target!r}.') from e
    task = task_lib.Task.from_yaml_config(task_config, env_overrides,
                                          secret_overrides)
    job_id, handle = execution.launch(
        task, cluster_name=cluster_name, dryrun=dryrun,
        detach_run=detach_run,
        idle_minutes_to_autostop=idle_minutes_to_autostop, down=down,
        retry_until_up=retry_until_up, no_setup=no_setup,
        optimize_target=optimizer_lib.OptimizeTarget(optimize_target))
    return {
        'job_id': job_id,
        'cluster_name': cluster_name,
        'handle': None if handle is None else {
            'cluster_name': handle.cluster_name,
            'num_hosts': handle.num_hosts,
            'head_agent_addr': handle.head_agent_addr,
            'resources': str(handle.launched_resources),
        },
    }


def exec(task_config: Dict[str, Any],  # pylint: disable=redefined-builtin
         cluster_name: str,
         dryrun: bool = False,
         detach_run: bool = True,
         env_overrides: Optional[Dict[str, str]] = None
         ) -> Dict[str, Any]:
    task = task_lib.Task.from_yaml_config(task_config, env_overrides)
    job_id, _ = execution.exec(task, cluster_name, dryrun=dryrun,
                               detach_run=detach_run)
    return {'job_id': job_id, 'cluster_name': cluster_name}


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False) -> List[Dict[str, Any]]:
    records = core.status(cluster_names, refresh=refresh)
    out = []
    for r in records:
        handle = r['handle']
        head_ip = None
        info = getattr(handle, 'cluster_info', None)
        if info is not None and info.instances:
            try:
                head_ip = info.get_head_instance().get_feasible_ip()
            except ValueError:
                pass
        # Ports from the launched Resources (cloud-agnostic), not the
        # deploy vars (only gcp/aws emit a 'ports' key there).
        launched = getattr(handle, 'launched_resources', None)
        ports = getattr(launched, 'ports', None) or None
        out.append({
            'name': r['name'],
            'status': r['status'].value,
            'launched_at': r['launched_at'],
            'resources_str': r['resources_str'],
            'autostop': r['autostop_minutes'],
            'autostop_down': bool(r['autostop_down']),
            'user': r.get('owner'),
            'num_hosts': getattr(handle, 'num_hosts', None),
            'head_agent_addr': getattr(handle, 'head_agent_addr', None),
            'head_ip': head_ip,
            'ports': ports,
        })
    return out


def start(cluster_name: str) -> None:
    core.start(cluster_name)


def stop(cluster_name: str) -> None:
    core.stop(cluster_name)


def down(cluster_name: str, purge: bool = False) -> None:
    core.down(cluster_name, purge=purge)


def autostop(cluster_name: str, idle_minutes: int,
             down_on_idle: bool = False) -> None:
    core.autostop(cluster_name, idle_minutes, down_on_idle)


def queue(cluster_name: str, all_jobs: bool = False) -> List[Dict[str, Any]]:
    return core.queue(cluster_name, all_jobs)


def cancel(cluster_name: str, job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> None:
    core.cancel(cluster_name, job_ids, all_jobs)


def cost_report() -> List[Dict[str, Any]]:
    return core.cost_report()


def storage_ls() -> List[str]:
    return core.storage_ls()


def storage_delete(name: str) -> None:
    core.storage_delete(name)


def check() -> List[str]:
    from skypilot_tpu import check as check_lib
    return check_lib.check(quiet=True)


def list_accelerators(name_filter: Optional[str] = None,
                      region_filter: Optional[str] = None
                      ) -> Dict[str, List[Dict[str, Any]]]:
    from skypilot_tpu.catalog import aws_catalog
    from skypilot_tpu.catalog import gcp_catalog
    result: Dict[str, List[Dict[str, Any]]] = {}
    for catalog in (gcp_catalog, aws_catalog):
        for acc, infos in catalog.list_accelerators(
                name_filter, region_filter).items():
            result.setdefault(acc, []).extend(
                i._asdict() for i in infos)
    return result
