"""Interactive web dashboard: a vanilla-JS SPA over JSON endpoints.

Reference: sky/dashboard/ (a 42k-LoC Next.js app). Same data, no build
chain: `dashboard_static/` ships index.html + app.js; the SPA polls
`/dashboard/api/summary` for live clusters/jobs/services/requests/
users tables and streams log tails through the server's existing
`/logs` and `/jobs/*/logs` endpoints.
"""
from __future__ import annotations

import asyncio
import functools
import os
from typing import Any, Dict

from aiohttp import web

_STATIC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           'dashboard_static')


def _summary() -> Dict[str, Any]:
    """Collect every table the SPA renders (runs in a worker thread)."""
    from skypilot_tpu import global_state
    from skypilot_tpu.jobs import state as jobs_state
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.server import server as server_mod
    from skypilot_tpu.server.requests import executor
    from skypilot_tpu.users import core as users_core

    clusters = []
    for r in global_state.get_clusters():
        handle = r.get('handle')
        clusters.append({
            'name': r['name'],
            'resources_str': r.get('resources_str'),
            'owner': r.get('owner'),
            'launched_at': r.get('launched_at'),
            'autostop': r.get('autostop_minutes', -1),
            'autostop_down': bool(r.get('autostop_down')),
            'status': r['status'].value,
            'num_hosts': getattr(handle, 'num_hosts', None),
            'head_agent_addr': getattr(handle, 'head_agent_addr', None),
            'events': global_state.get_cluster_events(r['name'])[-15:],
        })

    jobs = []
    for j in jobs_state.get_jobs():
        jobs.append({
            'job_id': j['job_id'],
            'name': j.get('name'),
            'job_group': j.get('job_group'),
            'stage': (f"{int(j.get('stage') or 0) + 1}"
                      f"/{len(j['task_config'])}"
                      if isinstance(j.get('task_config'), list) else None),
            'cluster_name': j.get('cluster_name'),
            'recovery_count': j.get('recovery_count', 0),
            'submitted_at': j.get('submitted_at'),
            'strategy': j.get('strategy'),
            'last_error': j.get('last_error'),
            'status': j['status'].value,
        })

    services = []
    for s in serve_state.get_services():
        replicas = serve_state.get_replicas(s['name'])
        ready = sum(1 for r in replicas if r['status'].is_serving)
        # Draining is surfaced separately from dead/shutting-down:
        # "finishing in-flight requests, out of rotation" is routine
        # scale-down, not an incident.
        draining = sum(
            1 for r in replicas
            if r['status'] == serve_state.ReplicaStatus.DRAINING)
        services.append({
            'name': s['name'],
            'version': s['version'],
            'ready': ready,
            'draining': draining,
            'total': len(replicas),
            'endpoint': (f'127.0.0.1:{s["lb_port"]}'
                         if s.get('lb_port') else None),
            'status': s['status'].value,
        })

    requests_rows = executor.list_requests(limit=50)
    users = users_core.ls()
    return {
        'server': {
            'api_version': server_mod.API_VERSION,
            'commit': os.environ.get('SKYPILOT_COMMIT', 'dev'),
        },
        'counts': {
            'clusters': len(clusters),
            'jobs': len(jobs),
            'services': len(services),
            'requests': len(requests_rows),
            'users': len(users),
        },
        'clusters': clusters,
        'jobs': jobs,
        'services': services,
        'requests': requests_rows,
        'users': users,
    }


def _cluster_detail(name: str) -> Dict[str, Any]:
    """Per-cluster drill-down: full events + the agent's job queue
    (reference: the dashboard's clusters/[cluster] page)."""
    from skypilot_tpu import global_state
    record = global_state.get_cluster(name)
    if record is None:
        return {'error': f'no cluster {name!r}'}
    handle = record['handle']
    jobs = []
    jobs_error = None
    try:
        for j in handle.agent().get_jobs():
            jobs.append({
                'job_id': j['job_id'],
                'name': j.get('name'),
                'status': j['status'].value,
                'submitted_at': j.get('submitted_at'),
                'num_ranks': j.get('num_ranks'),
            })
    except Exception as e:  # pylint: disable=broad-except
        # Distinct key, NOT a fake job row — the SPA surfaces it as a
        # banner instead of a row of dashes.
        jobs_error = str(e)
    return {
        'name': name,
        'num_hosts': getattr(handle, 'num_hosts', None),
        'events': global_state.get_cluster_events(name)[-50:],
        'jobs': jobs,
        'jobs_error': jobs_error,
    }


def _service_detail(name: str) -> Dict[str, Any]:
    """Per-service drill-down: replica table with hardware/procurement
    metadata (reference: the dashboard's serve/[service] page)."""
    from skypilot_tpu.serve import serve_state
    record = serve_state.get_service(name)
    if record is None:
        return {'error': f'no service {name!r}'}
    metas = serve_state.get_replica_meta(name)
    replicas = []
    for r in serve_state.get_replicas(name):
        meta = metas.get(r['replica_id'], {}) if isinstance(metas, dict) \
            else {}
        replicas.append({
            'replica_id': r['replica_id'],
            'version': r['version'],
            'endpoint': r.get('endpoint'),
            'status': r['status'].value,
            'use_spot': meta.get('use_spot'),
            'accelerator': meta.get('accelerator'),
            'weight': meta.get('weight'),
            'location': meta.get('location'),
        })
    return {
        'name': name,
        'version': record['version'],
        'status': record['status'].value,
        'lb_port': record.get('lb_port'),
        'controller_pid': record.get('controller_pid'),
        'replicas': replicas,
    }


async def summary(request: web.Request) -> web.Response:
    del request
    data = await asyncio.get_event_loop().run_in_executor(None, _summary)
    return web.json_response(data)


async def cluster_detail(request: web.Request) -> web.Response:
    name = request.match_info['name']
    data = await asyncio.get_event_loop().run_in_executor(
        None, _cluster_detail, name)
    return web.json_response(data, status=404 if 'error' in data else 200)


async def service_detail(request: web.Request) -> web.Response:
    name = request.match_info['name']
    data = await asyncio.get_event_loop().run_in_executor(
        None, _service_detail, name)
    return web.json_response(data, status=404 if 'error' in data else 200)


def _workspaces() -> dict:
    from skypilot_tpu.workspaces import core as ws_core
    out = {}
    for name in ws_core.get_workspaces():
        out[name] = {
            'allowed_clouds': ws_core.allowed_clouds(name),  # None=all
        }
    return {'active': ws_core.active_workspace(), 'workspaces': out}


async def workspaces(request: web.Request) -> web.Response:
    del request
    data = await asyncio.get_event_loop().run_in_executor(
        None, _workspaces)
    return web.json_response(data)


@functools.lru_cache(maxsize=None)
def _static_text(filename: str) -> str:
    """Read-once cache for the two shipped SPA files. They never
    change while the server runs, so the disk read happens on the
    first request only — and off the event loop (SKY001)."""
    with open(os.path.join(_STATIC_DIR, filename), 'r',
              encoding='utf-8') as f:
        return f.read()


async def index(request: web.Request) -> web.Response:
    del request
    text = await asyncio.get_event_loop().run_in_executor(
        None, _static_text, 'index.html')
    return web.Response(text=text, content_type='text/html')


async def app_js(request: web.Request) -> web.Response:
    del request
    text = await asyncio.get_event_loop().run_in_executor(
        None, _static_text, 'app.js')
    return web.Response(text=text,
                        content_type='application/javascript')


def register(app: web.Application) -> None:
    app.router.add_get('/dashboard', index)
    app.router.add_get('/dashboard/app.js', app_js)
    app.router.add_get('/dashboard/api/summary', summary)
    app.router.add_get('/dashboard/api/cluster/{name}', cluster_detail)
    app.router.add_get('/dashboard/api/service/{name}', service_detail)
    app.router.add_get('/dashboard/api/workspaces', workspaces)
