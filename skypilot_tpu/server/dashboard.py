"""Minimal web dashboard: one server-rendered page.

Reference: sky/dashboard/ (a 42k-LoC Next.js app). Round-1 scope is a
zero-dependency status page at `/dashboard` showing clusters, managed
jobs, services, and recent requests — the full SPA is a later-round
deliverable.
"""
from __future__ import annotations

import datetime
import html
from typing import Any, Dict, List

from aiohttp import web

_STYLE = """
body { font-family: -apple-system, system-ui, sans-serif; margin: 2rem;
       color: #1a1a1a; background: #fafafa; }
h1 { font-size: 1.4rem; } h2 { font-size: 1.05rem; margin-top: 2rem; }
table { border-collapse: collapse; width: 100%; background: white;
        box-shadow: 0 1px 2px rgba(0,0,0,.08); }
th, td { text-align: left; padding: .45rem .8rem; font-size: .85rem;
         border-bottom: 1px solid #eee; }
th { background: #f0f0f2; font-weight: 600; }
.status-UP, .status-READY, .status-RUNNING, .status-SUCCEEDED
  { color: #0a7d33; font-weight: 600; }
.status-INIT, .status-PENDING, .status-STARTING, .status-RECOVERING
  { color: #b07d00; font-weight: 600; }
.status-STOPPED { color: #666; }
.status-FAILED, .status-FAILED_SETUP, .status-FAILED_NO_RESOURCE
  { color: #c22; font-weight: 600; }
.empty { color: #999; font-style: italic; padding: .6rem; }
"""


def _table(headers: List[str], rows: List[List[str]]) -> str:
    if not rows:
        return '<div class="empty">none</div>'
    head = ''.join(f'<th>{html.escape(h)}</th>' for h in headers)
    body = ''
    for row in rows:
        cells = ''
        for cell in row:
            text = html.escape(str(cell))
            cls = (f' class="status-{text}"'
                   if text.isupper() and len(text) < 20 else '')
            cells += f'<td{cls}>{text}</td>'
        body += f'<tr>{cells}</tr>'
    return f'<table><tr>{head}</tr>{body}</table>'


def _ts(value) -> str:
    if not value:
        return '-'
    try:
        return datetime.datetime.fromtimestamp(float(value)).strftime(
            '%m-%d %H:%M')
    except (ValueError, OSError):
        return '-'


async def dashboard(request: web.Request) -> web.Response:
    del request
    from skypilot_tpu import global_state
    from skypilot_tpu.jobs import state as jobs_state
    from skypilot_tpu.serve import serve_state
    from skypilot_tpu.server.requests import executor

    clusters = [[r['name'], r['resources_str'] or '-',
                 _ts(r['launched_at']), r['status'].value]
                for r in global_state.get_clusters()]
    jobs = [[j['job_id'], j['name'] or '-', j['cluster_name'],
             j['recovery_count'], j['status'].value]
            for j in jobs_state.get_jobs()]
    services: List[List[Any]] = []
    for s in serve_state.get_services():
        replicas = serve_state.get_replicas(s['name'])
        ready = sum(1 for r in replicas if r['status'].is_serving)
        services.append([s['name'], f'v{s["version"]}',
                         f'{ready}/{len(replicas)}', s['status'].value])
    requests_rows = [[r['request_id'][:8], r['name'], r['user'] or '-',
                      _ts(r['created_at']), r['status']]
                     for r in executor.list_requests(limit=20)]

    page = f"""<!doctype html>
<html><head><title>skypilot_tpu</title><style>{_STYLE}</style>
<meta http-equiv="refresh" content="10"></head><body>
<h1>skypilot_tpu</h1>
<h2>Clusters</h2>
{_table(['name', 'resources', 'launched', 'status'], clusters)}
<h2>Managed jobs</h2>
{_table(['id', 'name', 'cluster', 'recoveries', 'status'], jobs)}
<h2>Services</h2>
{_table(['name', 'version', 'ready', 'status'], services)}
<h2>Recent requests</h2>
{_table(['id', 'name', 'user', 'created', 'status'], requests_rows)}
</body></html>"""
    return web.Response(text=page, content_type='text/html')


def register(app: web.Application) -> None:
    app.router.add_get('/dashboard', dashboard)
