"""Shared request-scheduling helper for all API route groups.

Every mutating route — core, jobs, serve, batch — funnels through
`schedule()` so that (a) the request's identity is the middleware's
server-derived `sky_user` (NOT the spoofable X-Skypilot-User header)
and (b) the RBAC policy (users/permission.py) is applied uniformly
before anything is enqueued.
"""
from __future__ import annotations

import asyncio
import threading
from typing import Callable, Iterator

from aiohttp import web

from skypilot_tpu.server.requests import executor


async def stream_lines(request: web.Request,
                       make_lines: Callable[[], Iterator[str]]
                       ) -> web.StreamResponse:
    """Stream a blocking line iterator to an HTTP response.

    Disconnect-safe: when the client goes away, the pump thread is
    signalled and its queue drained so it can never block forever on a
    full queue (a leaked thread + open fd per disconnected follower).
    """
    resp = web.StreamResponse()
    resp.content_type = 'text/plain'
    await resp.prepare(request)
    loop = asyncio.get_event_loop()
    queue: asyncio.Queue = asyncio.Queue(maxsize=1000)
    closed = threading.Event()

    def pump() -> None:
        try:
            for line in make_lines():
                if closed.is_set():
                    break
                try:
                    asyncio.run_coroutine_threadsafe(
                        queue.put(line), loop).result(timeout=60)
                except Exception:  # pylint: disable=broad-except  # stpu: ignore[SKY005] — client hung up / loop closed; break IS the handling
                    break
        finally:
            try:
                asyncio.run_coroutine_threadsafe(
                    queue.put(None), loop).result(timeout=5)
            except Exception:  # pylint: disable=broad-except  # stpu: ignore[SKY005] — sentinel put on a dead loop; consumer is gone
                pass

    threading.Thread(target=pump, daemon=True).start()
    try:
        while True:
            line = await queue.get()
            if line is None:
                break
            await resp.write(line.encode('utf-8', errors='replace'))
        await resp.write_eof()
    except (ConnectionResetError, asyncio.CancelledError):
        pass
    finally:
        closed.set()
        while not queue.empty():  # unblock a mid-put pump
            queue.get_nowait()
    return resp


async def schedule(request: web.Request, name: str, entrypoint: str,
                   schedule_type: str = 'long') -> web.Response:
    from skypilot_tpu.users import permission
    payload = await request.json() if request.can_read_body else {}
    user = request.get('sky_user', 'unknown')
    role = request.get('sky_role', 'admin')
    try:
        await asyncio.get_event_loop().run_in_executor(
            None, permission.check_request, name, payload, user, role)
    except permission.PermissionDeniedError as e:
        return web.json_response({'error': str(e)}, status=403)
    # Client-supplied id (X-Skypilot-Request-ID) dedupes retried POSTs.
    supplied = request.headers.get('X-Skypilot-Request-ID') or None
    if supplied is not None and not supplied.isalnum():
        supplied = None
    request_id = executor.schedule_request(
        name, entrypoint, payload, schedule_type=schedule_type, user=user,
        request_id=supplied)
    return web.json_response({'request_id': request_id})


def scheduled_handler(name: str, entrypoint: str,
                      schedule_type: str = 'long'):

    async def handler(request: web.Request) -> web.Response:
        return await schedule(request, name, entrypoint, schedule_type)

    return handler
