"""Shared request-scheduling helper for all API route groups.

Every mutating route — core, jobs, serve, batch — funnels through
`schedule()` so that (a) the request's identity is the middleware's
server-derived `sky_user` (NOT the spoofable X-Skypilot-User header)
and (b) the RBAC policy (users/permission.py) is applied uniformly
before anything is enqueued.
"""
from __future__ import annotations

import asyncio

from aiohttp import web

from skypilot_tpu.server.requests import executor


async def schedule(request: web.Request, name: str, entrypoint: str,
                   schedule_type: str = 'long') -> web.Response:
    from skypilot_tpu.users import permission
    payload = await request.json() if request.can_read_body else {}
    user = request.get('sky_user', 'unknown')
    role = request.get('sky_role', 'admin')
    try:
        await asyncio.get_event_loop().run_in_executor(
            None, permission.check_request, name, payload, user, role)
    except permission.PermissionDeniedError as e:
        return web.json_response({'error': str(e)}, status=403)
    # Client-supplied id (X-Skypilot-Request-ID) dedupes retried POSTs.
    supplied = request.headers.get('X-Skypilot-Request-ID') or None
    if supplied is not None and not supplied.isalnum():
        supplied = None
    request_id = executor.schedule_request(
        name, entrypoint, payload, schedule_type=schedule_type, user=user,
        request_id=supplied)
    return web.json_response({'request_id': request_id})


def scheduled_handler(name: str, entrypoint: str,
                      schedule_type: str = 'long'):

    async def handler(request: web.Request) -> web.Response:
        return await schedule(request, name, entrypoint, schedule_type)

    return handler
