"""API version negotiation between SDK and server.

Reference: sky/server/versions.py — client and server each carry an
integer API version plus the minimum they can still talk to; every
request/response carries the version header and both ends fail fast
with an actionable message instead of mis-parsing payloads.

The negotiated capability level is min(local, remote): new fields are
additive, so the older party's schema is always a subset.
"""
from __future__ import annotations

from typing import Optional, Tuple

# Bump API_VERSION on any wire-format change; raise MIN_COMPATIBLE
# only when a change cannot be expressed additively.
API_VERSION = 2
MIN_COMPATIBLE_API_VERSION = 1

HEADER = 'X-Skypilot-Api-Version'


def check_compatibility(remote_version: Optional[int],
                        remote_side: str = 'client'
                        ) -> Tuple[Optional[int], Optional[str]]:
    """(negotiated_version, error). remote_version None → legacy v1."""
    if remote_version is None:
        remote_version = 1
    try:
        remote_version = int(remote_version)
    except (TypeError, ValueError):
        return None, f'Unparseable {HEADER}: {remote_version!r}'
    if remote_version < MIN_COMPATIBLE_API_VERSION:
        upgrade = ('upgrade the client'
                   if remote_side == 'client' else 'upgrade the API server')
        return None, (
            f'{remote_side} API version {remote_version} is older than the '
            f'minimum supported {MIN_COMPATIBLE_API_VERSION}; {upgrade}.')
    return min(remote_version, API_VERSION), None
