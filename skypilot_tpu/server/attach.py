"""Interactive attach: a websocket PTY bridge to a cluster's head.

Reference: sky/server/server.py's websocket SSH tunnel
(websocket_utils) — `ssh <cluster>` rides a WS through the API server
so clients need no direct network path to the cluster. Here the
server runs the head host's interactive shell (the command runner's
`interactive_shell_argv`: `ssh -tt` for cloud hosts, a sandbox bash
for the Local cloud) under a PTY pair and bridges:

- binary WS frames  <->  raw PTY bytes (both directions)
- text WS frames carrying `{"resize": [rows, cols]}` set the PTY
  window size (TIOCSWINSZ), so curses/vim work.

The session ends when either side closes; the shell's process group
gets SIGTERM on disconnect (no orphaned shells).
"""
from __future__ import annotations

import asyncio
import fcntl
import json
import os
import signal
import struct
import subprocess
import termios
from typing import Optional

from aiohttp import WSMsgType, web


def _set_winsize(fd: int, rows: int, cols: int) -> None:
    fcntl.ioctl(fd, termios.TIOCSWINSZ,
                struct.pack('HHHH', rows, cols, 0, 0))


async def attach(request: web.Request) -> web.StreamResponse:
    from skypilot_tpu import global_state
    from skypilot_tpu.users import permission
    cluster = request.query.get('cluster', '')
    # A shell is strictly more powerful than any mutating endpoint:
    # apply the same per-cluster ownership gate (`stop` shares the
    # cluster_name-keyed rule).
    try:
        await asyncio.get_event_loop().run_in_executor(
            None, permission.check_request, 'stop',
            {'cluster_name': cluster}, request.get('sky_user', 'unknown'),
            request.get('sky_role', 'admin'))
    except permission.PermissionDeniedError as e:
        return web.json_response({'error': str(e)}, status=403)
    record = global_state.get_cluster(cluster)
    if record is None:
        return web.json_response({'error': f'no cluster {cluster!r}'},
                                 status=404)
    runners = record['handle'].get_command_runners()
    node_q = request.query.get('node', '0')
    if not node_q.isdigit():
        return web.json_response(
            {'error': f'node must be a non-negative integer, '
                      f'got {node_q!r}'}, status=400)
    node = int(node_q)
    if not node < len(runners):
        return web.json_response(
            {'error': f'node must be in [0, {len(runners)})'}, status=400)
    try:
        argv, env, cwd = runners[node].interactive_shell_argv()
    except NotImplementedError:
        return web.json_response(
            {'error': 'this cluster type has no interactive shell'},
            status=501)

    ws = web.WebSocketResponse(heartbeat=30)
    await ws.prepare(request)

    master, slave = os.openpty()
    # fork/exec can take tens of ms on a busy box — keep it off the
    # event loop (SKY001).
    proc = await asyncio.to_thread(
        subprocess.Popen, argv, stdin=slave, stdout=slave, stderr=slave,
        env=env, cwd=cwd, start_new_session=True)
    os.close(slave)
    loop = asyncio.get_event_loop()

    async def pty_to_ws() -> None:
        while True:
            try:
                data = await loop.run_in_executor(
                    None, os.read, master, 65536)
            except OSError:
                break
            if not data:
                break
            try:
                await ws.send_bytes(data)
            except ConnectionError:
                break
        if not ws.closed:
            await ws.close()

    reader = asyncio.ensure_future(pty_to_ws())
    try:
        async for msg in ws:
            if msg.type == WSMsgType.BINARY:
                try:
                    # Executor thread: a client outpacing the shell
                    # fills the small PTY buffer, and a blocking write
                    # here would wedge the whole event loop.
                    await loop.run_in_executor(None, os.write, master,
                                               msg.data)
                except OSError:
                    break
            elif msg.type == WSMsgType.TEXT:
                try:
                    body = json.loads(msg.data)
                    if not isinstance(body, dict):
                        continue
                    rows, cols = body.get('resize', (None, None))
                    if rows and cols:
                        _set_winsize(master, int(rows), int(cols))
                except (ValueError, TypeError, OSError):
                    pass
            elif msg.type in (WSMsgType.CLOSE, WSMsgType.ERROR):
                break
    finally:
        for sig in (signal.SIGTERM, signal.SIGKILL):
            try:
                os.killpg(proc.pid, sig)
            except (ProcessLookupError, PermissionError):
                break
            try:
                # Off-loop: interactive bash can ignore SIGTERM and a
                # synchronous wait would block every other request.
                await asyncio.wait_for(
                    loop.run_in_executor(None, proc.wait, 5), timeout=6)
                break
            except (asyncio.TimeoutError, subprocess.TimeoutExpired):
                continue
        # The child held the last slave fd: its exit raises EIO in the
        # reader's blocked os.read, so waiting here (instead of closing
        # `master` under it) prevents a stale thread from stealing
        # bytes off a REUSED fd number in a later session.
        try:
            await asyncio.wait_for(reader, timeout=5)
        except (asyncio.TimeoutError, asyncio.CancelledError):
            reader.cancel()
        try:
            os.close(master)
        except OSError:
            pass
    return ws


def register(app: web.Application) -> None:
    app.router.add_get('/attach', attach)


# ---------------------------------------------------------------------------
# Client side (stpu attach): terminal <-> WS pump.


def run_client(server_url: str, cluster: str, node: int = 0,
               token: Optional[str] = None) -> int:
    """Raw-mode terminal bridge; returns an exit code. aiohttp is a
    server-side dependency — if the client environment lacks it, point
    the user at ssh directly."""
    try:
        import aiohttp
    except ImportError:
        print('stpu attach needs the aiohttp package on the client '
              '(pip install aiohttp), or ssh to the host directly.')
        return 1
    import sys
    import termios as _termios
    import tty

    url = (f'{server_url.rstrip("/")}/attach'
           f'?cluster={cluster}&node={node}')
    if url.startswith('http'):
        url = 'ws' + url[len('http'):]
    headers = {'Authorization': f'Bearer {token}'} if token else {}

    async def _pump() -> int:
        stdin_fd = sys.stdin.fileno()
        loop = asyncio.get_event_loop()
        async with aiohttp.ClientSession() as session:
            async with session.ws_connect(url, headers=headers,
                                          max_msg_size=0) as ws:
                # Initial window size, then raw mode.
                try:
                    import shutil
                    size = shutil.get_terminal_size()
                    await ws.send_str(json.dumps(
                        {'resize': [size.lines, size.columns]}))
                except (OSError, ValueError):
                    pass

                async def stdin_to_ws() -> None:
                    while True:
                        data = await loop.run_in_executor(
                            None, os.read, stdin_fd, 4096)
                        if not data:
                            break
                        await ws.send_bytes(data)

                sender = asyncio.ensure_future(stdin_to_ws())
                try:
                    async for msg in ws:
                        if msg.type == WSMsgType.BINARY:
                            os.write(sys.stdout.fileno(), msg.data)
                        elif msg.type in (WSMsgType.CLOSE,
                                          WSMsgType.ERROR):
                            break
                finally:
                    sender.cancel()
        return 0

    interactive = sys.stdin.isatty()
    saved = _termios.tcgetattr(sys.stdin.fileno()) if interactive else None
    try:
        if interactive:
            tty.setraw(sys.stdin.fileno())
        return asyncio.new_event_loop().run_until_complete(_pump())
    finally:
        if saved is not None:
            _termios.tcsetattr(sys.stdin.fileno(), _termios.TCSADRAIN,
                               saved)
