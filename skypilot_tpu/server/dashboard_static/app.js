/* Dashboard SPA: live tables over /dashboard/api/summary + log tails
 * over the server's existing streaming endpoints. Vanilla JS — the
 * reference ships a 42k-LoC Next.js app; the data is the same. */
'use strict';

const TABS = ['Clusters', 'Jobs', 'Services', 'Requests', 'Users'];
let active = 'Clusters';
let data = null;
let logAbort = null;

const $ = (id) => document.getElementById(id);

/* Auth: once a service-account token is issued the server requires it
 * everywhere; the SPA keeps one in sessionStorage and prompts on 401. */
function authHeaders() {
  const t = sessionStorage.getItem('sky_token');
  return t ? { Authorization: `Bearer ${t}` } : {};
}

function promptToken() {
  const t = window.prompt(
    'This API server requires a service-account token\n' +
    '(mint one with: stpu users token issue <user>).\nToken:');
  if (t) { sessionStorage.setItem('sky_token', t.trim()); return true; }
  return false;
}

async function authFetch(url, opts) {
  let resp = await fetch(url, { ...(opts || {}), headers: authHeaders() });
  if (resp.status === 401 && promptToken()) {
    resp = await fetch(url, { ...(opts || {}), headers: authHeaders() });
  }
  return resp;
}

function statusClass(s) {
  if (!s) return 's-muted';
  if (/^(UP|READY|RUNNING|SUCCEEDED|ALIVE)$/.test(s)) return 's-ok';
  if (/^(INIT|PENDING|STARTING|RECOVERING|SUBMITTED|PROVISIONING|CANCELLING|NOT_READY)$/.test(s)) return 's-warn';
  if (/FAIL|ERROR|SHUTTING/.test(s)) return 's-bad';
  return 's-muted';
}

function ts(v) {
  if (!v) return '-';
  const d = new Date(v * 1000);
  return `${String(d.getMonth() + 1).padStart(2, '0')}-${String(d.getDate()).padStart(2, '0')} ` +
         `${String(d.getHours()).padStart(2, '0')}:${String(d.getMinutes()).padStart(2, '0')}`;
}

function table(headers, rows, onClick) {
  if (!rows.length) return '<div class="empty">none</div>';
  const head = headers.map((h) => `<th>${h}</th>`).join('');
  const body = rows.map((r, i) => {
    const cells = r.map((c) => {
      const text = String(c == null ? '-' : c);
      const cls = /^[A-Z_]{2,20}$/.test(text) ? ` class="${statusClass(text)}"` : '';
      return `<td${cls}>${text.replace(/</g, '&lt;')}</td>`;
    }).join('');
    const rowCls = onClick ? ' class="row"' : '';
    return `<tr${rowCls} data-i="${i}">${cells}</tr>`;
  }).join('');
  return `<table><tr>${head}</tr>${body}</table>`;
}

function renderTabs() {
  $('tabs').innerHTML = TABS.map((t) => {
    const n = data ? data.counts[t.toLowerCase()] : '';
    return `<button class="${t === active ? 'active' : ''}" data-tab="${t}">` +
           `${t}${n ? `<span class="pill">${n}</span>` : ''}</button>`;
  }).join('');
  document.querySelectorAll('#tabs button').forEach((b) => {
    b.onclick = () => { active = b.dataset.tab; closeDetail(); render(); };
  });
}

function render() {
  renderTabs();
  if (!data) { $('view').innerHTML = '<div class="empty">loading…</div>'; return; }
  const v = $('view');
  if (active === 'Clusters') {
    v.innerHTML = table(
      ['name', 'resources', 'owner', 'launched', 'autostop', 'status'],
      data.clusters.map((c) => [c.name, c.resources_str, c.owner, ts(c.launched_at),
                                c.autostop >= 0 ? `${c.autostop}m${c.autostop_down ? ' (down)' : ''}` : '-',
                                c.status]),
      true);
    bindRows((i) => showClusterDetail(data.clusters[i]));
  } else if (active === 'Jobs') {
    v.innerHTML = table(
      ['id', 'name', 'group', 'stage', 'cluster', 'recoveries',
       'submitted', 'status'],
      data.jobs.map((j) => [j.job_id, j.name, j.job_group, j.stage,
                            j.cluster_name, j.recovery_count,
                            ts(j.submitted_at), j.status]),
      true);
    bindRows((i) => showJobDetail(data.jobs[i]));
  } else if (active === 'Services') {
    v.innerHTML = table(
      ['name', 'version', 'replicas (ready/total)', 'endpoint', 'status'],
      data.services.map((s) => [s.name, `v${s.version}`, `${s.ready}/${s.total}`,
                                s.endpoint, s.status]));
  } else if (active === 'Requests') {
    v.innerHTML = table(
      ['id', 'name', 'user', 'created', 'status'],
      data.requests.map((r) => [r.request_id.slice(0, 8), r.name, r.user,
                                ts(r.created_at), r.status]));
  } else if (active === 'Users') {
    v.innerHTML = table(
      ['user', 'role', 'requests', 'last seen'],
      data.users.map((u) => [u.name, u.role || 'user', u.request_count,
                             ts(u.last_seen)]));
  }
}

function bindRows(fn) {
  document.querySelectorAll('#view tr.row').forEach((tr) => {
    tr.onclick = () => fn(Number(tr.dataset.i));
  });
}

function closeDetail() {
  if (logAbort) { logAbort.abort(); logAbort = null; }
  $('detail').innerHTML = '';
}

function detailShell(title, bodyHtml) {
  $('detail').innerHTML =
    `<div class="detail"><button class="close" id="dclose">✕ close</button>` +
    `<h3>${title}</h3>${bodyHtml}</div>`;
  $('dclose').onclick = closeDetail;
}

function showClusterDetail(c) {
  closeDetail();
  const events = (c.events || []).map((e) => [ts(e.timestamp), e.event_type, e.message]);
  detailShell(`Cluster ${c.name}`,
    `<div>${c.resources_str || ''} · ${c.num_hosts || '?'} host(s) · ` +
    `agent ${c.head_agent_addr || '-'}</div>` +
    `<h4>Events</h4>${table(['time', 'event', 'detail'], events)}` +
    `<h4>Latest job log</h4><pre class="logs" id="logbox">…</pre>`);
  streamLogs(`/logs?cluster=${encodeURIComponent(c.name)}&follow=0&tail=200`);
}

function showJobDetail(j) {
  closeDetail();
  detailShell(`Managed job ${j.job_id} — ${j.name || ''}`,
    `<div>cluster ${j.cluster_name} · strategy ${j.strategy || '-'} · ` +
    `recoveries ${j.recovery_count}` +
    (j.last_error ? `<div class="err">${String(j.last_error).replace(/</g, '&lt;')}</div>` : '') +
    `</div><h4>Log</h4><pre class="logs" id="logbox">…</pre>`);
  streamLogs(`/jobs/logs?job_id=${j.job_id}&follow=0`);
}

async function streamLogs(url) {
  const box = $('logbox');
  box.textContent = '';
  logAbort = new AbortController();
  try {
    const resp = await fetch(url, { signal: logAbort.signal,
                                    headers: authHeaders() });
    if (!resp.ok) { box.textContent = `(${resp.status}: no logs)`; return; }
    const reader = resp.body.getReader();
    const dec = new TextDecoder();
    for (;;) {
      const { done, value } = await reader.read();
      if (done) break;
      box.textContent += dec.decode(value, { stream: true });
      box.scrollTop = box.scrollHeight;
    }
  } catch (e) { /* aborted or stream ended */ }
}

async function refresh() {
  try {
    const resp = await authFetch('/dashboard/api/summary');
    if (!resp.ok) throw new Error(`${resp.status}`);
    data = await resp.json();
    $('meta').textContent =
      `${data.server.commit || 'dev'} · api v${data.server.api_version} · ` +
      `refreshed ${new Date().toLocaleTimeString()}`;
    render();
  } catch (e) {
    $('meta').textContent = `disconnected (${e.message})`;
  }
}

renderTabs();
render();
refresh();
setInterval(refresh, 5000);
