/* Dashboard SPA: live tables, per-entity detail pages, and actions
 * over the server's JSON API. Vanilla JS — the reference ships a
 * 42k-LoC Next.js app; the data and verbs are the same. */
'use strict';

const TABS = ['Clusters', 'Jobs', 'Services', 'Requests', 'Users',
              'Workspaces', 'Costs'];
let active = 'Clusters';
let data = null;
let tokens = null;       // /users/tokens (admin); null = not loaded
let workspaces = null;   // /dashboard/api/workspaces
let costs = null;        // /cost_report (async request round-trip)
let logAbort = null;

const $ = (id) => document.getElementById(id);
const esc = (s) => String(s == null ? '-' : s).replace(/&/g, '&amp;').replace(/</g, '&lt;');

/* Auth: once a service-account token is issued the server requires it
 * everywhere; the SPA keeps one in sessionStorage and prompts on 401. */
function authHeaders(json) {
  const t = sessionStorage.getItem('sky_token');
  const h = t ? { Authorization: `Bearer ${t}` } : {};
  if (json) h['Content-Type'] = 'application/json';
  return h;
}

function promptToken() {
  const t = window.prompt(
    'This API server requires a service-account token\n' +
    '(mint one with: stpu users token issue <user>).\nToken:');
  if (t) { sessionStorage.setItem('sky_token', t.trim()); return true; }
  return false;
}

async function authFetch(url, opts) {
  const mk = () => ({ ...(opts || {}),
                      headers: { ...authHeaders(opts && opts.body),
                                 ...((opts || {}).headers || {}) } });
  let resp = await fetch(url, mk());
  if (resp.status === 401 && promptToken()) resp = await fetch(url, mk());
  return resp;
}

/* Actions: every mutating route returns {request_id}; the result shows
 * up via the 5s refresh, so we just confirm + toast. */
async function act(label, url, payload) {
  if (!window.confirm(`${label}?`)) return;
  try {
    const resp = await authFetch(url, { method: 'POST',
                                        body: JSON.stringify(payload || {}) });
    const body = await resp.json().catch(() => ({}));
    if (!resp.ok) throw new Error(body.error || resp.status);
    toast(`${label}: submitted (${(body.request_id || '').slice(0, 8)})`);
  } catch (e) { toast(`${label} failed: ${e.message}`, true); }
  setTimeout(refresh, 800);
}

function toast(msg, bad) {
  const el = $('toast');
  el.textContent = msg;
  el.className = bad ? 'toast bad show' : 'toast show';
  clearTimeout(toast._t);
  toast._t = setTimeout(() => { el.className = 'toast'; }, 4000);
}

function statusClass(s) {
  if (!s) return 's-muted';
  if (/^(UP|READY|RUNNING|SUCCEEDED|ALIVE)$/.test(s)) return 's-ok';
  if (/^(INIT|PENDING|STARTING|RECOVERING|SUBMITTED|PROVISIONING|CANCELLING|NOT_READY)$/.test(s)) return 's-warn';
  if (/FAIL|ERROR|SHUTTING/.test(s)) return 's-bad';
  return 's-muted';
}

function ts(v) {
  if (!v) return '-';
  const d = new Date(v * 1000);
  return `${String(d.getMonth() + 1).padStart(2, '0')}-${String(d.getDate()).padStart(2, '0')} ` +
         `${String(d.getHours()).padStart(2, '0')}:${String(d.getMinutes()).padStart(2, '0')}`;
}

/* rows: array of arrays; a cell may be {html: '...'} to opt out of
 * escaping (used for action buttons only — never for server data). */
function table(headers, rows, onClick) {
  if (!rows.length) return '<div class="empty">none</div>';
  const head = headers.map((h) => `<th>${h}</th>`).join('');
  const body = rows.map((r, i) => {
    const cells = r.map((c) => {
      if (c && typeof c === 'object' && 'html' in c) return `<td class="act">${c.html}</td>`;
      const text = String(c == null ? '-' : c);
      const cls = /^[A-Z_]{2,20}$/.test(text) ? ` class="${statusClass(text)}"` : '';
      return `<td${cls}>${esc(text)}</td>`;
    }).join('');
    const rowCls = onClick ? ' class="row"' : '';
    return `<tr${rowCls} data-i="${i}">${cells}</tr>`;
  }).join('');
  return `<table><tr>${head}</tr>${body}</table>`;
}

function btn(label, cls, id) {
  return `<button class="abtn ${cls || ''}" data-act="${id}">${label}</button>`;
}

/* After innerHTML, wire data-act buttons to handlers by id. */
function bindActs(handlers) {
  document.querySelectorAll('[data-act]').forEach((b) => {
    const h = handlers[b.dataset.act];
    if (h) b.onclick = (ev) => { ev.stopPropagation(); h(); };
  });
}

function renderTabs() {
  $('tabs').innerHTML = TABS.map((t) => {
    const n = data ? data.counts[t.toLowerCase()] : '';
    return `<button class="${t === active ? 'active' : ''}" data-tab="${t}">` +
           `${t}${n ? `<span class="pill">${n}</span>` : ''}</button>`;
  }).join('');
  document.querySelectorAll('#tabs button').forEach((b) => {
    b.onclick = () => { active = b.dataset.tab; closeDetail(); render(); };
  });
}

function render() {
  renderTabs();
  if (!data) { $('view').innerHTML = '<div class="empty">loading…</div>'; return; }
  const v = $('view');
  const acts = {};
  if (active === 'Clusters') {
    v.innerHTML = table(
      ['name', 'resources', 'owner', 'launched', 'autostop', 'status', ''],
      data.clusters.map((c, i) => {
        acts[`stop${i}`] = () => act(`Stop cluster ${c.name}`, '/stop',
                                     { cluster_name: c.name });
        acts[`down${i}`] = () => act(`Down (terminate) cluster ${c.name}`,
                                     '/down', { cluster_name: c.name });
        return [c.name, c.resources_str, c.owner, ts(c.launched_at),
                c.autostop >= 0 ? `${c.autostop}m${c.autostop_down ? ' (down)' : ''}` : '-',
                c.status,
                { html: btn('stop', '', `stop${i}`) + btn('down', 'danger', `down${i}`) }];
      }),
      true);
    bindRows((i) => showClusterDetail(data.clusters[i]));
  } else if (active === 'Jobs') {
    v.innerHTML = table(
      ['id', 'name', 'group', 'stage', 'cluster', 'recoveries',
       'submitted', 'status', ''],
      data.jobs.map((j, i) => {
        const live = !/SUCCEEDED|FAILED|CANCELLED/.test(j.status);
        acts[`jcancel${i}`] = () => act(`Cancel managed job ${j.job_id}`,
                                        '/jobs/cancel', { job_ids: [j.job_id] });
        return [j.job_id, j.name, j.job_group, j.stage, j.cluster_name,
                j.recovery_count, ts(j.submitted_at), j.status,
                { html: live ? btn('cancel', 'danger', `jcancel${i}`) : '' }];
      }),
      true);
    bindRows((i) => showJobDetail(data.jobs[i]));
  } else if (active === 'Services') {
    v.innerHTML = table(
      ['name', 'version', 'replicas (ready/total)', 'endpoint', 'status', ''],
      data.services.map((s, i) => {
        acts[`sdown${i}`] = () => act(`Tear down service ${s.name}`,
                                      '/serve/down', { service_name: s.name });
        return [s.name, `v${s.version}`, `${s.ready}/${s.total}`,
                s.endpoint, s.status,
                { html: btn('down', 'danger', `sdown${i}`) }];
      }),
      true);
    bindRows((i) => showServiceDetail(data.services[i].name));
  } else if (active === 'Requests') {
    v.innerHTML = table(
      ['id', 'name', 'user', 'created', 'status', ''],
      data.requests.map((r, i) => {
        const live = /PENDING|RUNNING/.test(r.status);
        acts[`rcancel${i}`] = () => act(`Cancel request ${r.request_id.slice(0, 8)}`,
                                        '/api/cancel', { request_id: r.request_id });
        return [r.request_id.slice(0, 8), r.name, r.user, ts(r.created_at),
                r.status, { html: live ? btn('cancel', 'danger', `rcancel${i}`) : '' }];
      }));
  } else if (active === 'Users') {
    renderUsers(v, acts);
  } else if (active === 'Workspaces') {
    renderWorkspaces(v);
  } else if (active === 'Costs') {
    renderCosts(v);
  }
  bindActs(acts);
}

/* Cost report: terminated-cluster history with accrued cost (the
 * CLI's `stpu cost-report`). The verb is an async request: POST
 * /cost_report -> request_id -> poll /api/get for the result. */
function renderCosts(v) {
  if (costs === null) {
    v.innerHTML = '<div class="empty">loading…</div>';
    loadCosts();
    return;
  }
  if (costs.error) {
    v.innerHTML = `<div class="err">${esc(costs.error)}</div>`;
    return;
  }
  const total = costs.reduce((s, r) => s + (r.cost || 0), 0);
  v.innerHTML =
    `<div class="empty">lifetime total: $${total.toFixed(2)}</div>` +
    table(
      ['cluster', 'resources', 'nodes', 'user', 'launched',
       'duration', 'cost', 'final status'],
      costs.map((r) => [
        r.name, r.resources_str, r.num_nodes, r.user,
        ts(r.launched_at),
        r.duration ? `${Math.round(r.duration / 60)}m` : '-',
        r.cost != null ? `$${r.cost.toFixed(2)}` : '-',
        r.last_status]));
}

async function loadCosts() {
  try {
    const sub = await authFetch('/cost_report',
                                { method: 'POST', body: '{}' });
    const body = await sub.json();
    if (!sub.ok) throw new Error(body.error || sub.status);
    const rid = body.request_id;
    for (let i = 0; i < 30; i += 1) {
      const resp = await authFetch(
        `/api/get?request_id=${rid}&timeout=2`);
      const rec = await resp.json();
      if (!resp.ok) throw new Error(rec.error || resp.status);
      if (rec.status === 'SUCCEEDED') {
        costs = rec.return_value || [];
        break;
      }
      if (rec.status === 'FAILED' || rec.status === 'CANCELLED') {
        throw new Error(`cost report ${rec.status}`);
      }
    }
    if (costs === null) throw new Error('timed out');
  } catch (e) { costs = { error: `cost report: ${e.message}` }; }
  if (active === 'Costs') render();
}

/* Users admin: set role, issue service-account tokens, revoke them —
 * the management surface behind `stpu users ...` (admin-only routes;
 * non-admin tokens get a 403 toast). */
function renderUsers(v, acts) {
  const userRows = data.users.map((u, i) => {
    acts[`role${i}`] = () => {
      const sel = $(`rolesel${i}`);
      act(`Set ${u.name} role to ${sel.value}`, '/users/role',
          { user: u.name, role: sel.value });
    };
    acts[`tok${i}`] = () => issueToken(u.name, u.role || 'user');
    const roleSel =
      `<select id="rolesel${i}">` +
      ['user', 'admin'].map((r) =>
        `<option${r === (u.role || 'user') ? ' selected' : ''}>${r}</option>`)
        .join('') + '</select>';
    return [u.name, { html: roleSel + btn('set role', '', `role${i}`) },
            u.request_count, ts(u.last_seen),
            { html: btn('issue token', '', `tok${i}`) }];
  });
  let html = '<h4>Users</h4>' +
    table(['user', 'role', 'requests', 'last seen', ''], userRows);
  html += '<h4>Service-account tokens</h4>';
  if (tokens === null) {
    html += '<div class="empty">loading…</div>';
    loadTokens();
  } else if (tokens.error) {
    html += `<div class="err">${esc(tokens.error)}</div>`;
  } else {
    const tokRows = tokens.map((t, i) => {
      acts[`trev${i}`] = () => {
        act(`Revoke token ${t.token_id}`, '/users/tokens/revoke',
            { token_id: t.token_id });
        tokens = null;  // reload after the revoke lands
      };
      return [t.token_id, t.user_hash, ts(t.created_at),
              ts(t.last_used_at), t.revoked ? 'REVOKED' : 'active',
              { html: t.revoked ? '' : btn('revoke', 'danger', `trev${i}`) }];
    });
    html += table(['id', 'user', 'created', 'last used', 'state', ''],
                  tokRows);
  }
  v.innerHTML = html;
}

async function loadTokens() {
  try {
    const resp = await authFetch('/users/tokens');
    const body = await resp.json();
    if (!resp.ok) throw new Error(body.error || resp.status);
    tokens = body.tokens || [];
  } catch (e) { tokens = { error: `tokens: ${e.message}` }; }
  if (active === 'Users') render();
}

async function issueToken(user, role) {
  if (!window.confirm(`Issue a ${role} token for ${user}?`)) return;
  try {
    const resp = await authFetch('/users/tokens', {
      method: 'POST', body: JSON.stringify({ user, role }) });
    const body = await resp.json();
    if (!resp.ok) throw new Error(body.error || resp.status);
    /* The secret is shown ONCE (the server stores only its hash) —
     * same contract as `stpu users token issue`. */
    window.prompt(`Token for ${user} — copy it now (not shown again):`,
                  body.token);
    tokens = null;  // reload the token list
    render();
  } catch (e) { toast(`issue token failed: ${e.message}`, true); }
}

/* Workspaces: registry + per-workspace cloud allow-list (config-
 * driven; edited via the server's config YAML, viewable here). */
function renderWorkspaces(v) {
  if (workspaces === null) {
    v.innerHTML = '<div class="empty">loading…</div>';
    loadWorkspaces();
    return;
  }
  if (workspaces.error) {
    v.innerHTML = `<div class="err">${esc(workspaces.error)}</div>`;
    return;
  }
  const names = Object.keys(workspaces.workspaces || {});
  v.innerHTML = table(
    ['workspace', 'allowed clouds', 'active'],
    names.map((n) => {
      const ws = workspaces.workspaces[n];
      const clouds = ws.allowed_clouds === null ? 'all clouds'
        : (ws.allowed_clouds || []).join(', ') || 'none';
      return [n, clouds, n === workspaces.active ? '✓' : ''];
    }));
}

async function loadWorkspaces() {
  try {
    const resp = await authFetch('/dashboard/api/workspaces');
    const body = await resp.json();
    if (!resp.ok) throw new Error(body.error || resp.status);
    workspaces = body;
  } catch (e) { workspaces = { error: `workspaces: ${e.message}` }; }
  if (active === 'Workspaces') render();
}

function bindRows(fn) {
  document.querySelectorAll('#view tr.row').forEach((tr) => {
    tr.onclick = () => fn(Number(tr.dataset.i));
  });
}

function closeDetail() {
  if (logAbort) { logAbort.abort(); logAbort = null; }
  $('detail').innerHTML = '';
}

function detailShell(title, bodyHtml) {
  $('detail').innerHTML =
    `<div class="detail"><button class="close" id="dclose">✕ close</button>` +
    `<h3>${esc(title)}</h3>${bodyHtml}</div>`;
  $('dclose').onclick = closeDetail;
}

async function showClusterDetail(c) {
  closeDetail();
  let detail = { events: c.events || [], jobs: [], num_hosts: c.num_hosts };
  try {
    const resp = await authFetch(`/dashboard/api/cluster/${encodeURIComponent(c.name)}`);
    if (resp.ok) detail = await resp.json();
  } catch (e) { /* fall back to summary data */ }
  const events = (detail.events || []).map((e) => [ts(e.timestamp), e.event_type, e.message]);
  const jobs = (detail.jobs || []).map((j) => [j.job_id, j.name, j.status, ts(j.submitted_at)]);
  const nHosts = detail.num_hosts || c.num_hosts || 1;
  const rankOpts = ['<option value="">combined</option>'];
  for (let r = 0; r < nHosts; r += 1) rankOpts.push(`<option value="${r}">rank ${r}</option>`);
  detailShell(`Cluster ${c.name}`,
    `<div>${esc(c.resources_str || '')} · ${nHosts} host(s) · ` +
    `agent ${esc(c.head_agent_addr || '-')}</div>` +
    `<h4>Jobs on cluster</h4>` +
    (detail.jobs_error ? `<div class="err">agent unreachable: ${esc(detail.jobs_error)}</div>` : '') +
    `${table(['id', 'name', 'status', 'submitted'], jobs)}` +
    `<h4>Events</h4>${table(['time', 'event', 'detail'], events)}` +
    `<h4>Log <select id="rank">${rankOpts.join('')}</select></h4>` +
    `<pre class="logs" id="logbox">…</pre>`);
  const load = () => {
    const rank = $('rank').value;
    streamLogs(`/logs?cluster=${encodeURIComponent(c.name)}&follow=0&tail=200` +
               (rank === '' ? '' : `&rank=${rank}`));
  };
  $('rank').onchange = load;
  load();
}

async function showJobDetail(j) {
  closeDetail();
  /* Per-rank logs: the job's cluster knows its host count; rank N
   * streams that host's file via the cluster log endpoint (the
   * controller view stays the default — recovery context lives
   * there). */
  let nHosts = 0;
  if (j.cluster_name) {
    try {
      const resp = await authFetch(
        `/dashboard/api/cluster/${encodeURIComponent(j.cluster_name)}`);
      if (resp.ok) nHosts = (await resp.json()).num_hosts || 0;
    } catch (e) { /* cluster may be torn down between recoveries */ }
  }
  const srcOpts = ['<option value="">controller</option>'];
  for (let r = 0; r < nHosts; r += 1) {
    srcOpts.push(`<option value="${r}">rank ${r}</option>`);
  }
  detailShell(`Managed job ${j.job_id} — ${j.name || ''}`,
    `<div>cluster ${esc(j.cluster_name)} · strategy ${esc(j.strategy || '-')} · ` +
    `recoveries ${j.recovery_count}` +
    (j.last_error ? `<div class="err">${esc(j.last_error)}</div>` : '') +
    `</div><h4>Log <select id="jsrc">${srcOpts.join('')}</select></h4>` +
    `<pre class="logs" id="logbox">…</pre>`);
  const load = () => {
    const src = $('jsrc').value;
    if (src === '') {
      streamLogs(`/jobs/logs?job_id=${j.job_id}&follow=0`);
    } else {
      streamLogs(`/logs?cluster=${encodeURIComponent(j.cluster_name)}` +
                 `&follow=0&tail=200&rank=${src}`);
    }
  };
  $('jsrc').onchange = load;
  load();
}

async function showServiceDetail(name) {
  closeDetail();
  let d = null;
  try {
    const resp = await authFetch(`/dashboard/api/service/${encodeURIComponent(name)}`);
    d = await resp.json();
    if (!resp.ok) throw new Error(d.error || resp.status);
  } catch (e) { toast(`service detail: ${e.message}`, true); return; }
  const reps = (d.replicas || []).map((r) => [
    r.replica_id, `v${r.version}`, r.endpoint,
    r.use_spot == null ? '-' : (r.use_spot ? 'spot' : 'on-demand'),
    r.accelerator, r.weight, r.status]);
  detailShell(`Service ${d.name}`,
    `<div>v${d.version} · ${esc(d.status)} · LB :${d.lb_port || '-'} · ` +
    `controller pid ${d.controller_pid || '-'}</div>` +
    `<h4>Replicas</h4>` +
    table(['id', 'version', 'endpoint', 'procurement', 'accelerator',
           'weight', 'status'], reps) +
    `<h4>Controller log</h4><pre class="logs" id="logbox">…</pre>`);
  streamLogs(`/serve/logs?service=${encodeURIComponent(name)}&follow=0`);
}

async function streamLogs(url) {
  if (logAbort) logAbort.abort();
  const box = $('logbox');
  box.textContent = '';
  logAbort = new AbortController();
  try {
    const resp = await fetch(url, { signal: logAbort.signal,
                                    headers: authHeaders() });
    if (!resp.ok) { box.textContent = `(${resp.status}: no logs)`; return; }
    const reader = resp.body.getReader();
    const dec = new TextDecoder();
    for (;;) {
      const { done, value } = await reader.read();
      if (done) break;
      box.textContent += dec.decode(value, { stream: true });
      box.scrollTop = box.scrollHeight;
    }
  } catch (e) { /* aborted or stream ended */ }
}

async function refresh() {
  try {
    const resp = await authFetch('/dashboard/api/summary');
    if (!resp.ok) throw new Error(`${resp.status}`);
    data = await resp.json();
    $('meta').textContent =
      `${data.server.commit || 'dev'} · api v${data.server.api_version} · ` +
      `refreshed ${new Date().toLocaleTimeString()}`;
    render();
  } catch (e) {
    $('meta').textContent = `disconnected (${e.message})`;
  }
}

renderTabs();
render();
refresh();
setInterval(refresh, 5000);
