"""Request DB + process executor for the API server.

Reference: sky/server/requests/executor.py (1208 LoC) — requests
persisted in a DB, LONG/SHORT queues, a process pool of disposable
workers, per-request log files, env/config isolation, kill-on-cancel.

This build: every request is one forked process (cancellation = kill
process group; memory returned to the OS when it exits — the
reference's BurstableExecutor "disposable worker" behavior), with a
semaphore per queue bounding concurrency.
"""
from __future__ import annotations

import enum
import functools
import importlib
import json
import multiprocessing
import os
import pickle
import signal
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import constants
from skypilot_tpu import exceptions
from skypilot_tpu.utils import db_utils
from skypilot_tpu.utils import subprocess_utils

_CREATE_SQL = """\
CREATE TABLE IF NOT EXISTS requests (
    request_id TEXT PRIMARY KEY,
    name TEXT,
    entrypoint TEXT,
    payload TEXT,
    status TEXT,
    created_at REAL,
    started_at REAL,
    finished_at REAL,
    pid INTEGER DEFAULT -1,
    return_value BLOB,
    error TEXT,
    log_path TEXT,
    user TEXT,
    schedule_type TEXT
);
"""

# queue name -> max concurrent request processes
_CONCURRENCY = {'long': 4, 'short': 16}


class RequestStatus(enum.Enum):
    PENDING = 'PENDING'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in (RequestStatus.SUCCEEDED, RequestStatus.FAILED,
                        RequestStatus.CANCELLED)


@functools.lru_cache(maxsize=None)
def _db_for(path: str) -> db_utils.SQLiteDB:
    return db_utils.open_db(path, _CREATE_SQL)


def _db() -> db_utils.SQLiteDB:
    return _db_for(os.path.join(constants.api_server_dir(), 'requests.db'))


def _log_path(request_id: str) -> str:
    d = os.path.join(constants.api_server_dir(), 'requests')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'{request_id}.log')


# ---------------------------------------------------------------------------
# Submission
# ---------------------------------------------------------------------------
def schedule_request(name: str, entrypoint: str, payload: Dict[str, Any],
                     schedule_type: str = 'long',
                     user: str = 'unknown',
                     request_id: Optional[str] = None) -> str:
    """Persist a request; the scheduler thread picks it up.

    A client-supplied `request_id` makes scheduling idempotent: a
    retried POST (lost response over a flaky network) re-inserts
    nothing and returns the same id, so network-level retries can
    never double-run a launch.
    """
    request_id = request_id or uuid.uuid4().hex[:16]
    _db().execute(
        'INSERT OR IGNORE INTO requests (request_id, name, entrypoint, '
        'payload, status, created_at, log_path, user, schedule_type) '
        'VALUES (?,?,?,?,?,?,?,?,?)',
        (request_id, name, entrypoint, json.dumps(payload),
         RequestStatus.PENDING.value, time.time(), _log_path(request_id),
         user, schedule_type))
    return request_id


def get_request(request_id: str) -> Optional[Dict[str, Any]]:
    row = _db().query_one('SELECT * FROM requests WHERE request_id=?',
                          (request_id,))
    if row is None:
        return None
    out = dict(row)
    out['status'] = RequestStatus(out['status'])
    out['payload'] = json.loads(out['payload']) if out['payload'] else {}
    if out.get('return_value') is not None:
        out['return_value'] = pickle.loads(out['return_value'])
    if out.get('error'):
        out['error'] = json.loads(out['error'])
    return out


def list_requests(limit: int = 100) -> List[Dict[str, Any]]:
    rows = _db().query(
        'SELECT request_id, name, status, created_at, finished_at, user '
        'FROM requests ORDER BY created_at DESC LIMIT ?', (limit,))
    return rows


def cancel_request(request_id: str) -> bool:
    row = _db().query_one('SELECT pid, status FROM requests '
                          'WHERE request_id=?', (request_id,))
    if row is None:
        raise exceptions.RequestNotFoundError(request_id)
    status = RequestStatus(row['status'])
    if status.is_terminal():
        return False
    _set_status(request_id, RequestStatus.CANCELLED)
    if row['pid'] and row['pid'] > 0:
        subprocess_utils.kill_process_tree(row['pid'])
    return True


def gc_requests(retention_seconds: float) -> int:
    """Drop terminal requests that finished more than
    `retention_seconds` ago, along with their log files; returns how
    many rows were removed. Reference: sky/server/daemons.py's
    request-log maintenance; bounds requests.db + the log dir on a
    long-lived server."""
    cutoff = time.time() - retention_seconds
    terminal = tuple(s.value for s in RequestStatus if s.is_terminal())
    marks = ','.join('?' * len(terminal))
    rows = _db().query(
        f'SELECT request_id, log_path FROM requests '
        f'WHERE status IN ({marks}) AND finished_at IS NOT NULL '
        f'AND finished_at < ?', terminal + (cutoff,))
    for row in rows:
        if row.get('log_path'):
            try:
                os.unlink(row['log_path'])
            except OSError:
                pass
        _db().execute('DELETE FROM requests WHERE request_id=?',
                      (row['request_id'],))
    return len(rows)


def _set_status(request_id: str, status: RequestStatus,
                **extra: Any) -> None:
    sets = ['status=?']
    params: List[Any] = [status.value]
    for k, v in extra.items():
        sets.append(f'{k}=?')
        params.append(v)
    if status == RequestStatus.RUNNING:
        sets.append('started_at=?')
        params.append(time.time())
    if status.is_terminal():
        sets.append('finished_at=?')
        params.append(time.time())
    params.append(request_id)
    _db().execute(f'UPDATE requests SET {", ".join(sets)} '
                  'WHERE request_id=?', tuple(params))


# ---------------------------------------------------------------------------
# Execution (worker process)
# ---------------------------------------------------------------------------
def _resolve_entrypoint(entrypoint: str) -> Callable:
    module_name, fn_name = entrypoint.rsplit('.', 1)
    module = importlib.import_module(module_name)
    return getattr(module, fn_name)


def _request_worker_main(request_id: str, entrypoint: str,
                         payload_json: str, log_path: str,
                         db_path: str, user: str = 'unknown') -> None:
    """Runs in the forked worker process (reference:
    _request_execution_wrapper, executor.py:670)."""
    os.setpgrp()  # own process group: cancel kills the whole tree
    # The fork inherits aiohttp's asyncio signal handlers, which are
    # no-ops without the parent's event loop — a worker would silently
    # IGNORE SIGTERM (cancel, chaos kill). Restore default dispositions.
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    signal.signal(signal.SIGINT, signal.SIG_DFL)
    db = _db_for(db_path)
    import sys
    log_file = open(log_path, 'ab', buffering=0)
    os.dup2(log_file.fileno(), sys.stdout.fileno())
    os.dup2(log_file.fileno(), sys.stderr.fileno())
    from skypilot_tpu.utils import request_context
    request_context.set_request_user(user)
    try:
        fn = _resolve_entrypoint(entrypoint)
        payload = json.loads(payload_json)
        result = fn(**payload)
        db.execute(
            'UPDATE requests SET status=?, return_value=?, finished_at=? '
            'WHERE request_id=?',
            (RequestStatus.SUCCEEDED.value, pickle.dumps(result),
             time.time(), request_id))
    except BaseException as e:  # pylint: disable=broad-except
        traceback.print_exc()
        db.execute(
            'UPDATE requests SET status=?, error=?, finished_at=? '
            'WHERE request_id=?',
            (RequestStatus.FAILED.value,
             json.dumps(exceptions.serialize_exception(e)), time.time(),
             request_id))


class RequestWorkerLoop:
    """Scheduler thread: spawns worker processes for pending requests."""

    def __init__(self) -> None:
        self._running: Dict[str, multiprocessing.Process] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        # Recover orphaned requests from a previous server run.
        for row in _db().query(
                'SELECT request_id, pid, status FROM requests WHERE '
                'status IN (?, ?)', (RequestStatus.RUNNING.value,
                                     RequestStatus.PENDING.value)):
            if RequestStatus(row['status']) == RequestStatus.RUNNING and \
                    not subprocess_utils.process_alive(row['pid']):
                _set_status(row['request_id'], RequestStatus.FAILED,
                            error=json.dumps({
                                'type': 'ApiRequestError',
                                'message': 'server restarted mid-request',
                            }))
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._step()
            except Exception:  # pylint: disable=broad-except
                traceback.print_exc()
            time.sleep(0.2)

    def _step(self) -> None:
        # Reap finished processes.
        for rid, proc in list(self._running.items()):
            if not proc.is_alive():
                proc.join()
                row = _db().query_one(
                    'SELECT status FROM requests WHERE request_id=?', (rid,))
                if row and not RequestStatus(row['status']).is_terminal():
                    # Worker died without recording a result.
                    _set_status(rid, RequestStatus.FAILED, error=json.dumps({
                        'type': 'ApiRequestError',
                        'message': f'worker exited rc={proc.exitcode} '
                                   'without result',
                    }))
                del self._running[rid]

        # Count running per queue.
        counts: Dict[str, int] = {'long': 0, 'short': 0}
        rows = _db().query(
            'SELECT request_id, schedule_type FROM requests WHERE status=?',
            (RequestStatus.RUNNING.value,))
        for r in rows:
            counts[r['schedule_type'] or 'long'] = counts.get(
                r['schedule_type'] or 'long', 0) + 1

        pending = _db().query(
            'SELECT * FROM requests WHERE status=? ORDER BY created_at',
            (RequestStatus.PENDING.value,))
        for req in pending:
            queue = req['schedule_type'] or 'long'
            if counts.get(queue, 0) >= _CONCURRENCY.get(queue, 4):
                continue
            self._spawn(req)
            counts[queue] = counts.get(queue, 0) + 1

    def _spawn(self, req: Dict[str, Any]) -> None:
        ctx = multiprocessing.get_context('fork')
        # daemon=True: workers die with the server (in-flight requests
        # are marked FAILED on restart by start()'s recovery scan);
        # workers only spawn subprocess.Popen children, which daemonic
        # processes are allowed to do.
        proc = ctx.Process(
            target=_request_worker_main,
            args=(req['request_id'], req['entrypoint'], req['payload'],
                  req['log_path'],
                  os.path.join(constants.api_server_dir(), 'requests.db'),
                  req['user'] or 'unknown'),
            daemon=True)
        proc.start()
        _set_status(req['request_id'], RequestStatus.RUNNING, pid=proc.pid)
        self._running[req['request_id']] = proc
